"""Native (C++) runtime components, built on demand with the system
toolchain and loaded via ctypes.

``lib()`` returns the loaded library or ``None`` (no g++ / build
failure) — callers keep their pure-Python path as the fallback, so the
native layer is an accelerator, never a dependency.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import List, Optional, Tuple

LOG = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "framing.cpp")
_SO = os.path.join(_DIR, "_libatpu_native.so")

_lock = threading.Lock()
_lib: "ctypes.CDLL | None | bool" = None  # None=untried, False=failed


def _build() -> Optional[str]:
    """Compile the shared library when missing or stale."""
    try:
        if os.path.exists(_SO) and \
                os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return _SO
        # build into a temp file then rename: concurrent processes
        # (minicluster roles) must never dlopen a half-written .so
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               "-o", tmp, _SRC]
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0:
            LOG.warning("native build failed: %s", r.stderr.decode()[:500])
            os.unlink(tmp)
            return None
        os.replace(tmp, _SO)
        return _SO
    except Exception:  # noqa: BLE001 - no toolchain: python fallback
        LOG.debug("native build unavailable", exc_info=True)
        return None


def lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib or None
    with _lock:
        if _lib is not None:
            return _lib or None
        so = _build()
        if so is None:
            _lib = False
            return None
        try:
            handle = ctypes.CDLL(so)
        except OSError:
            _lib = False
            return None
        handle.atpu_crc32.restype = ctypes.c_uint32
        handle.atpu_crc32.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                      ctypes.c_uint32]
        handle.atpu_scan_frames.restype = ctypes.c_size_t
        handle.atpu_scan_frames.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64)]
        handle.atpu_prefault.restype = ctypes.c_uint64
        handle.atpu_prefault.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                         ctypes.c_size_t]
        _lib = handle
        return handle


def _buffer_address(view) -> "Tuple[int, int, object] | None":
    """(address, nbytes, keepalive) of a buffer WITHOUT copying,
    readonly or not — hold ``keepalive`` for the duration of the native
    call. None when no zero-copy address is obtainable."""
    # numpy arrays expose the address directly regardless of flags
    data_attr = getattr(view, "ctypes", None)
    if data_attr is not None and hasattr(data_attr, "data"):
        return data_attr.data, view.nbytes, view
    if isinstance(view, bytes):
        # ctypes.cast of a bytes object points at its internal buffer
        return (ctypes.cast(view, ctypes.c_void_p).value or 0,
                len(view), view)
    mv = memoryview(view)
    if not mv.readonly:
        buf = (ctypes.c_char * mv.nbytes).from_buffer(mv)
        return ctypes.addressof(buf), mv.nbytes, buf
    try:
        # readonly memoryview/mmap: a numpy view exposes the address
        # without requiring writability (native code only reads)
        import numpy as np

        arr = np.frombuffer(mv, dtype=np.uint8)
        return arr.ctypes.data, arr.nbytes, (arr, mv)
    except Exception:  # noqa: BLE001
        return None


_SCAN_CHUNK = 65536  # frames per native call: bounds the offset arrays


def scan_frames(view) -> "Tuple[List[Tuple[int, int]], int] | None":
    """Scan ``[u32 len][u32 crc][body]`` frames over a buffer
    (bytes/bytearray/ndarray/mmap) with NO copy of the data. Returns
    ``([(body_off, body_len), ...], end_off)`` — ``end_off`` is the
    truncation point after the last valid frame — or ``None`` when the
    native library (or a zero-copy address) is unavailable. The scan
    runs in bounded chunks so offset arrays stay small regardless of
    journal size."""
    handle = lib()
    if handle is None:
        return None
    loc = _buffer_address(view)
    if loc is None:
        return None
    addr, n, keepalive = loc
    if n == 0:
        return [], 0
    offs = (ctypes.c_uint64 * _SCAN_CHUNK)()
    lens = (ctypes.c_uint32 * _SCAN_CHUNK)()
    end = ctypes.c_uint64(0)
    frames: List[Tuple[int, int]] = []
    start = 0
    while True:
        got = handle.atpu_scan_frames(addr, n, start, offs, lens,
                                      _SCAN_CHUNK, ctypes.byref(end))
        frames.extend((offs[i], lens[i]) for i in range(got))
        start = end.value
        if got < _SCAN_CHUNK:
            break
    del keepalive
    return frames, end.value


def crc32(data: bytes, seed: int = 0) -> Optional[int]:
    handle = lib()
    if handle is None:
        return None
    return handle.atpu_crc32(data, len(data), seed)


def prefault(view, stride: int = 4096) -> bool:
    """Touch one byte per page, GIL-free, readonly-safe and zero-copy.
    True when the native path ran (False -> caller falls back)."""
    handle = lib()
    if handle is None:
        return False
    loc = _buffer_address(view)
    if loc is None:
        return False
    addr, n, keepalive = loc
    if n:
        handle.atpu_prefault(addr, n, stride)
    del keepalive
    return True
