"""Native (C++) runtime components, built on demand with the system
toolchain and loaded via ctypes.

``lib()`` returns the loaded library or ``None`` (no g++ / build
failure) — callers keep their pure-Python path as the fallback, so the
native layer is an accelerator, never a dependency.
"""

from __future__ import annotations

import ctypes
import glob
import logging
import os
import struct
import subprocess
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

LOG = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_libatpu_native.so")

_lock = threading.Lock()
_lib: "ctypes.CDLL | None | bool" = None  # None=untried, False=failed

# Every ctypes prototype the Python side relies on, as the single
# source of truth: ``lib()`` attaches these, and the atpu-lint
# ``native-abi`` rule cross-checks this table against the symbols the
# compiled .so actually exports (both directions), so C++/Python
# signature drift is a lint failure, not a runtime segfault.
_PROTOTYPES: "Dict[str, Tuple[list, object]]" = {
    "atpu_crc32": (
        [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32],
        ctypes.c_uint32),
    "atpu_scan_frames": (
        [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
         ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32),
         ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64)],
        ctypes.c_size_t),
    "atpu_prefault": (
        [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t],
        ctypes.c_uint64),
    "atpu_plan_exec": (
        [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p,
         ctypes.c_size_t],
        ctypes.c_int64),
}


def _sources() -> List[str]:
    """All translation units, sorted for a deterministic compile line."""
    return sorted(glob.glob(os.path.join(_DIR, "*.cpp")))


def _build() -> Optional[str]:
    """Compile the shared library when missing or stale."""
    try:
        srcs = _sources()
        if not srcs:
            return None
        # stale when ANY source (*.cpp or *.h) is newer than the .so —
        # keying on a single file once served a stale library after a
        # new translation unit landed
        deps = srcs + glob.glob(os.path.join(_DIR, "*.h"))
        if os.path.exists(_SO) and \
                os.path.getmtime(_SO) >= max(map(os.path.getmtime, deps)):
            return _SO
        # build into a temp file then rename: concurrent processes
        # (minicluster roles) must never dlopen a half-written .so
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               "-Wall", "-Werror", "-o", tmp] + srcs
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0:
            LOG.warning("native build failed: %s", r.stderr.decode()[:500])
            os.unlink(tmp)
            return None
        os.replace(tmp, _SO)
        return _SO
    except Exception:  # noqa: BLE001 - no toolchain: python fallback
        LOG.debug("native build unavailable", exc_info=True)
        return None


def lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib or None
    with _lock:
        if _lib is not None:
            return _lib or None
        so = _build()
        if so is None:
            _lib = False
            return None
        try:
            handle = ctypes.CDLL(so)
        except OSError:
            _lib = False
            return None
        try:
            for name, (argtypes, restype) in _PROTOTYPES.items():
                fn = getattr(handle, name)
                fn.argtypes = argtypes
                fn.restype = restype
        except AttributeError:
            # .so predates a declared symbol (e.g. stale build from a
            # read-only checkout): unusable, fall back everywhere
            LOG.warning("native library missing symbols; rebuild needed")
            _lib = False
            return None
        _lib = handle
        return handle


def _buffer_address(view) -> "Tuple[int, int, object] | None":
    """(address, nbytes, keepalive) of a buffer WITHOUT copying,
    readonly or not — hold ``keepalive`` for the duration of the native
    call. None when no zero-copy address is obtainable."""
    # numpy arrays expose the address directly regardless of flags
    data_attr = getattr(view, "ctypes", None)
    if data_attr is not None and hasattr(data_attr, "data"):
        return data_attr.data, view.nbytes, view
    if isinstance(view, bytes):
        # ctypes.cast of a bytes object points at its internal buffer
        return (ctypes.cast(view, ctypes.c_void_p).value or 0,
                len(view), view)
    mv = memoryview(view)
    if not mv.readonly:
        buf = (ctypes.c_char * mv.nbytes).from_buffer(mv)
        return ctypes.addressof(buf), mv.nbytes, buf
    try:
        # readonly memoryview/mmap: a numpy view exposes the address
        # without requiring writability (native code only reads)
        import numpy as np

        arr = np.frombuffer(mv, dtype=np.uint8)
        return arr.ctypes.data, arr.nbytes, (arr, mv)
    except Exception:  # noqa: BLE001
        return None


_SCAN_CHUNK = 65536  # frames per native call: bounds the offset arrays


def scan_frames(view) -> "Tuple[List[Tuple[int, int]], int] | None":
    """Scan ``[u32 len][u32 crc][body]`` frames over a buffer
    (bytes/bytearray/ndarray/mmap) with NO copy of the data. Returns
    ``([(body_off, body_len), ...], end_off)`` — ``end_off`` is the
    truncation point after the last valid frame — or ``None`` when the
    native library (or a zero-copy address) is unavailable. The scan
    runs in bounded chunks so offset arrays stay small regardless of
    journal size."""
    handle = lib()
    if handle is None:
        return None
    loc = _buffer_address(view)
    if loc is None:
        return None
    addr, n, keepalive = loc
    if n == 0:
        return [], 0
    offs = (ctypes.c_uint64 * _SCAN_CHUNK)()
    lens = (ctypes.c_uint32 * _SCAN_CHUNK)()
    end = ctypes.c_uint64(0)
    frames: List[Tuple[int, int]] = []
    start = 0
    while True:
        got = handle.atpu_scan_frames(addr, n, start, offs, lens,
                                      _SCAN_CHUNK, ctypes.byref(end))
        frames.extend((offs[i], lens[i]) for i in range(got))
        start = end.value
        if got < _SCAN_CHUNK:
            break
    del keepalive
    return frames, end.value


def crc32(data: bytes, seed: int = 0) -> Optional[int]:
    handle = lib()
    if handle is None:
        return None
    return handle.atpu_crc32(data, len(data), seed)


def prefault(view, stride: int = 4096) -> bool:
    """Touch one byte per page, GIL-free, readonly-safe and zero-copy.
    True when the native path ran (False -> caller falls back)."""
    handle = lib()
    if handle is None:
        return False
    loc = _buffer_address(view)
    if loc is None:
        return False
    addr, n, keepalive = loc
    if n:
        handle.atpu_prefault(addr, n, stride)
    del keepalive
    return True


# ---------------------------------------------------------------- plan exec

# Mirrors struct AtpuPlanOp in plan_exec.cpp exactly: 48 bytes,
# little-endian, naturally aligned (u32+i32 then five u64) — no
# padding, so a C-contiguous structured array IS the C op table.
OP_COPY = 0
OP_PREAD = 1
OP_DTYPE_FIELDS = [
    ("kind", "<u4"), ("fd", "<i4"), ("src", "<u8"), ("src_off", "<u8"),
    ("src_len", "<u8"), ("dst_off", "<u8"), ("len", "<u8"),
]


def op_dtype():
    import numpy as np

    dt = np.dtype(OP_DTYPE_FIELDS)
    assert dt.itemsize == 48, "op dtype drifted from plan_exec.cpp"
    return dt


def exec_plan(ops, dest) -> Optional[int]:
    """Run a packed op table (a C-contiguous structured array of
    ``op_dtype()`` records) against ``dest`` (writable buffer) in ONE
    native call — the GIL is released for the whole batch. Returns the
    executor's result (total bytes written >= 0, or ``-(i+1)`` when op
    ``i`` failed), or ``None`` when the native library is unavailable
    (caller falls back to Python)."""
    handle = lib()
    if handle is None:
        return None
    nops = len(ops)
    if nops == 0:
        return 0
    dst = _buffer_address(dest)
    if dst is None:
        return None
    dst_addr, dst_len, dst_keep = dst
    rc = handle.atpu_plan_exec(ops.ctypes.data, nops, dst_addr, dst_len)
    del dst_keep
    return rc


# ------------------------------------------------------------- ELF symbols

def exported_symbols(path: Optional[str] = None) -> Optional[List[str]]:
    """Defined ``atpu_*`` function symbols exported by the compiled
    library, read from the ELF ``.dynsym`` table directly (no ``nm``
    dependency). Returns ``None`` when the .so is missing or not a
    64-bit little-endian ELF — used by the atpu-lint ``native-abi``
    rule to diff the C++ export surface against ``_PROTOTYPES``."""
    so = path or (_build() if os.path.exists(_DIR) else None)
    if so is None or not os.path.exists(so):
        return None
    try:
        with open(so, "rb") as f:
            data = f.read()
        if data[:4] != b"\x7fELF" or data[4] != 2 or data[5] != 1:
            return None  # not ELF64 little-endian
        e_shoff, = struct.unpack_from("<Q", data, 0x28)
        e_shentsize, e_shnum = struct.unpack_from("<HH", data, 0x3A)
        dynsym = dynstr = None
        for i in range(e_shnum):
            base = e_shoff + i * e_shentsize
            sh_type, = struct.unpack_from("<I", data, base + 4)
            sh_offset, sh_size = struct.unpack_from("<QQ", data, base + 24)
            sh_link, = struct.unpack_from("<I", data, base + 40)
            sh_entsize, = struct.unpack_from("<Q", data, base + 56)
            if sh_type == 11:  # SHT_DYNSYM
                dynsym = (sh_offset, sh_size, sh_entsize, sh_link)
        if dynsym is None:
            return None
        str_base = e_shoff + dynsym[3] * e_shentsize
        str_off, str_size = struct.unpack_from("<QQ", data, str_base + 24)
        dynstr = data[str_off:str_off + str_size]
        out: List[str] = []
        off, size, entsize, _ = dynsym
        for pos in range(off, off + size, entsize or 24):
            st_name, st_info = struct.unpack_from("<IB", data, pos)
            st_shndx, = struct.unpack_from("<H", data, pos + 6)
            if (st_info & 0xF) != 2 or st_shndx == 0:  # STT_FUNC, defined
                continue
            end = dynstr.index(b"\0", st_name)
            name = dynstr[st_name:end].decode("ascii", "replace")
            if name.startswith("atpu_"):
                out.append(name)
        return sorted(out)
    except Exception:  # noqa: BLE001 - malformed ELF: lint rule skips
        LOG.debug("exported_symbols parse failed", exc_info=True)
        return None
