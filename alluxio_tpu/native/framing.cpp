// Native journal-frame scanner + zlib-compatible CRC32.
//
// The runtime analogue of the reference's native storage engines (its
// metastore rides RocksDB's C++ via JNI): recovery-scanning a journal in
// Python costs a bytes allocation + two attribute lookups + a zlib call
// PER FRAME; this scanner validates [u32 len][u32 crc32][body] framing
// over one mmap'd buffer at memory bandwidth with zero per-frame
// allocations, returning frame offsets for the (semantic) msgpack decode
// to consume. Shared by journal/format.py and journal/raft.py — both
// write the same frame layout.
//
// Built on demand by build.py (g++ -O3); loaded via ctypes, so every
// entry point is extern "C" with POD-only signatures.

#include <cstddef>
#include <cstdint>

namespace {

// zlib CRC32 (poly 0xEDB88320, reflected), slice-by-8. Tables build in
// a static initializer (runs once at dlopen, before any ctypes call can
// race it — lazy bool-guarded init would be UB under the concurrent
// first calls the GIL-releasing ctypes boundary allows).
uint32_t g_tab[8][256];

struct TabInit {
    TabInit() {
        for (uint32_t i = 0; i < 256; ++i) {
            uint32_t c = i;
            for (int k = 0; k < 8; ++k)
                c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
            g_tab[0][i] = c;
        }
        for (uint32_t i = 0; i < 256; ++i)
            for (int s = 1; s < 8; ++s)
                g_tab[s][i] = g_tab[0][g_tab[s - 1][i] & 0xFFu] ^
                              (g_tab[s - 1][i] >> 8);
    }
};
const TabInit g_tab_init;

inline uint32_t crc32_impl(const uint8_t* p, size_t n, uint32_t seed) {
    uint32_t c = ~seed;
    while (n >= 8) {
        // byte-wise 64-bit gather keeps this endian/alignment safe
        uint32_t lo = static_cast<uint32_t>(p[0]) |
                      (static_cast<uint32_t>(p[1]) << 8) |
                      (static_cast<uint32_t>(p[2]) << 16) |
                      (static_cast<uint32_t>(p[3]) << 24);
        uint32_t hi = static_cast<uint32_t>(p[4]) |
                      (static_cast<uint32_t>(p[5]) << 8) |
                      (static_cast<uint32_t>(p[6]) << 16) |
                      (static_cast<uint32_t>(p[7]) << 24);
        c ^= lo;
        c = g_tab[7][c & 0xFF] ^ g_tab[6][(c >> 8) & 0xFF] ^
            g_tab[5][(c >> 16) & 0xFF] ^ g_tab[4][c >> 24] ^
            g_tab[3][hi & 0xFF] ^ g_tab[2][(hi >> 8) & 0xFF] ^
            g_tab[1][(hi >> 16) & 0xFF] ^ g_tab[0][hi >> 24];
        p += 8;
        n -= 8;
    }
    while (n--) c = g_tab[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
    return ~c;
}

inline uint32_t read_u32le(const uint8_t* p) {
    return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
}

}  // namespace

extern "C" {

uint32_t atpu_crc32(const uint8_t* p, size_t n, uint32_t seed) {
    return crc32_impl(p, n, seed);
}

// Scan frames in buf[start_off:len]. For each valid frame i < cap,
// write the BODY offset into offsets[i] and body length into
// lengths[i]. Stops at the first torn/invalid frame (short header,
// length==0 zero-padding guard, body past EOF, or CRC mismatch) —
// everything after a torn frame is unreachable on restart, matching
// the Python scanners. Returns the number of valid frames; *end_off
// gets the byte offset one past the last valid frame (resume point
// for chunked calls / truncation point for torn tails).
size_t atpu_scan_frames(const uint8_t* buf, size_t len, size_t start_off,
                        uint64_t* offsets, uint32_t* lengths, size_t cap,
                        uint64_t* end_off) {
    size_t off = start_off, count = 0;
    while (count < cap && off + 8 <= len) {
        uint32_t flen = read_u32le(buf + off);
        uint32_t fcrc = read_u32le(buf + off + 4);
        if (flen == 0) break;                    // zero padding
        if (off + 8 + flen > len) break;         // torn body
        if (crc32_impl(buf + off + 8, flen, 0) != fcrc) break;
        offsets[count] = off + 8;
        lengths[count] = flen;
        ++count;
        off += 8 + static_cast<size_t>(flen);
    }
    if (end_off) *end_off = off;
    return count;
}

// Touch one byte per page so a later sequential consumer never
// page-fault-stalls (loader pre-fault; GIL-free by construction).
uint64_t atpu_prefault(const uint8_t* buf, size_t len, size_t stride) {
    if (stride == 0) stride = 4096;
    uint64_t acc = 0;
    for (size_t i = 0; i < len; i += stride) acc += buf[i];
    if (len) acc += buf[len - 1];
    return acc;
}

}  // extern "C"
