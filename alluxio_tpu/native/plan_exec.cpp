// Native small-read plan executor.
//
// choose_route() stays in Python as the planner; this file is the
// engine. The client packs a plan — one 48-byte POD record per op —
// and hands the whole table across the ctypes boundary in ONE call, so
// the GIL is released exactly once per batch instead of once per op.
// Each op is either a memcpy from an already-mapped source (SHM
// segment, received read_many payload, stripe scratch) or a pread(2)
// from a local file descriptor, landing in a single preallocated
// destination buffer at the planned offset. Zero per-op Python frames;
// the per-op cost drops from interpreter-dispatch time to memory
// bandwidth.
//
// Failure contract: the executor validates every op's bounds before
// touching memory for it and returns -(i+1) on the first bad op i
// (unknown kind, source/dest overrun, pread error or short read).
// Bytes already written for earlier ops stay written — the Python
// caller discards the buffer and falls down the route ladder to the
// pure-Python path, which is byte-identical by construction.
//
// Loaded via ctypes, so the entry point is extern "C" with POD-only
// arguments; the record layout below is naturally aligned (4+4+8*5 =
// 48 bytes, no padding) and mirrored by OP_DTYPE in __init__.py.

#include <cerrno>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <unistd.h>

namespace {

enum : uint32_t {
    kOpCopy = 0,   // memcpy(dst + dst_off, src + src_off, len)
    kOpPread = 1,  // pread(fd, dst + dst_off, len, src_off)
};

struct AtpuPlanOp {
    uint32_t kind;
    int32_t fd;        // kOpPread only; -1 otherwise
    uint64_t src;      // kOpCopy: source base address
    uint64_t src_off;  // offset within source (kOpCopy) / file (kOpPread)
    uint64_t src_len;  // kOpCopy: source extent for bounds checking
    uint64_t dst_off;  // offset within the destination buffer
    uint64_t len;      // bytes to move; 0 is a valid no-op
};

static_assert(sizeof(AtpuPlanOp) == 48, "op record layout drifted");

// Full read at an absolute offset: pread may return short on signals
// or page-cache boundaries; anything short of len after EOF is an
// error (the planner clamped sizes to the readable extent already).
bool pread_full(int fd, uint8_t* dst, uint64_t len, uint64_t off) {
    while (len > 0) {
        ssize_t got = ::pread(fd, dst, static_cast<size_t>(len),
                              static_cast<off_t>(off));
        if (got < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        if (got == 0) return false;  // EOF before the planned extent
        dst += got;
        off += static_cast<uint64_t>(got);
        len -= static_cast<uint64_t>(got);
    }
    return true;
}

}  // namespace

extern "C" {

// Execute nops plan records into dst[0:dst_len]. Returns the total
// bytes written (>= 0) on success, or -(i+1) when op i fails
// validation or I/O. Ops may overlap in the destination (last writer
// wins, in table order) — the Python reference executor matches.
int64_t atpu_plan_exec(const AtpuPlanOp* ops, size_t nops,
                       uint8_t* dst, size_t dst_len) {
    int64_t total = 0;
    for (size_t i = 0; i < nops; ++i) {
        const AtpuPlanOp& op = ops[i];
        if (op.len == 0) continue;
        if (op.dst_off > dst_len || op.len > dst_len - op.dst_off)
            return -static_cast<int64_t>(i + 1);
        uint8_t* out = dst + op.dst_off;
        switch (op.kind) {
            case kOpCopy: {
                if (op.src == 0 || op.src_off > op.src_len ||
                    op.len > op.src_len - op.src_off)
                    return -static_cast<int64_t>(i + 1);
                std::memcpy(out,
                            reinterpret_cast<const uint8_t*>(op.src) +
                                op.src_off,
                            static_cast<size_t>(op.len));
                break;
            }
            case kOpPread: {
                if (op.fd < 0 ||
                    !pread_full(op.fd, out, op.len, op.src_off))
                    return -static_cast<int64_t>(i + 1);
                break;
            }
            default:
                return -static_cast<int64_t>(i + 1);
        }
        total += static_cast<int64_t>(op.len);
    }
    return total;
}

}  // extern "C"
