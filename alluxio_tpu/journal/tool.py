"""Journal inspection tool.

Re-design of ``core/server/master/.../master/journal/tool/JournalTool.java:77``
(+ ``UfsJournalDumper``): human-readable dump of a journal directory —
latest checkpoint summary and every entry of every segment, without
needing a running master.
"""

from __future__ import annotations

import os
import sys
from typing import Optional, TextIO

import msgpack

from alluxio_tpu.journal.format import JournalEntry
from alluxio_tpu.journal.system import (
    CKPT_DIR, LOG_DIR, latest_checkpoint_name, sorted_segments,
)


def _fmt_payload(payload: dict, max_len: int = 160) -> str:
    s = repr(payload)
    return s if len(s) <= max_len else s[:max_len] + "...}"


def dump_journal(folder: str, out: Optional[TextIO] = None, *,
                 start_seq: int = 0,
                 end_seq: Optional[int] = None) -> int:
    """Print checkpoint + entries in [start_seq, end_seq]; returns the
    number of entries printed."""
    out = out if out is not None else sys.stdout  # late-bind: honor redirects
    ckpt_dir = os.path.join(folder, CKPT_DIR)
    log_dir = os.path.join(folder, LOG_DIR)
    printed = 0
    if os.path.isdir(ckpt_dir):
        cks = sorted(f for f in os.listdir(ckpt_dir)
                     if f.endswith(".ckpt"))
        for ck in cks:
            with open(os.path.join(ckpt_dir, ck), "rb") as f:
                snap = msgpack.unpackb(f.read(), raw=False,
                                       strict_map_key=False)
            comps = ", ".join(sorted(snap.get("components", {})))
            print(f"checkpoint {ck}: sequence={snap.get('sequence')} "
                  f"components=[{comps}]", file=out)
    if not os.path.isdir(log_dir):
        return printed
    segs = sorted(
        (f for f in os.listdir(log_dir) if f.endswith(".log")),
        key=lambda f: (1 << 62) if f.startswith("current")
        else int(f.split("-")[0], 16))
    for seg in segs:
        print(f"segment {seg}:", file=out)
        with open(os.path.join(log_dir, seg), "rb") as f:
            for entry in JournalEntry.decode_stream(f):
                if entry.sequence < start_seq:
                    continue
                if end_seq is not None and entry.sequence > end_seq:
                    continue
                print(f"  #{entry.sequence} {entry.type} "
                      f"{_fmt_payload(entry.payload)}", file=out)
                printed += 1
    return printed
