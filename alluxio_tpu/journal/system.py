"""Journal system: segmented WAL + checkpoints + group-commit flushing.

Re-design of the reference's journal stack
(``core/server/common/.../journal/{JournalSystem,AsyncJournalWriter,
JournalContext}.java`` and the UFS flavor ``journal/ufs/UfsJournal.java:71``):

- A **LocalJournalSystem** writes sequence-contiguous segment files
  ``<dir>/logs/0x<start>-0x<end>.log`` plus an active ``current.log``; a
  **checkpoint** is a msgpack snapshot of every `Journaled` component at a
  sequence number (``<dir>/checkpoints/0x<seq>.ckpt``), after which older
  segments are garbage-collected.
- **Group commit**: all entries of one ``JournalContext`` are written and
  fsynced together on context exit — the same acknowledged-durability
  contract the reference gets from ``AsyncJournalWriter``'s flush-before-
  RPC-return, batched per operation instead of per timer tick.
- **Primacy fencing** uses an epoch file + O_EXCL lock file; a master that
  loses the lock stops writing (the reference fences via log rotation /
  Raft terms). Raft-style replicated mode lives in ``journal/raft.py``.
- A NOOP flavor backs read-only/standby and unit-test uses.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

import msgpack

from alluxio_tpu.journal.format import JournalEntry, Journaled
from alluxio_tpu.utils.exceptions import JournalClosedError

LOG_DIR = "logs"
CKPT_DIR = "checkpoints"
ACTIVE_LOG = "current.log"


def sorted_segments(log_dir: str) -> List[str]:
    """Closed segments by start sequence, then the active log."""
    if not os.path.isdir(log_dir):
        return []
    segs = [f for f in os.listdir(log_dir) if f.endswith(".log")]
    return sorted(segs, key=lambda f: (1 << 62) if f == ACTIVE_LOG
                  else int(f.split("-")[0], 16))


def latest_checkpoint_name(ckpt_dir: str) -> Optional[str]:
    if not os.path.isdir(ckpt_dir):
        return None
    cks = [f for f in os.listdir(ckpt_dir) if f.endswith(".ckpt")]
    if not cks:
        return None
    return max(cks, key=lambda f: int(f.split(".")[0], 16))


class JournalContext:
    """Scoped appender: entries written through one context are flushed
    (durable) by the time the context exits (reference: ``JournalContext``
    + ``MasterJournalContext``)."""

    def __init__(self, system: "JournalSystem") -> None:
        self._system = system
        self._pending: List[JournalEntry] = []

    def append(self, entry_type: str, payload: dict) -> JournalEntry:
        entry = self._system.allocate_entry(entry_type, payload)
        self._pending.append(entry)
        return entry

    def __enter__(self) -> "JournalContext":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self._system.write_and_flush(self._pending)
        self._pending.clear()
        return False


class JournalSystem:
    """Abstract journal system."""

    def __init__(self) -> None:
        self._components: Dict[str, Journaled] = {}

    def register(self, component: Journaled) -> None:
        assert component.journal_name, "Journaled needs a journal_name"
        self._components[component.journal_name] = component

    # lifecycle
    def start(self) -> None: ...
    def gain_primacy(self) -> None: ...
    def lose_primacy(self) -> None: ...
    def stop(self) -> None: ...

    def is_primary(self) -> bool:
        return True

    # writing
    def allocate_entry(self, entry_type: str, payload: dict) -> JournalEntry:
        raise NotImplementedError

    def write_and_flush(self, entries: List[JournalEntry]) -> None:
        raise NotImplementedError

    def create_context(self) -> JournalContext:
        return JournalContext(self)

    def deferred_durability(self):
        """Scope in which journal contexts may DEFER their durability
        wait to scope exit (reference: ``AsyncJournalWriter`` — state is
        applied immediately, the fsync happens once per RPC, after all
        locks are released, before the response goes out). Default: a
        no-op scope; flavors with a real fsync override this."""
        import contextlib

        return contextlib.nullcontext()

    def immediate_durability(self):
        """Scope that suspends ``deferred_durability`` for writes that
        must be durable BEFORE their effects are exposed to other
        threads (e.g. id-chunk reservations: an id may be handed out,
        used and journaled by another RPC before the deferring RPC ever
        flushes its reservation)."""
        import contextlib

        return contextlib.nullcontext()

    # maintenance
    def checkpoint(self) -> None: ...

    def _apply(self, entry: JournalEntry) -> None:
        for comp in self._components.values():
            if comp.process_entry(entry):
                return
        raise ValueError(f"no component applied journal entry {entry.type}")


class NoopJournalSystem(JournalSystem):
    """Applies entries to state immediately; durability-free (tests)."""

    def __init__(self) -> None:
        super().__init__()
        self._seq = 0
        self._lock = threading.Lock()

    def allocate_entry(self, entry_type: str, payload: dict) -> JournalEntry:
        with self._lock:
            self._seq += 1
            return JournalEntry(self._seq, entry_type, payload)

    def write_and_flush(self, entries: List[JournalEntry]) -> None:
        # serialize applies: with the striped inode tree, concurrent
        # disjoint-subtree mutations reach here in parallel, and the
        # Journaled components' registries assume one applier at a time
        with self._lock:
            for e in entries:
                self._apply(e)


class LocalJournalSystem(JournalSystem):
    """Durable single-writer journal over a directory (local disk or any
    mounted shared filesystem — the UFS-journal analogue)."""

    #: bound on queued-but-unwritten entries in group-commit mode:
    #: producers block (briefly — one flusher drain) at the cap, so a
    #: flusher stall cannot grow the queue without bound
    COMMIT_QUEUE_MAX_ENTRIES = 10_000

    def __init__(self, folder: str, *,
                 max_log_size: int = 64 << 20,
                 checkpoint_period_entries: int = 2_000_000) -> None:
        super().__init__()
        self._folder = folder
        self._log_dir = os.path.join(folder, LOG_DIR)
        self._ckpt_dir = os.path.join(folder, CKPT_DIR)
        self._max_log_size = max_log_size
        self._checkpoint_period = checkpoint_period_entries
        self._seq = 0
        self._last_checkpoint_seq = 0
        self._primary = False
        self._file = None
        self._file_start_seq = 1
        self._lock = threading.RLock()
        self._closed = False
        # Durability is tracked by WRITE TICKETS, not sequence numbers:
        # a ticket is assigned under the main lock in the same critical
        # section as the batch's acceptance, so "synced ticket >= mine"
        # really means "my batch reached the disk".  (Sequence numbers
        # cannot carry this: they are allocated before the write, so a
        # batch written AFTER a covering fsync could carry a smaller
        # seq and be acknowledged without ever being fsynced.)
        self._write_ticket = 0    # batches accepted (inline: written)
        self._synced_ticket = 0   # batches known fsync-durable
        # inline group commit: one fsync covers every batch written
        # before it (reference: AsyncJournalWriter's flush batching)
        self._flush_lock = threading.Lock()
        self._deferred = threading.local()
        # -- dedicated group-commit flusher (atpu.master.journal.flush.
        # batch.time): entries are accepted + applied under the main
        # lock, queued, and written+fsynced by ONE background flusher
        # in timed batches; producers block only until their batch's
        # fsync completes — the same acknowledged-durability point,
        # off the callers' inode-lock critical sections.
        self._commit_cond = threading.Condition(self._lock)
        self._commit_queue: List[List[JournalEntry]] = []
        self._commit_queue_entries = 0
        self._batch_time_s = 0.0
        self._flusher: "threading.Thread | None" = None
        self._flusher_stop = False
        self._flush_error: "BaseException | None" = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        os.makedirs(self._log_dir, exist_ok=True)
        os.makedirs(self._ckpt_dir, exist_ok=True)

    def gain_primacy(self) -> None:
        """Replay (checkpoint + segments) then open a fresh active log."""
        with self._lock:
            self._replay()
            self._open_log()
            self._primary = True

    def lose_primacy(self) -> None:
        self._stop_flusher()
        with self._lock:
            self._primary = False
            self._close_log()

    def stop(self) -> None:
        self._stop_flusher()
        with self._lock:
            self._close_log()
            self._closed = True

    # -- group-commit flusher ----------------------------------------------
    def start_group_commit(self, batch_time_s: float = 0.005) -> None:
        """Start the dedicated journal flusher
        (``atpu.master.journal.flush.batch.time``): from here on,
        ``write_and_flush`` queues entries instead of writing inline,
        and the flusher coalesces up to ``batch_time_s`` of arrivals
        into one file write + one fsync.  Idempotent."""
        with self._lock:
            if self._flusher is not None:
                return
            self._batch_time_s = max(0.0, float(batch_time_s))
            self._flusher_stop = False
            self._flusher = threading.Thread(
                target=self._flusher_loop, name="journal-flusher",
                daemon=True)
            self._flusher.start()

    def _stop_flusher(self) -> None:
        with self._lock:
            t = self._flusher
            if t is None:
                return
            self._flusher_stop = True
            self._commit_cond.notify_all()
        t.join(timeout=30.0)
        with self._lock:
            self._flusher = None

    def _flusher_loop(self) -> None:
        from alluxio_tpu.metrics import metrics as _metrics

        batch_timer = _metrics().timer("Master.MetadataJournalBatchSize")
        flush_timer = _metrics().timer("Master.MetadataJournalFlushTime")
        pressured = False  # queue was non-empty right after the last flush
        while True:
            with self._commit_cond:
                while not self._commit_queue and not self._flusher_stop:
                    self._commit_cond.wait(0.2)
                if not self._commit_queue and self._flusher_stop:
                    return
            # Coalescing window (reference: AsyncJournalWriter waits up
            # to the batch time for more entries) — applied ONLY under
            # sustained pressure: a lone sequential writer flushes
            # immediately (inline-class latency), while concurrent load
            # — which refills the queue during the previous fsync —
            # accumulates batch_time of arrivals into one fsync.
            if pressured and self._batch_time_s > 0 and \
                    not self._flusher_stop:
                time.sleep(self._batch_time_s)
            t0 = time.perf_counter()
            fd = None
            with self._commit_cond:
                batches = self._commit_queue
                self._commit_queue = []
                n_entries = self._commit_queue_entries
                self._commit_queue_entries = 0
                ticket = self._write_ticket
                try:
                    if self._file is None:
                        raise JournalClosedError(
                            "journal log closed with entries queued")
                    for batch in batches:
                        for e in batch:
                            self._file.write(e.encode())
                    self._maybe_rotate()
                    if self._seq - self._last_checkpoint_seq >= \
                            self._checkpoint_period:
                        self._checkpoint_locked()
                    if self._file is not None:
                        self._file.flush()
                        fd = self._file.fileno()
                except BaseException as e:  # noqa: BLE001 latch + surface
                    self._flush_error = e
                # free bounded-queue waiters
                self._commit_cond.notify_all()
            if fd is not None and self._flush_error is None:
                try:
                    self._fsync(fd)
                except (OSError, ValueError) as e:
                    # a concurrent rotation (checkpoint RPC) closes this
                    # fd AFTER fsyncing it and marks the written tickets
                    # synced — benign iff our ticket is already covered;
                    # a real fsync failure is latched: an acknowledged-
                    # durability journal must not limp on
                    with self._commit_cond:
                        if self._synced_ticket < ticket:
                            self._flush_error = e
            with self._commit_cond:
                if self._flush_error is None and \
                        ticket > self._synced_ticket:
                    self._synced_ticket = ticket
                pressured = bool(self._commit_queue)
                self._commit_cond.notify_all()
            batch_timer.update(float(n_entries))
            flush_timer.update(time.perf_counter() - t0)

    def _fsync(self, fd: int) -> None:
        """The one fsync choke point (tests/benches override to model
        slow devices; the chaos injector's ``fsync_errors`` countdown
        fails the next N syncs here — the ack-durability crash drill)."""
        from alluxio_tpu.utils import faults

        if faults.armed() and faults.injector().take_fsync_error():
            raise OSError("injected journal fsync failure")
        os.fsync(fd)

    def is_primary(self) -> bool:
        return self._primary

    # -- replay -------------------------------------------------------------
    def _list_segments(self) -> List[str]:
        return sorted_segments(self._log_dir)

    def _latest_checkpoint(self) -> Optional[str]:
        return latest_checkpoint_name(self._ckpt_dir)

    def _replay(self) -> None:
        for comp in self._components.values():
            comp.reset_state()
        start_seq = 0
        ck = self._latest_checkpoint()
        if ck:
            with open(os.path.join(self._ckpt_dir, ck), "rb") as f:
                snap = msgpack.unpackb(f.read(), raw=False, strict_map_key=False)
            start_seq = snap["sequence"]
            for name, comp in self._components.items():
                if name in snap["components"]:
                    comp.restore(snap["components"][name])
        max_seq = start_seq
        for seg in self._list_segments():
            path = os.path.join(self._log_dir, seg)
            try:
                f = open(path, "rb")
            except FileNotFoundError:  # GC'd by a live primary mid-scan
                continue
            with f:
                for entry in JournalEntry.decode_stream(f):
                    if entry.sequence <= start_seq:
                        continue
                    self._apply(entry)
                    max_seq = max(max_seq, entry.sequence)
        self._seq = max_seq
        self._last_checkpoint_seq = start_seq

    # -- writing ------------------------------------------------------------
    def _open_log(self) -> None:
        self._file_start_seq = self._seq + 1
        path = os.path.join(self._log_dir, ACTIVE_LOG)
        self._file = open(path, "ab")

    def _close_log(self) -> None:
        if self._file is None:
            return
        self._file.flush()
        self._fsync(self._file.fileno())
        # every WRITTEN batch is in this file (or an earlier, already-
        # fsynced one): rotation is a durability point.  Batches still
        # in the commit queue (group-commit mode, one ticket each) are
        # not written yet and must stay uncovered.
        written = self._write_ticket - len(self._commit_queue)
        self._synced_ticket = max(self._synced_ticket, written)
        self._file.close()
        self._file = None
        cur = os.path.join(self._log_dir, ACTIVE_LOG)
        if os.path.exists(cur) and self._seq >= self._file_start_seq:
            final = os.path.join(
                self._log_dir,
                f"{self._file_start_seq:016x}-{self._seq:016x}.log")
            os.rename(cur, final)
        elif os.path.exists(cur) and os.path.getsize(cur) == 0:
            os.remove(cur)

    def _maybe_rotate(self) -> None:
        if self._file is not None and self._file.tell() >= self._max_log_size:
            self._close_log()
            self._open_log()

    def allocate_entry(self, entry_type: str, payload: dict) -> JournalEntry:
        with self._lock:
            if self._closed:
                raise JournalClosedError("journal is closed")
            if self._file is None:
                # tail-only (standby) or not yet primary: sequences are
                # assigned by the primary.  Allocating here would bump
                # _seq past entries we have not tailed, and catch_up
                # would then silently SKIP the primary's real entries
                # at those sequences — fail the write attempt instead.
                raise JournalClosedError("journal not open for writes")
            self._seq += 1
            return JournalEntry(self._seq, entry_type, payload)

    def write_and_flush(self, entries: List[JournalEntry]) -> None:
        """Accept + apply this batch; make it durable before returning —
        either right here, or (inside a ``deferred_durability`` scope)
        once at scope exit so one fsync covers every context the RPC
        opened AND coalesces with other threads' flushes (group commit,
        reference ``AsyncJournalWriter``).

        Inline mode writes the file under the main lock and fsyncs via
        the flush convoy.  Group-commit mode (``start_group_commit``)
        queues the batch for the dedicated flusher — the file write and
        fsync both leave the caller's critical section, and the caller
        blocks only until its batch's fsync completes.  Either way the
        in-memory apply happens here, under the main lock, in
        acceptance order — an entry is applied before it is durable:
        the same visibility contract as the reference, which applies
        first and flushes before the mutating RPC responds, so no
        ACKNOWLEDGED mutation is ever lost.
        """
        if not entries:
            return
        with self._lock:
            if self._closed or self._file is None:
                raise JournalClosedError("journal not open for writes")
            batched = self._flusher is not None
            if batched:
                if self._flush_error is not None:
                    raise JournalClosedError(
                        "journal flusher failed") from self._flush_error
                while self._commit_queue_entries >= \
                        self.COMMIT_QUEUE_MAX_ENTRIES:
                    self._commit_cond.wait(0.5)
                    if self._flush_error is not None:
                        raise JournalClosedError(
                            "journal flusher failed") from self._flush_error
                    if self._closed or self._file is None:
                        raise JournalClosedError("journal not open for writes")
                self._commit_queue.append(list(entries))
                self._commit_queue_entries += len(entries)
            else:
                for e in entries:
                    self._file.write(e.encode())
            self._write_ticket += 1
            ticket = self._write_ticket
            for e in entries:
                self._apply(e)
            if batched:
                self._commit_cond.notify_all()  # wake the flusher
            else:
                self._maybe_rotate()
                if self._seq - self._last_checkpoint_seq >= \
                        self._checkpoint_period:
                    self._checkpoint_locked()
        if getattr(self._deferred, "on", False):
            self._deferred.want = ticket
            return
        self._ensure_durable(ticket)

    def deferred_durability(self):
        import contextlib

        @contextlib.contextmanager
        def scope():
            prev = getattr(self._deferred, "on", False)
            # Nest-safe: an inner scope must not discard the outer scope's
            # accumulated flush obligation — entries journaled in the outer
            # scope before the inner one would otherwise be acknowledged
            # but never fsynced at outer-scope exit.
            prev_want = getattr(self._deferred, "want", 0)
            self._deferred.on = True
            self._deferred.want = prev_want
            try:
                yield
            finally:
                want = getattr(self._deferred, "want", 0)
                self._deferred.on = prev
                if prev:
                    self._deferred.want = max(want, prev_want)
                else:
                    self._deferred.want = 0  # don't seed later scopes
                    if want:
                        self._ensure_durable(want)

        return scope()

    def immediate_durability(self):
        import contextlib

        @contextlib.contextmanager
        def scope():
            prev = getattr(self._deferred, "on", False)
            self._deferred.on = False
            try:
                yield
            finally:
                self._deferred.on = prev

        return scope()

    def _ensure_durable(self, ticket: int) -> None:
        """Block until the batch holding ``ticket`` is fsync-durable.

        Group-commit mode: wait for the flusher to cover the ticket.
        Inline mode: one flusher syncs for the whole convoy — waiters
        that arrive while an fsync is in flight find their ticket
        already covered and return without issuing their own.  Tickets
        (assigned atomically with the write/acceptance) make coverage
        exact: a batch accepted after an fsync began can never be
        acknowledged by it."""
        if self._synced_ticket >= ticket:  # racy fast path: monotonic
            return
        if self._flusher is not None:
            with self._commit_cond:
                while self._synced_ticket < ticket:
                    if self._flush_error is not None:
                        raise JournalClosedError(
                            "journal flusher failed") from self._flush_error
                    if self._flusher is None or self._closed:
                        # stop() drains before closing; anything still
                        # uncovered here was never made durable
                        raise JournalClosedError("journal closed before "
                                                 "flush completed")
                    self._commit_cond.wait(0.5)
            return
        with self._flush_lock:
            with self._lock:
                if self._synced_ticket >= ticket:
                    return
                f = self._file
                if f is None:
                    # rotation/close fsyncs everything it closes
                    return
                f.flush()
                # tickets still sitting in the commit queue (one per
                # batch) are NOT in this file: an fsync here must never
                # cover them.  A caller whose own batch is among them
                # (flusher-shutdown race) must fail, not false-ack.
                target = self._write_ticket - len(self._commit_queue)
                if target < ticket:
                    raise JournalClosedError(
                        "journal flusher stopped with this batch "
                        "unwritten")
                fd = f.fileno()
            try:
                self._fsync(fd)
            except (OSError, ValueError):
                # the log rotated under us and closed this fd — rotation
                # fsyncs before closing, so our entries are durable
                with self._lock:
                    if self._synced_ticket >= ticket:
                        return
                    raise
            with self._lock:
                if target > self._synced_ticket:
                    self._synced_ticket = target

    # -- checkpoint ---------------------------------------------------------
    def checkpoint(self) -> None:
        with self._lock:
            self._checkpoint_locked()

    def _checkpoint_locked(self) -> None:
        snap = {
            "sequence": self._seq,
            "components": {name: comp.snapshot()
                           for name, comp in self._components.items()},
        }
        tmp = os.path.join(self._ckpt_dir,
                           f".tmp.{self._seq:016x}.{os.getpid()}")
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(snap, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        final = os.path.join(self._ckpt_dir, f"{self._seq:016x}.ckpt")
        os.rename(tmp, final)
        self._last_checkpoint_seq = self._seq
        # GC fully-covered closed segments (keep current.log)
        for seg in self._list_segments():
            if seg == ACTIVE_LOG:
                continue
            end = int(seg.split("-")[1].split(".")[0], 16)
            if end <= self._seq:
                try:
                    os.remove(os.path.join(self._log_dir, seg))
                except FileNotFoundError:
                    pass  # a standby's checkpoint GC'd it first
        # rotate the active log so the pre-checkpoint tail can be dropped too
        if self._file is not None:
            self._close_log()
            self._open_log()

    # -- standby mode (reference: standby masters tail the journal) ---------
    def standby_start(self) -> None:
        """Initial standby load: checkpoint + all durable segments, without
        opening a write log."""
        with self._lock:
            self.start()
            self._replay()

    def catch_up(self) -> int:
        """Apply entries newer than the local sequence (the tailer tick).
        Tolerates the primary's in-flight torn tail. STRICTLY contiguous:
        a sequence gap (e.g. the primary rotated the active log between
        our listdir and open, so we read the new log first) triggers a
        full rescan instead of silently skipping entries. Returns the
        number of entries applied."""
        applied = 0
        with self._lock:
            # a newer checkpoint than our state implies entries we can no
            # longer read from GC'd segments: reload from scratch
            ck = self._latest_checkpoint()
            if ck and int(ck.split(".")[0], 16) > self._seq:
                self._replay()
                return 0
            gap = False
            for seg in self._list_segments():
                path = os.path.join(self._log_dir, seg)
                try:
                    f = open(path, "rb")
                except FileNotFoundError:  # GC'd between list and open
                    continue
                with f:
                    for entry in JournalEntry.decode_stream(f):
                        if entry.sequence <= self._seq:
                            continue
                        if entry.sequence != self._seq + 1:
                            gap = True
                            break
                        self._apply(entry)
                        self._seq = entry.sequence
                        applied += 1
                if gap:
                    break
            if gap:
                # rotation raced the scan: rebuild deterministically
                self._replay()
        return applied

    def gain_primacy_from_standby(self) -> None:
        """Promotion for an already-tailing standby: finish the tail and
        open the write log — no state reset, so failover is O(tail), not
        O(snapshot) (reference: the standby's caught-up state serves)."""
        with self._lock:
            self.catch_up()
            self._open_log()
            self._primary = True

    def checkpoint_standby(self) -> None:
        """Checkpoint from standby state (no write log held). Shortens the
        primary-promotion replay (reference: checkpoint on standby)."""
        with self._lock:
            if self._primary:
                return
            self._checkpoint_locked()

    # -- backup / restore (reference: BackupLeaderRole.java:62 +
    # initFromBackup AlluxioMasterProcess.java:173-190) --------------------
    def write_backup(self, backup_dir: str) -> str:
        """Full metadata backup = one checkpoint-format file; returns its
        path. Safe on a live primary (state snapshot under the lock)."""
        os.makedirs(backup_dir, exist_ok=True)
        with self._lock:
            snap = {
                "sequence": self._seq,
                "components": {name: comp.snapshot()
                               for name, comp in self._components.items()},
            }
        stamp = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(backup_dir,
                            f"atpu-backup-{stamp}-{snap['sequence']}.bak")
        n = 1
        while os.path.exists(path):  # same second + sequence: uniquify
            path = os.path.join(
                backup_dir,
                f"atpu-backup-{stamp}-{snap['sequence']}.{n}.bak")
            n += 1
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(snap, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
        return path

    def init_from_backup(self, backup_path: str) -> bool:
        """Seed an EMPTY journal from a backup file: the backup becomes the
        initial checkpoint so the normal replay path restores it. Returns
        False (and does nothing) when the journal already has state."""
        self.start()
        if self._latest_checkpoint() is not None or any(
                self._list_segments()):
            return False
        with open(backup_path, "rb") as f:
            snap = msgpack.unpackb(f.read(), raw=False,
                                   strict_map_key=False)
        seq = int(snap["sequence"])
        tmp = os.path.join(self._ckpt_dir, ".tmp.restore")
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(snap, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, os.path.join(self._ckpt_dir, f"{seq:016x}.ckpt"))
        return True

    # -- introspection ------------------------------------------------------
    @property
    def sequence(self) -> int:
        with self._lock:
            return self._seq

    @property
    def last_checkpoint_sequence(self) -> int:
        with self._lock:
            return self._last_checkpoint_seq


def create_journal_system(journal_type: str, folder: str, **kwargs) -> JournalSystem:
    """Factory keyed by ``atpu.master.journal.type``."""
    jt = journal_type.upper()
    if jt == "NOOP":
        return NoopJournalSystem()
    if jt in ("LOCAL", "UFS"):
        return LocalJournalSystem(folder, **kwargs)
    if jt == "EMBEDDED":
        try:
            from alluxio_tpu.journal.raft import EmbeddedJournalSystem
        except ImportError as e:
            raise ValueError(
                "journal type EMBEDDED requires the replicated journal "
                "module (alluxio_tpu.journal.raft); use LOCAL or UFS") from e
        return EmbeddedJournalSystem(folder, **kwargs)
    raise ValueError(f"unknown journal type {journal_type}")
