"""High availability: primary election, standby tailing, failover.

Re-designs of the reference HA stack:
- ``PrimarySelector`` SPI (``master/{PrimarySelector,
  ZkPrimarySelector}.java`` + ``journal/raft/RaftPrimarySelector.java``):
  here the in-tree implementation is a **file-lock selector** — an OS
  ``flock`` on ``<journal>/primary.lock`` IS the fence: a deposed primary
  cannot re-acquire while the new one lives, and a crashed one releases
  automatically. Suited to masters sharing a journal directory (same host
  or POSIX-locking shared fs); multi-host quorum = EMBEDDED journal.
- Standby tailing (``UfsJournalCheckpointThread.java:47``): a standby
  replays new segments on an interval and takes periodic checkpoints so
  failover replay is short.
- ``FaultTolerantMasterProcess`` (``master/FaultTolerantAlluxioMaster
  Process.java``): boot as standby, serve when primacy arrives.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, List, Optional

from alluxio_tpu.journal.system import LocalJournalSystem
from alluxio_tpu.journal.format import JournalEntry

LOG = logging.getLogger(__name__)


class PrimarySelector:
    """Election SPI (reference: PrimarySelector)."""

    def start(self) -> None: ...

    def stop(self) -> None: ...

    def try_acquire(self) -> bool:
        raise NotImplementedError

    def is_primary(self) -> bool:
        raise NotImplementedError

    def release(self) -> None: ...

    def wait_for_primacy(self, timeout_s: Optional[float] = None,
                         poll_s: float = 0.1) -> bool:
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        while True:
            if self.try_acquire():
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)


class AlwaysPrimarySelector(PrimarySelector):
    """Single-master deployments (no HA)."""

    def try_acquire(self) -> bool:
        return True

    def is_primary(self) -> bool:
        return True


class FileLockPrimarySelector(PrimarySelector):
    """flock-based election over the shared journal directory. The held
    lock doubles as the write fence (reference: the UFS journal fences via
    log rotation; Raft via terms)."""

    LOCK_FILE = "primary.lock"

    def __init__(self, journal_folder: str) -> None:
        self._path = os.path.join(journal_folder, self.LOCK_FILE)
        self._fd: Optional[int] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        os.makedirs(os.path.dirname(self._path), exist_ok=True)

    def try_acquire(self) -> bool:
        import fcntl

        with self._lock:
            if self._fd is not None:
                return True
            fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(fd)
                return False
            os.ftruncate(fd, 0)
            os.write(fd, str(os.getpid()).encode())
            self._fd = fd
            return True

    def is_primary(self) -> bool:
        with self._lock:
            return self._fd is not None

    def release(self) -> None:
        import fcntl

        with self._lock:
            if self._fd is None:
                return
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None

    stop = release


class JournalTailer:
    """Standby-side catch-up: periodically applies new journal entries and
    takes checkpoints so a later failover replays only a short tail
    (reference: UfsJournalCheckpointThread)."""

    def __init__(self, journal: LocalJournalSystem, *,
                 interval_s: float = 1.0,
                 checkpoint_period_entries: int = 10_000) -> None:
        self._journal = journal
        self._interval = interval_s
        self._ckpt_period = checkpoint_period_entries
        self._applied_at_ckpt = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        self._journal.start()
        self._thread = threading.Thread(target=self._run,
                                        name="journal-tailer", daemon=True)
        self._stop.clear()
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                applied = self._journal.catch_up()
                if applied and self._journal.sequence - \
                        self._applied_at_ckpt >= self._ckpt_period:
                    self._journal.checkpoint_standby()
                    self._applied_at_ckpt = self._journal.sequence
            except Exception:  # noqa: BLE001 - keep tailing
                LOG.exception("standby journal tail failed")
            self._stop.wait(self._interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
