"""High availability: primary election, standby tailing, failover.

Re-designs of the reference HA stack:
- ``PrimarySelector`` SPI (``master/{PrimarySelector,
  ZkPrimarySelector}.java`` + ``journal/raft/RaftPrimarySelector.java``):
  here the in-tree implementation is a **file-lock selector** — an OS
  ``flock`` on ``<journal>/primary.lock`` IS the fence: a deposed primary
  cannot re-acquire while the new one lives, and a crashed one releases
  automatically. Suited to masters sharing a journal directory (same host
  or POSIX-locking shared fs); multi-host quorum = EMBEDDED journal.
- Standby tailing (``UfsJournalCheckpointThread.java:47``): a standby
  replays new segments on an interval and takes periodic checkpoints so
  failover replay is short.
- ``FaultTolerantMasterProcess`` (``master/FaultTolerantAlluxioMaster
  Process.java``): boot as standby, serve when primacy arrives.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from alluxio_tpu.journal.system import LocalJournalSystem
from alluxio_tpu.journal.format import JournalEntry

LOG = logging.getLogger(__name__)


class MasterRegistry:
    """Shared-journal master presence registry: every HA master
    periodically publishes one JSON row (client address, role, term,
    applied sequence) under ``<journal>/masters/``, and anyone sharing
    the folder can list the quorum — the data behind
    ``fsadmin report masters`` and the ``master-quorum-degraded`` health
    rule for the file-lock HA flavor (the EMBEDDED flavor additionally
    merges live Raft quorum state; see ``MasterProcess.masters_report``).

    Rows are atomically replaced (tmp + rename) and carry a wall-clock
    stamp; readers derive ``last_contact_s`` from it.  A stopped master
    removes its row; a crashed one ages out visibly instead."""

    DIR = "masters"

    def __init__(self, journal_folder: str) -> None:
        self._dir = os.path.join(journal_folder, self.DIR)

    def _path_for(self, address: str) -> str:
        return os.path.join(self._dir,
                            address.replace(":", "_").replace("/", "_")
                            + ".json")

    def publish(self, address: str, *, role: str, sequence: int,
                term: int = 0) -> None:
        os.makedirs(self._dir, exist_ok=True)
        row = {"address": address, "role": role, "sequence": int(sequence),
               "term": int(term), "at": time.time()}
        # pid alone is not unique enough: the publish heartbeat and a
        # get_masters RPC (masters_report refreshes our own row) publish
        # concurrently from one process, and a shared tmp name would let
        # one thread os.replace the file out from under the other
        tmp = self._path_for(address) + \
            f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(row, f)
        os.replace(tmp, self._path_for(address))

    def withdraw(self, address: str) -> None:
        try:
            os.remove(self._path_for(address))
        except OSError:
            pass

    def list(self) -> List[Dict]:
        """All published rows, stamped with ``last_contact_s`` age."""
        if not os.path.isdir(self._dir):
            return []
        out: List[Dict] = []
        now = time.time()
        for name in sorted(os.listdir(self._dir)):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self._dir, name),
                          encoding="utf-8") as f:
                    row = json.load(f)
            except (OSError, ValueError):
                continue  # torn write / concurrent replace: skip this tick
            row["last_contact_s"] = max(0.0, now - float(row.pop("at", now)))
            out.append(row)
        return out


class PrimarySelector:
    """Election SPI (reference: PrimarySelector)."""

    def start(self) -> None: ...

    def stop(self) -> None: ...

    def try_acquire(self) -> bool:
        raise NotImplementedError

    def is_primary(self) -> bool:
        raise NotImplementedError

    def release(self) -> None: ...

    def wait_for_primacy(self, timeout_s: Optional[float] = None,
                         poll_s: float = 0.1) -> bool:
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        while True:
            if self.try_acquire():
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)


class AlwaysPrimarySelector(PrimarySelector):
    """Single-master deployments (no HA)."""

    def try_acquire(self) -> bool:
        return True

    def is_primary(self) -> bool:
        return True


class FileLockPrimarySelector(PrimarySelector):
    """flock-based election over the shared journal directory. The held
    lock doubles as the write fence (reference: the UFS journal fences via
    log rotation; Raft via terms)."""

    LOCK_FILE = "primary.lock"

    def __init__(self, journal_folder: str) -> None:
        self._path = os.path.join(journal_folder, self.LOCK_FILE)
        self._fd: Optional[int] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        os.makedirs(os.path.dirname(self._path), exist_ok=True)

    def try_acquire(self) -> bool:
        import fcntl

        with self._lock:
            if self._fd is not None:
                return True
            fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(fd)
                return False
            os.ftruncate(fd, 0)
            os.write(fd, str(os.getpid()).encode())
            self._fd = fd
            return True

    def is_primary(self) -> bool:
        with self._lock:
            return self._fd is not None

    def release(self) -> None:
        import fcntl

        with self._lock:
            if self._fd is None:
                return
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None

    stop = release


class JournalTailer:
    """Standby-side catch-up: periodically applies new journal entries and
    takes checkpoints so a later failover replays only a short tail
    (reference: UfsJournalCheckpointThread)."""

    def __init__(self, journal: LocalJournalSystem, *,
                 interval_s: float = 1.0,
                 checkpoint_period_entries: int = 10_000,
                 node: str = "",
                 on_tick: Optional[Callable[[], None]] = None,
                 apply_exclusion: Optional[Callable] = None) -> None:
        """``node``: identity matched against the chaos injector's
        tailer-freeze scope; ``on_tick`` runs after every tail attempt
        (the FT master publishes its registry row on it).
        ``apply_exclusion``: context-manager factory held around each
        catch-up batch — a standby that serves reads installs the inode
        tree's write lock here, excluding served readers from torn
        mid-apply states (the apply path holds no inode-path locks).
        Acquired OUTSIDE the journal lock, preserving the canonical
        tree-lock -> journal-lock order (docs/ha.md)."""
        self._journal = journal
        self._interval = interval_s
        self._ckpt_period = checkpoint_period_entries
        self._applied_at_ckpt = 0
        self._node = node
        self._on_tick = on_tick
        self._apply_exclusion = apply_exclusion
        #: monotonic stamp of the last tick that APPLIED entries (or
        #: found none pending) — `fsadmin report masters` surfaces the
        #: age as tailer lag; a frozen tailer's lag visibly grows
        self.last_caught_up = time.monotonic()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        self._journal.start()
        self._thread = threading.Thread(target=self._run,
                                        name="journal-tailer", daemon=True)
        self._stop.clear()
        self._thread.start()

    def _run(self) -> None:
        from alluxio_tpu.utils import faults

        while not self._stop.is_set():
            try:
                if faults.armed() and \
                        faults.injector().tailer_frozen(self._node):
                    pass  # chaos: standby falls behind, lag grows
                else:
                    excl = self._apply_exclusion
                    if excl is None:
                        applied = self._journal.catch_up()
                    else:
                        with excl():
                            applied = self._journal.catch_up()
                    self.last_caught_up = time.monotonic()
                    if applied and self._journal.sequence - \
                            self._applied_at_ckpt >= self._ckpt_period:
                        self._journal.checkpoint_standby()
                        self._applied_at_ckpt = self._journal.sequence
            except Exception:  # noqa: BLE001 - keep tailing
                LOG.exception("standby journal tail failed")
            if self._on_tick is not None:
                try:
                    self._on_tick()
                except Exception:  # noqa: BLE001 - publish is best-effort
                    LOG.debug("tailer on_tick failed", exc_info=True)
            self._stop.wait(self._interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
