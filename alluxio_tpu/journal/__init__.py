"""Journaled metadata durability (reference: ``core/server/common/.../journal``)."""

from alluxio_tpu.journal.format import EntryType, JournalEntry, Journaled  # noqa: F401
from alluxio_tpu.journal.system import (  # noqa: F401
    JournalContext, JournalSystem, LocalJournalSystem, NoopJournalSystem,
    create_journal_system,
)
