"""Embedded replicated journal: Raft consensus over the msgpack-RPC plane.

Re-design of the reference's embedded journal
(``core/server/common/src/main/java/alluxio/master/journal/raft/
RaftJournalSystem.java:150``, ``JournalStateMachine.java:83``,
``SnapshotReplicationManager.java``, ``RaftPrimarySelector.java``): there
the journal is an Apache Ratis state machine — every metadata mutation is
a Raft log command, leader election IS primary election, and snapshots
ship leader->standby. Here the same contract is implemented directly on
the framework's own transport (``rpc/core.py``) instead of an external
consensus library:

- **Log replication**: each group-commit batch of ``JournalEntry``s is one
  Raft log record. ``write_and_flush`` blocks until the record is
  committed on a quorum AND applied locally, so an acknowledged mutation
  survives any minority of failures — the same durability the reference
  gets from Ratis' ``appendEntries`` round.
- **Election as primacy**: masters boot as followers; the elected leader
  is the primary. ``RaftPrimarySelector`` adapts the node to the
  ``PrimarySelector`` SPI so ``FaultTolerantMasterProcess`` needs no
  special-casing. Terms fence deposed leaders (a stale primary's appends
  are rejected by quorum, its writes raise, and it steps down).
- **Hot standbys**: followers apply committed entries continuously — the
  standby-tailing behavior of ``UfsJournalCheckpointThread`` falls out of
  the consensus protocol itself; promotion is O(election), not O(replay).
- **Snapshot install**: a follower too far behind the leader's truncated
  log receives a full component snapshot (reference:
  ``SnapshotReplicationManager``); nodes also snapshot locally on an
  entry-count period to bound their own logs.

TPU-deployment note: quorum members are metadata masters on TPU-host VMs;
this traffic rides DCN (it is control-plane, never ICI — SURVEY §5.8 maps
Raft to "keep Raft (etcd-style)" on the host network).
"""

from __future__ import annotations

import contextlib
import logging
import os
import random
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import msgpack

from alluxio_tpu.journal.format import JournalEntry
from alluxio_tpu.journal.ha import PrimarySelector
from alluxio_tpu.journal.system import JournalSystem
from alluxio_tpu.utils.exceptions import JournalClosedError

LOG = logging.getLogger(__name__)

RAFT_SERVICE = "raft_journal"
_FRAME = struct.Struct("<II")  # length, crc32

FOLLOWER = "FOLLOWER"
CANDIDATE = "CANDIDATE"
LEADER = "LEADER"


class RaftRecord:
    """One Raft log record = one group-commit batch of journal entries."""

    __slots__ = ("term", "index", "entries")

    def __init__(self, term: int, index: int,
                 entries: List[JournalEntry]) -> None:
        self.term = term
        self.index = index
        self.entries = entries

    def to_wire(self) -> list:
        return [self.term, self.index,
                [[e.sequence, e.type, e.payload] for e in self.entries]]

    @staticmethod
    def from_wire(w: list) -> "RaftRecord":
        return RaftRecord(w[0], w[1],
                          [JournalEntry(s, t, p) for s, t, p in w[2]])


class RaftLog:
    """Durable append-only Raft log + persistent (term, voted_for) meta.

    Records are framed ``[u32 len][u32 crc][msgpack]`` (same torn-tail
    discipline as ``journal/format.py``); byte offsets are tracked so a
    conflict truncation (Raft §5.3) is an ``ftruncate``. The log lives in
    memory too — metadata batches between snapshots are small, and the
    snapshot period bounds growth.
    """

    def __init__(self, folder: str) -> None:
        self._folder = folder
        self._log_path = os.path.join(folder, "log.bin")
        self._meta_path = os.path.join(folder, "meta.bin")
        self.records: List[RaftRecord] = []
        self._offsets: List[int] = []  # byte offset of each record
        self.start_index = 1  # index of records[0] (moves up on truncation)
        self.term = 0
        self.voted_for: Optional[str] = None
        self._file = None
        # logical end-of-file: tracked explicitly because a buffered
        # 'ab' handle's tell() goes stale after ftruncate — offsets
        # derived from it would point past EOF and corrupt later
        # truncations (advisor r2 finding, high)
        self._end = 0

    # -- persistence ---------------------------------------------------------
    def open(self) -> None:
        os.makedirs(self._folder, exist_ok=True)
        if os.path.exists(self._meta_path):
            with open(self._meta_path, "rb") as f:
                meta = msgpack.unpackb(f.read(), raw=False)
            self.term = meta["term"]
            self.voted_for = meta.get("voted_for")
            self.start_index = meta.get("start_index", 1)
        dirty = False
        if os.path.exists(self._log_path):
            off = 0
            from alluxio_tpu.journal.format import iter_frames, map_or_read

            with open(self._log_path, "rb") as f:
                data = map_or_read(f)
                for body_off, length in iter_frames(data):
                    try:
                        rec = RaftRecord.from_wire(msgpack.unpackb(
                            data[body_off:body_off + length], raw=False))
                    except Exception:  # noqa: BLE001 crc-coincident junk
                        break  # treat as torn tail, same as format.py
                    self.records.append(rec)
                    self._offsets.append(body_off - _FRAME.size)
                    off = body_off + length
                if hasattr(data, "close"):
                    data.close()
            # a torn tail MUST be truncated away before appending: 'ab'
            # positions past the garbage, and records written after it
            # would be unreadable on the next restart (scan stops at the
            # torn frame) — silently losing acknowledged entries
            dirty = off != os.path.getsize(self._log_path)
            # drop any pre-start_index remnants (post-snapshot-truncation
            # crash window)
            while self.records and self.records[0].index < self.start_index:
                self.records.pop(0)
                self._offsets.pop(0)
                dirty = True
        if dirty:
            self._rewrite()
        else:
            self._end = off if os.path.exists(self._log_path) else 0
            self._file = open(self._log_path, "ab")

    def save_meta(self) -> None:
        tmp = self._meta_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb({"term": self.term,
                                   "voted_for": self.voted_for,
                                   "start_index": self.start_index},
                                  use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path)

    def _rewrite(self) -> None:
        """Rewrite the whole log file from memory (truncation paths)."""
        if self._file is not None:
            self._file.close()
        tmp = self._log_path + ".tmp"
        with open(tmp, "wb") as f:
            self._offsets = []
            off = 0
            for rec in self.records:
                body = msgpack.packb(rec.to_wire(), use_bin_type=True)
                f.write(_FRAME.pack(len(body), zlib.crc32(body)) + body)
                self._offsets.append(off)
                off += _FRAME.size + len(body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._log_path)
        self._end = off
        self._file = open(self._log_path, "ab")

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    # -- accessors -----------------------------------------------------------
    @property
    def last_index(self) -> int:
        return self.start_index + len(self.records) - 1 if self.records \
            else self.start_index - 1

    def term_at(self, index: int, *, snapshot_term: int = 0) -> int:
        """Term of the record at ``index``; snapshot_term covers the
        truncated prefix boundary."""
        if index == 0:
            return 0
        i = index - self.start_index
        if i < 0:
            return snapshot_term
        if i >= len(self.records):
            return -1
        return self.records[i].term

    def get(self, index: int) -> Optional[RaftRecord]:
        i = index - self.start_index
        if 0 <= i < len(self.records):
            return self.records[i]
        return None

    def slice_from(self, index: int, limit: int = 64) -> List[RaftRecord]:
        i = max(0, index - self.start_index)
        return self.records[i:i + limit]

    # -- mutation ------------------------------------------------------------
    def append(self, rec: RaftRecord, *, fsync: bool = True) -> None:
        body = msgpack.packb(rec.to_wire(), use_bin_type=True)
        self._offsets.append(self._end)
        self._file.write(_FRAME.pack(len(body), zlib.crc32(body)) + body)
        self._end += _FRAME.size + len(body)
        if fsync:
            self._file.flush()
            os.fsync(self._file.fileno())
        self.records.append(rec)

    def flush(self) -> None:
        self._file.flush()
        os.fsync(self._file.fileno())

    def truncate_from(self, index: int) -> None:
        """Drop records >= index (follower conflict resolution)."""
        i = index - self.start_index
        if i < 0 or i >= len(self.records):
            if i < 0:
                self.records = []
                self._offsets = []
                self._rewrite()
            return
        off = self._offsets[i]
        self.records = self.records[:i]
        self._offsets = self._offsets[:i]
        self._file.flush()
        self._file.truncate(off)
        os.fsync(self._file.fileno())
        # reopen so the 'ab' handle's position reflects the new EOF
        # (a buffered append handle does not follow ftruncate)
        self._file.close()
        self._file = open(self._log_path, "ab")
        self._end = off

    def truncate_prefix(self, upto_index: int) -> None:
        """Drop records <= upto_index (after a snapshot covers them)."""
        n = upto_index - self.start_index + 1
        if n <= 0:
            return
        self.records = self.records[n:]
        self.start_index = upto_index + 1
        self.save_meta()
        self._rewrite()


class RaftNode:
    """One quorum member: consensus state + election + replication.

    Single coarse lock guards all Raft state; replication fan-out and the
    apply loop run on their own threads and re-take it per step. Commit
    advancement wakes ``commit_cv`` waiters (the write path) and the apply
    thread.
    """

    def __init__(self, node_id: str, peers: Dict[str, str], folder: str, *,
                 election_timeout_ms: Tuple[int, int] = (300, 600),
                 heartbeat_interval_ms: int = 100,
                 apply_fn=None, snapshot_fn=None, restore_fn=None,
                 snapshot_period_entries: int = 100_000) -> None:
        """``peers``: node_id -> address for ALL members (incl. self).
        ``apply_fn(entry)`` applies one committed JournalEntry;
        ``snapshot_fn() -> dict`` / ``restore_fn(dict)`` capture/install
        component state for snapshot truncation + install."""
        self.node_id = node_id
        self.peers = {nid: addr for nid, addr in peers.items()
                      if nid != node_id}
        self.quorum_size = (len(peers) // 2) + 1
        #: deterministic election-timeout stagger by member rank: after a
        #: leader death every survivor's randomized timeout starts from
        #: the same instant, and a scheduler stall (GIL pause, CI noise)
        #: can land two draws inside one RPC round trip — a split vote
        #: that costs a full extra election round.  Offsetting each
        #: member by rank * 15% of the band makes the lowest-ranked
        #: survivor usually campaign first and win clean, while the
        #: random draw still decorrelates equal-rank restarts.
        self._rank = sorted(peers).index(node_id) if node_id in peers else 0
        self.log = RaftLog(os.path.join(folder, "raft", node_id))
        self._folder = folder
        self._apply_fn = apply_fn or (lambda e: None)
        self._snapshot_fn = snapshot_fn or (lambda: {})
        self._restore_fn = restore_fn or (lambda s: None)
        self._snapshot_period = snapshot_period_entries
        #: optional context-manager factory held around each apply-loop
        #: batch (follower replication; leader barrier/orphan records).
        #: A standby that serves reads installs the inode tree's write
        #: lock: the apply loop holds no inode-path locks, so a served
        #: read could otherwise observe a torn multi-step apply.
        #: Acquired BEFORE _state_lock/lock — the same tree-first order
        #: the propose path uses — so no lock cycle forms.  The
        #: propose-wait apply path stays unwrapped: there the proposing
        #: RPC thread already holds the path's write locks (and holds
        #: the tree READ lock, which this write lock must not wait on
        #: from the same thread).
        self.apply_exclusion = None

        self.state = FOLLOWER
        self.leader_id: Optional[str] = None
        self._transferring = False  # §3.10: no proposals mid-handover
        self.commit_index = 0
        self.applied_index = 0
        self.applied_seq = 0
        self._entries_since_snapshot = 0
        self.snapshot_term = 0  # term at log.start_index - 1
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}

        self.lock = threading.RLock()
        self.commit_cv = threading.Condition(self.lock)
        self.apply_cv = threading.Condition(self.lock)
        # serializes snapshot FILE IO (periodic + admin checkpoint +
        # install) without stalling consensus under self.lock
        self._snap_io_lock = threading.Lock()
        # serializes component-state mutation (apply/restore) against
        # snapshot capture, so _snapshot_fn() — a full serialization of
        # every component — never runs under the consensus lock where it
        # would stall votes/appends past the election timeout (advisor
        # r2 finding). Lock order: _snap_io_lock -> _state_lock -> lock.
        self._state_lock = threading.Lock()
        #: index -> RaftRecord for batches proposed by THIS node's callers.
        #: The proposing thread applies its own batch once committed and
        #: in-order (it holds the owning component's write lock — the same
        #: thread-applies contract as the local journal; the apply loop
        #: handles only non-local records: follower replication, barriers,
        #: and orphans whose proposer gave up).
        self._local_batches: Dict[int, RaftRecord] = {}
        self._election_timeout_ms = election_timeout_ms
        self._heartbeat_ms = heartbeat_interval_ms
        self._deadline = 0.0
        #: when we last accepted a live leader's append (pre-vote gate)
        self._last_leader_contact = time.monotonic()
        self._reset_election_deadline()
        self._stopped = False
        self._threads: List[threading.Thread] = []
        self._peer_wakeups: Dict[str, threading.Event] = {
            nid: threading.Event() for nid in self.peers}
        #: injectable peer transport (tests install drop/partition
        #: shims here; the MultiProcessCluster exercises real
        #: network failures, this seam covers asymmetric partitions)
        self.transport = _peer_call
        #: monotonic stamp of each peer's last successful RPC response —
        #: quorum_info serves it as last_contact_s, and the HA health
        #: sampling counts "live" members from it
        self.peer_contact: Dict[str, float] = {}
        self._step_down_cbs: List = []

    def _call_peer(self, addr: str, method: str, req: dict,
                   timeout: float):
        """Peer RPC via the injectable transport, behind the chaos
        injector's partition gate (outbound-only dropping cuts the link
        both ways — responses ride the same call)."""
        from alluxio_tpu.utils import faults

        if faults.armed() and \
                faults.injector().link_blocked(self.node_id, addr):
            raise ConnectionError(
                f"injected partition {self.node_id} -/- {addr}")
        return self.transport(addr, method, req, timeout=timeout)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self.log.open()
        self._load_snapshot()
        # replay the durable log into local state up to... nothing is
        # known-committed yet; entries apply as commit advances (either by
        # winning an election or by hearing a leader's commit index).
        self._stopped = False
        t = threading.Thread(target=self._timer_loop,
                             name=f"raft-timer-{self.node_id}", daemon=True)
        t.start()
        self._threads.append(t)
        a = threading.Thread(target=self._apply_loop,
                             name=f"raft-apply-{self.node_id}", daemon=True)
        a.start()
        self._threads.append(a)
        for nid in self.peers:
            s = threading.Thread(target=self._peer_loop, args=(nid,),
                                 name=f"raft-peer-{self.node_id}-{nid}",
                                 daemon=True)
            s.start()
            self._threads.append(s)

    def stop(self) -> None:
        with self.lock:
            self._stopped = True
            self.commit_cv.notify_all()
            self.apply_cv.notify_all()
        for ev in self._peer_wakeups.values():
            ev.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()
        self.log.close()

    def on_step_down(self, cb) -> None:
        self._step_down_cbs.append(cb)

    # -- snapshots ------------------------------------------------------------
    def _snap_dir(self) -> str:
        return os.path.join(self._folder, "raft", self.node_id, "snapshots")

    def _latest_snapshot_path(self) -> Optional[str]:
        d = self._snap_dir()
        if not os.path.isdir(d):
            return None
        snaps = [f for f in os.listdir(d) if f.endswith(".snap")]
        if not snaps:
            return None
        return os.path.join(d, max(
            snaps, key=lambda f: int(f.split("_")[1].split(".")[0], 16)))

    def _load_snapshot(self) -> None:
        p = self._latest_snapshot_path()
        if p is None:
            return
        with open(p, "rb") as f:
            snap = msgpack.unpackb(f.read(), raw=False, strict_map_key=False)
        self._restore_fn(snap["components"])
        self.snapshot_term = snap["term"]
        self.commit_index = max(self.commit_index, snap["index"])
        self.applied_index = snap["index"]
        self.applied_seq = snap["seq"]
        if self.log.start_index <= snap["index"]:
            self.log.truncate_prefix(snap["index"])

    def take_snapshot(self) -> None:
        """Snapshot local applied state; truncate the covered log prefix.
        File IO happens outside the consensus lock (under _snap_io_lock,
        which also serializes concurrent periodic/admin/install callers)."""
        with self._snap_io_lock:
            with self._state_lock:
                # _state_lock freezes component state (appliers take it
                # before mutating); consensus proceeds under self.lock
                # while the potentially-large serialization runs
                with self.lock:
                    index, seq = self.applied_index, self.applied_seq
                    term = self.log.term_at(
                        index, snapshot_term=self.snapshot_term)
                    if index == 0:
                        return
                comps = self._snapshot_fn()
            d = self._snap_dir()
            os.makedirs(d, exist_ok=True)
            blob = msgpack.packb({"term": term, "index": index, "seq": seq,
                                  "components": comps}, use_bin_type=True)
            self._write_snapshot_file(d, term, index, blob)
            with self.lock:
                self.snapshot_term = term
                self._entries_since_snapshot = 0
                if self.log.start_index <= index:
                    self.log.truncate_prefix(index)
            # GC older snapshots
            keep = self._latest_snapshot_path()
            for f in os.listdir(d):
                if f.endswith(".snap") and os.path.join(d, f) != keep:
                    try:
                        os.remove(os.path.join(d, f))
                    except OSError:
                        pass

    def _write_snapshot_file(self, d: str, term: int, index: int,
                             blob: bytes) -> None:
        """Caller holds _snap_io_lock (unique tmp per thread regardless)."""
        tmp = os.path.join(d, f".tmp.{os.getpid()}.{threading.get_ident()}")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(d, f"{term:08x}_{index:016x}.snap"))

    # -- elections -----------------------------------------------------------
    def _reset_election_deadline(self) -> None:
        lo, hi = self._election_timeout_ms
        stagger = self._rank * 0.15 * (hi - lo)
        self._deadline = time.monotonic() + \
            (random.uniform(lo, hi) + stagger) / 1000.0

    def _timer_loop(self) -> None:
        while True:
            with self.lock:
                if self._stopped:
                    return
                state = self.state
                expired = time.monotonic() >= self._deadline
            if state == LEADER:
                # heartbeat tick: nudge idle peer senders
                for ev in self._peer_wakeups.values():
                    ev.set()
                time.sleep(self._heartbeat_ms / 1000.0)
            else:
                if expired:
                    from alluxio_tpu.utils import faults

                    if faults.armed() and faults.injector() \
                            .election_frozen(self.node_id):
                        # chaos: sit this one out (still votes) — the
                        # drill decides who may win the next election
                        with self.lock:
                            self._reset_election_deadline()
                    else:
                        self._start_election()
                time.sleep(0.02)

    def _start_election(self, *, force: bool = False) -> None:
        """``force`` skips the pre-vote round — used by leadership
        transfer (Raft §3.10 TimeoutNow): the target must be able to
        depose a HEALTHY leader, which pre-vote exists to prevent."""
        if not force and not self._pre_vote_wins():
            # a live leader is still heartbeating a majority (we're the
            # partitioned/rejoining one): do NOT bump the term — pre-vote
            # (Raft §9.6) keeps a rejoining node from deposing a healthy
            # leader and failing its in-flight commits
            with self.lock:
                self._reset_election_deadline()
            return
        with self.lock:
            if self._stopped or self.state == LEADER:
                return
            self.state = CANDIDATE
            self.log.term += 1
            term = self.log.term
            self.log.voted_for = self.node_id
            self.log.save_meta()
            self.leader_id = None
            self._reset_election_deadline()
            last_idx = self.log.last_index
            last_term = self.log.term_at(
                last_idx, snapshot_term=self.snapshot_term)
        votes = [1]  # self-vote
        done = threading.Event()

        def ask(addr):
            try:
                resp = self._call_peer(addr, "request_vote", {
                    "term": term, "candidate_id": self.node_id,
                    "last_log_index": last_idx, "last_log_term": last_term,
                    "force": force,
                }, timeout=self._election_timeout_ms[0] / 1000.0)
            except Exception:  # noqa: BLE001 peer down: no vote
                return
            with self.lock:
                if resp["term"] > self.log.term:
                    self._become_follower(resp["term"], None)
                    done.set()
                    return
                if resp.get("granted") and self.state == CANDIDATE \
                        and self.log.term == term:
                    votes[0] += 1
                    if votes[0] >= self.quorum_size:
                        self._become_leader()
                        done.set()

        threads = [threading.Thread(target=ask, args=(a,), daemon=True)
                   for a in self.peers.values()]
        for t in threads:
            t.start()
        if not self.peers:  # single-node quorum
            with self.lock:
                self._become_leader()
        done.wait(timeout=self._election_timeout_ms[1] / 1000.0)

    def _pre_vote_wins(self) -> bool:
        """Pre-vote round (Raft §9.6): ask peers whether they would grant
        a vote at term+1 WITHOUT bumping terms. A peer refuses while its
        own election deadline is fresh (it hears a live leader). True
        when a majority would vote — only then is a real (disruptive)
        election worth starting."""
        with self.lock:
            if self._stopped or self.state == LEADER:
                return False
            term = self.log.term + 1
            last_idx = self.log.last_index
            last_term = self.log.term_at(
                last_idx, snapshot_term=self.snapshot_term)
        if not self.peers:
            return True
        votes = [1]
        decided = threading.Event()

        def ask(addr):
            try:
                resp = self._call_peer(addr, "request_vote", {
                    "term": term, "candidate_id": self.node_id,
                    "last_log_index": last_idx, "last_log_term": last_term,
                    "pre_vote": True,
                }, timeout=self._election_timeout_ms[0] / 1000.0)
            except Exception:  # noqa: BLE001 unreachable: no pre-vote
                return
            if resp.get("granted"):
                with self.lock:
                    votes[0] += 1
                    if votes[0] >= self.quorum_size:
                        decided.set()

        threads = [threading.Thread(target=ask, args=(a,), daemon=True)
                   for a in self.peers.values()]
        for t in threads:
            t.start()
        decided.wait(timeout=self._election_timeout_ms[0] / 1000.0)
        with self.lock:
            return votes[0] >= self.quorum_size

    def _become_leader(self) -> None:
        """Caller holds the lock. Appends a no-op barrier record in the new
        term (Raft's leader-completeness read barrier: once it commits, all
        previous terms' entries are committed and applied here)."""
        if self.state == LEADER:
            return
        self.state = LEADER
        self.leader_id = self.node_id
        for nid in self.peers:
            self.next_index[nid] = self.log.last_index + 1
            self.match_index[nid] = 0
        barrier = RaftRecord(self.log.term, self.log.last_index + 1, [])
        self.log.append(barrier)
        self._advance_commit()
        LOG.info("raft %s: leader for term %d", self.node_id, self.log.term)
        for ev in self._peer_wakeups.values():
            ev.set()

    def _become_follower(self, term: int, leader: Optional[str]) -> None:
        """Caller holds the lock."""
        was_leader = self.state == LEADER
        if term > self.log.term:
            self.log.term = term
            self.log.voted_for = None
            self.log.save_meta()
        self.state = FOLLOWER
        if leader is not None:
            self.leader_id = leader
        elif was_leader:
            # stepping down with no known successor: a stale self-
            # pointing leader_id would read as "someone else won" to
            # transfer_leadership and misdirect client redirects
            self.leader_id = None
        self._reset_election_deadline()
        if was_leader:
            LOG.warning("raft %s: stepped down in term %d",
                        self.node_id, term)
            self.commit_cv.notify_all()
            for cb in self._step_down_cbs:
                try:
                    cb()
                except Exception:  # noqa: BLE001
                    LOG.exception("step-down callback failed")

    # -- RPC handlers (peer-facing) ------------------------------------------
    def handle_request_vote(self, req: dict) -> dict:
        if req.get("pre_vote"):
            return self._handle_pre_vote(req)
        with self.lock:
            if not req.get("force") and req["term"] > self.log.term:
                # Leader stickiness for REAL votes too (Raft §4.2.3):
                # pre-vote gates a candidate on ITS view, but a candidate
                # that passed pre-vote just before a leader emerged can
                # still depose the fresh leader and churn terms (observed
                # as back-to-back step-downs after a failover).  While we
                # hear a live leader — or ARE one — ignore the candidate
                # without adopting its term; a legitimately newer leader
                # still flips us via AppendEntries, and leadership
                # transfer (TimeoutNow) bypasses with ``force``.
                lo_s = self._election_timeout_ms[0] / 1000.0
                leader_fresh = self.state == LEADER or \
                    (time.monotonic() - self._last_leader_contact) < lo_s
                if leader_fresh:
                    return {"term": self.log.term, "granted": False}
            if req["term"] > self.log.term:
                self._become_follower(req["term"], None)
            granted = False
            if req["term"] == self.log.term and \
                    self.log.voted_for in (None, req["candidate_id"]):
                last_idx = self.log.last_index
                last_term = self.log.term_at(
                    last_idx, snapshot_term=self.snapshot_term)
                # candidate log must be at least as up-to-date (§5.4.1)
                if (req["last_log_term"], req["last_log_index"]) >= \
                        (last_term, last_idx):
                    granted = True
                    self.log.voted_for = req["candidate_id"]
                    self.log.save_meta()
                    self._reset_election_deadline()
            return {"term": self.log.term, "granted": granted}

    def _handle_pre_vote(self, req: dict) -> dict:
        """Pre-vote answer: NO state mutation (term, voted_for, deadline
        all untouched). Granted only when (a) we ourselves have not heard
        a leader within the MINIMUM election timeout (gating on the
        randomized deadline would refuse the first legitimate candidate
        after a leader death and chain refusal rounds) and (b) the
        candidate's term+log could win."""
        with self.lock:
            lo_s = self._election_timeout_ms[0] / 1000.0
            leader_fresh = self.state == LEADER or \
                (time.monotonic() - self._last_leader_contact) < lo_s
            if req["term"] < self.log.term or leader_fresh:
                return {"term": self.log.term, "granted": False}
            last_idx = self.log.last_index
            last_term = self.log.term_at(
                last_idx, snapshot_term=self.snapshot_term)
            granted = (req["last_log_term"], req["last_log_index"]) >= \
                (last_term, last_idx)
            return {"term": self.log.term, "granted": granted}

    def handle_append_entries(self, req: dict) -> dict:
        with self.lock:
            if req["term"] < self.log.term:
                return {"term": self.log.term, "success": False}
            self._become_follower(req["term"], req["leader_id"])
            self._reset_election_deadline()
            self._last_leader_contact = time.monotonic()
            prev_i, prev_t = req["prev_index"], req["prev_term"]
            if prev_i >= self.log.start_index - 1 or prev_i == 0:
                local_prev = self.log.term_at(
                    prev_i, snapshot_term=self.snapshot_term)
            else:
                # prev is inside our snapshotted prefix: anything the
                # leader sends there is already committed state
                local_prev = prev_t
            if local_prev == -1 or local_prev != prev_t:
                # missing or conflicting: ask to back up (include a hint)
                return {"term": self.log.term, "success": False,
                        "hint_index": min(self.log.last_index + 1,
                                          prev_i)}
            dirty = False
            for w in req.get("records", []):
                rec = RaftRecord.from_wire(w)
                if rec.index <= self.log.last_index:
                    if self.log.term_at(
                            rec.index,
                            snapshot_term=self.snapshot_term) == rec.term:
                        continue  # duplicate
                    if rec.index <= self.applied_index:
                        # conflicting below applied state should be
                        # impossible (committed entries never conflict)
                        LOG.error("raft %s: conflict below applied index",
                                  self.node_id)
                        return {"term": self.log.term, "success": False}
                    self.log.truncate_from(rec.index)
                if rec.index == self.log.last_index + 1:
                    self.log.append(rec, fsync=False)
                    dirty = True
            if dirty:
                self.log.flush()
            if req["leader_commit"] > self.commit_index:
                self.commit_index = min(req["leader_commit"],
                                        self.log.last_index)
                self.apply_cv.notify_all()
                self.commit_cv.notify_all()
            return {"term": self.log.term, "success": True,
                    "match_index": self.log.last_index}

    def handle_install_snapshot(self, req: dict) -> dict:
        with self.lock:
            if req["term"] < self.log.term:
                return {"term": self.log.term, "ok": False}
            self._become_follower(req["term"], req["leader_id"])
            self._last_leader_contact = time.monotonic()
            snap = req["snapshot"]
            if snap["index"] <= self.applied_index:
                return {"term": self.log.term, "ok": True,
                        "match_index": self.log.last_index}
        # lock order _snap_io_lock -> _state_lock -> self.lock, same as
        # take_snapshot; _state_lock freezes appliers during restore
        with self._snap_io_lock:
            with self._state_lock:
                with self.lock:
                    # re-check: state may have moved while unlocked
                    if req["term"] < self.log.term:
                        return {"term": self.log.term, "ok": False}
                    if snap["index"] <= self.applied_index:
                        return {"term": self.log.term, "ok": True,
                                "match_index": self.log.last_index}
                self._restore_fn(snap["components"])
                with self.lock:
                    self.snapshot_term = snap["term"]
                    self.applied_index = snap["index"]
                    self.applied_seq = snap["seq"]
                    self.commit_index = max(self.commit_index, snap["index"])
            # persist the snapshot file BEFORE truncating the durable log
            # (a crash in between leaves snapshot+old-log, which recovery
            # reconciles; truncating first would leave a hole) — and do
            # the file IO outside the consensus lock
            d = self._snap_dir()
            os.makedirs(d, exist_ok=True)
            blob = msgpack.packb(snap, use_bin_type=True)
            self._write_snapshot_file(d, snap["term"], snap["index"], blob)
        with self.lock:
            # discard the log prefix the snapshot covers (usually all)
            self.log.records = [r for r in self.log.records
                                if r.index > snap["index"]]
            self.log.start_index = max(self.log.start_index,
                                       snap["index"] + 1)
            self.log.save_meta()
            self.log._rewrite()
            return {"term": self.log.term, "ok": True,
                    "match_index": self.log.last_index}

    def transfer_leadership(self, target_id: str,
                            timeout_s: float = 5.0) -> bool:
        """Leader-side graceful handover (Raft §3.10; reference: Ratis
        leadership transfer behind ``journal quorum elect``): pause new
        proposals, bring the target fully up to date, then TimeoutNow so
        it elects immediately (force-election past pre-vote). Returns
        True once this node observes the target's leadership. Aborts
        WITHOUT firing the election when catch-up fails — TimeoutNow at
        a lagging target can only depose the healthy leader and lose
        the vote (§5.4.1), a pure availability hole."""
        with self.lock:
            if self.state != LEADER:
                raise JournalClosedError(
                    f"not the raft leader (leader={self.leader_id})")
            if target_id not in self.peers:
                raise ValueError(f"unknown quorum member {target_id!r}")
            addr = self.peers[target_id]
            # §3.10: stop taking client requests for the duration, THEN
            # snapshot the index the target must reach — no append can
            # race past it while the flag is up
            self._transferring = True
            last = self.log.last_index
            term = self.log.term
        try:
            catch_up_deadline = time.monotonic() + timeout_s / 2
            caught_up = False
            while time.monotonic() < catch_up_deadline:
                with self.lock:
                    if self.match_index.get(target_id, 0) >= last:
                        caught_up = True
                        break
                    ev = self._peer_wakeups.get(target_id)
                if ev is not None:
                    ev.set()
                time.sleep(0.02)
            if not caught_up:
                return False  # abort: no TimeoutNow at a lagging target
            try:
                self._call_peer(addr, "timeout_now",
                               {"term": term, "leader_id": self.node_id},
                               timeout=2.0)
            except Exception:  # noqa: BLE001 target unreachable
                return False
            observe_deadline = time.monotonic() + timeout_s / 2
            while time.monotonic() < observe_deadline:
                with self.lock:
                    if self.state != LEADER:
                        # step-down cleared leader_id; the new leader's
                        # first heartbeat fills it in
                        if self.leader_id == target_id:
                            return True
                        if self.leader_id is not None:
                            return False  # someone else won
                time.sleep(0.02)
            return False
        finally:
            with self.lock:
                self._transferring = False

    def handle_timeout_now(self, req: dict) -> dict:
        """TimeoutNow from the leader: start a forced election NOW.
        §3.10: TimeoutNow is LEADER-initiated only — a sender that
        CONTRADICTS a leader we already recognize at the current term is
        rejected. When we have not yet recorded a leader for the term
        (leader_id None right after a vote-driven term bump, before the
        first AppendEntries) the request is accepted: the legitimate
        leader's transfer must not silently abort in that window, at the
        cost of also trusting an equal-term sender we cannot yet
        disprove. Like all of Raft this is crash-fault-tolerant only: a
        *malicious* peer forging the leader's id is outside the model
        (peers are trusted)."""
        with self.lock:
            if self._stopped or self.state == LEADER or \
                    req.get("term", 0) < self.log.term:
                return {"ok": False}
            sender = req.get("leader_id")
            # Accept when we have not yet recorded a leader for this term
            # (leader_id None right after a vote-driven term bump, before
            # the first AppendEntries) — the legitimate leader's transfer
            # must not silently abort then. Reject only a sender that
            # CONTRADICTS a known leader.
            if req.get("term", 0) == self.log.term and \
                    self.leader_id is not None and \
                    sender != self.leader_id:
                return {"ok": False}
        threading.Thread(target=self._start_election,
                         kwargs={"force": True}, daemon=True).start()
        return {"ok": True}

    def quorum_info(self) -> dict:
        now = time.monotonic()
        with self.lock:
            members = [{"node_id": self.node_id, "address": "self",
                        "role": self.state,
                        "match_index": self.log.last_index,
                        "last_contact_s": 0.0}]
            for nid, addr in self.peers.items():
                at = self.peer_contact.get(nid)
                members.append({
                    "node_id": nid, "address": addr,
                    "role": "LEADER" if nid == self.leader_id else "UNKNOWN"
                    if self.state != LEADER else "FOLLOWER",
                    "match_index": self.match_index.get(nid, 0),
                    # None = never heard from (or we are not the leader,
                    # so we do not probe peers at all)
                    "last_contact_s": None if at is None
                    else max(0.0, now - at)})
            return {"leader": self.leader_id, "term": self.log.term,
                    "commit_index": self.commit_index, "members": members}

    # -- leader write path ----------------------------------------------------
    def propose(self, entries: List[JournalEntry],
                timeout_s: float = 30.0) -> None:
        """Append a batch as the leader; block until committed on a
        quorum, then apply it ON THIS THREAD (the caller holds the owning
        component's write lock, which is what serializes application
        against readers). Raises JournalClosedError when not leader,
        deposed mid-flight, or quorum-commit times out — in the last two
        cases the batch MAY still commit later (ambiguous failure, as in
        the reference; the apply loop then applies it)."""
        # copy: the caller (JournalContext) clears its batch list after
        # write_and_flush returns, but this record outlives the call (log
        # retention + lazy re-serialization for follower replication)
        entries = list(entries)
        with self.lock:
            if self.state != LEADER:
                raise JournalClosedError(
                    f"not the raft leader (leader={self.leader_id})")
            if self._transferring:
                raise JournalClosedError(
                    "leadership transfer in progress; retry against "
                    "the new leader")
            rec = RaftRecord(self.log.term, self.log.last_index + 1, entries)
            self.log.append(rec)
            idx = rec.index
            self._local_batches[idx] = rec
            self._advance_commit()  # single-node quorum commits instantly
        for ev in self._peer_wakeups.values():
            ev.set()
        deadline = time.monotonic() + timeout_s
        try:
            with self.lock:
                while not (self.commit_index >= idx
                           and self.applied_index == idx - 1):
                    if self._stopped:
                        raise JournalClosedError("raft node stopped")
                    if self.state != LEADER and self.commit_index < idx:
                        raise JournalClosedError(
                            "lost leadership before commit; entry not "
                            "acknowledged")
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise JournalClosedError(
                            "timed out waiting for quorum commit")
                    self.commit_cv.wait(timeout=min(remaining, 0.5))
            # committed + predecessor applied: apply on this thread.
            # _state_lock taken BEFORE self.lock (lock order) freezes
            # component state against snapshot capture; applied_index
            # cannot move meanwhile — our record is in _local_batches so
            # the apply loop skips it, and nothing can apply idx+1 first.
            with self._state_lock:
                with self.lock:
                    if self.log.get(idx) is not rec:
                        # deposed before replication: a new leader's record
                        # truncated ours away — the committed slot at idx
                        # is NOT our batch; never apply the stale entries
                        # (the apply loop handles the real record once we
                        # unregister in the finally block)
                        raise JournalClosedError(
                            "entry superseded after leadership loss; not "
                            "acknowledged")
                    for e in rec.entries:
                        self._apply_fn(e)
                        self.applied_seq = max(self.applied_seq, e.sequence)
                        self._entries_since_snapshot += 1
                    self.applied_index = idx
                    self.apply_cv.notify_all()
                    self.commit_cv.notify_all()
        finally:
            with self.lock:
                self._local_batches.pop(idx, None)
                self.apply_cv.notify_all()

    def _advance_commit(self) -> None:
        """Caller holds the lock. Leader-only: commit = highest index
        replicated on a quorum with a record of the current term (§5.4.2)."""
        if self.state != LEADER:
            return
        for idx in range(self.log.last_index, self.commit_index, -1):
            if self.log.term_at(idx, snapshot_term=self.snapshot_term) != \
                    self.log.term:
                break
            count = 1 + sum(1 for nid in self.peers
                            if self.match_index.get(nid, 0) >= idx)
            if count >= self.quorum_size:
                self.commit_index = idx
                self.apply_cv.notify_all()
                self.commit_cv.notify_all()
                break

    # -- replication (leader -> one peer) ------------------------------------
    def _peer_loop(self, nid: str) -> None:
        ev = self._peer_wakeups[nid]
        addr = self.peers[nid]
        while True:
            ev.wait(timeout=self._heartbeat_ms / 1000.0)
            ev.clear()
            with self.lock:
                if self._stopped:
                    return
                if self.state != LEADER:
                    continue
                term = self.log.term
                nxt = self.next_index.get(nid, self.log.last_index + 1)
                need_snap = nxt < self.log.start_index
                if not need_snap:
                    prev = nxt - 1
                    prev_term = self.log.term_at(
                        prev, snapshot_term=self.snapshot_term)
                    recs = [r.to_wire() for r in self.log.slice_from(nxt)]
                    commit = self.commit_index
            payload = None
            if need_snap:
                # read + decode the (possibly large) snapshot file OUTSIDE
                # the consensus lock — a slow standby must not stall
                # appends/votes into an election timeout
                snap_path = self._latest_snapshot_path()
                if snap_path is not None:
                    with open(snap_path, "rb") as f:
                        payload = msgpack.unpackb(
                            f.read(), raw=False, strict_map_key=False)
            try:
                if need_snap:
                    if payload is None:
                        # no snapshot on disk yet (all state in log):
                        # take one, then retry with it available
                        self.take_snapshot()
                        continue
                    resp = self._call_peer(addr, "install_snapshot", {
                        "term": term, "leader_id": self.node_id,
                        "snapshot": payload}, timeout=10.0)
                    self.peer_contact[nid] = time.monotonic()
                    with self.lock:
                        if resp["term"] > self.log.term:
                            self._become_follower(resp["term"], None)
                            continue
                        if resp.get("ok"):
                            self.match_index[nid] = payload["index"]
                            self.next_index[nid] = payload["index"] + 1
                    continue
                resp = self._call_peer(addr, "append_entries", {
                    "term": term, "leader_id": self.node_id,
                    "prev_index": prev, "prev_term": prev_term,
                    "records": recs, "leader_commit": commit,
                }, timeout=2.0)
            except Exception:  # noqa: BLE001 peer unreachable: retry later
                continue
            # any decoded reply is proof of life (quorum view + the
            # quorum-degraded health sampling read this)
            self.peer_contact[nid] = time.monotonic()
            with self.lock:
                if resp["term"] > self.log.term:
                    self._become_follower(resp["term"], None)
                    continue
                if self.state != LEADER or self.log.term != term:
                    continue
                if resp.get("success"):
                    self.match_index[nid] = resp["match_index"]
                    self.next_index[nid] = resp["match_index"] + 1
                    self._advance_commit()
                    if self.next_index[nid] <= self.log.last_index:
                        ev.set()  # more to send
                else:
                    hint = resp.get("hint_index")
                    self.next_index[nid] = max(
                        1, hint if hint is not None else nxt - 1)
                    ev.set()

    # -- apply loop -----------------------------------------------------------
    def _apply_loop(self) -> None:
        """Applies committed NON-local records in order (replication on
        followers; barrier records and orphaned batches on leaders).
        Records whose proposer is live-waiting are left to that thread."""
        from alluxio_tpu.utils import faults

        while True:
            with self.lock:
                rec = None
                while not self._stopped:
                    if faults.armed() and faults.injector() \
                            .tailer_frozen(self.node_id):
                        # chaos tailer-freeze, Raft flavor: commit may
                        # advance but this member stops APPLYING — its
                        # served md_version stalls, exactly the standby
                        # staleness drill
                        self.apply_cv.wait(timeout=0.05)
                        continue
                    if self.applied_index < self.commit_index:
                        nxt = self.log.get(self.applied_index + 1)
                        if nxt is not None and \
                                nxt.index not in self._local_batches:
                            rec = nxt
                            break
                    self.apply_cv.wait(timeout=0.5)
                if self._stopped:
                    return
                was_leader = self.state == LEADER
            # apply under _state_lock -> lock (same order as propose /
            # take_snapshot); re-verify the record is still the next one
            # (a conflict truncation may have replaced it while unlocked)
            snap_due = False
            # FOLLOWERS ONLY: a leader applying an orphan/barrier record
            # must not wait on the tree write lock — a live-waiting
            # proposer holds the tree READ lock until this very record
            # applies, a cross-thread cycle that would stall every write
            # for the propose timeout.  Leaders have no standby readers
            # to exclude anyway; the rare just-deposed race (one batch
            # applied unexcluded) closes on the next loop iteration.
            excl = self.apply_exclusion if not was_leader else None
            with (excl() if excl is not None else contextlib.nullcontext()):
                with self._state_lock:
                    with self.lock:
                        if self._stopped:
                            return
                        if self.log.get(self.applied_index + 1) is not rec:
                            continue
                        for e in rec.entries:
                            self._apply_fn(e)
                            self.applied_seq = max(self.applied_seq,
                                                   e.sequence)
                            self._entries_since_snapshot += 1
                        self.applied_index = rec.index
                        self.commit_cv.notify_all()
                        self.apply_cv.notify_all()
                        snap_due = self._entries_since_snapshot >= \
                            self._snapshot_period
            if snap_due:
                try:
                    self.take_snapshot()
                except Exception:  # noqa: BLE001
                    LOG.exception("periodic raft snapshot failed")

    def is_leader(self) -> bool:
        with self.lock:
            return self.state == LEADER

    def leader_ready(self) -> bool:
        """Leader AND the no-op barrier of its term has been applied (all
        prior-term entries are in local state — safe to serve)."""
        with self.lock:
            return self.state == LEADER and \
                self.applied_index >= self.commit_index and \
                self.log.term_at(self.commit_index,
                                 snapshot_term=self.snapshot_term) == \
                self.log.term


def _peer_call(addr: str, method: str, req: dict, timeout: float):
    from alluxio_tpu.rpc.core import RpcChannel

    return RpcChannel(addr).call(RAFT_SERVICE, method, req, timeout=timeout)


def raft_journal_service(node: RaftNode):
    """RPC surface (reference: ``grpc/raft_journal.proto`` +
    ``grpc/journal_master.proto`` quorum info)."""
    from alluxio_tpu.rpc.core import ServiceDefinition

    svc = ServiceDefinition(RAFT_SERVICE)
    svc.unary("request_vote", node.handle_request_vote)
    svc.unary("append_entries", node.handle_append_entries)
    svc.unary("install_snapshot", node.handle_install_snapshot)
    svc.unary("get_quorum_info", lambda r: node.quorum_info())
    svc.unary("timeout_now", node.handle_timeout_now)
    return svc


class EmbeddedJournalSystem(JournalSystem):
    """The EMBEDDED journal flavor: a RaftNode + its RPC server.

    ``write_and_flush`` = propose-to-quorum; components register exactly as
    with the local journal; standby application is continuous (followers'
    components stay hot). Reference: ``RaftJournalSystem.java:150``.
    """

    def __init__(self, folder: str, *, node_id: str = "",
                 address: str = "", addresses: str = "",
                 election_timeout_ms: Tuple[int, int] = (300, 600),
                 heartbeat_interval_ms: int = 100,
                 snapshot_period_entries: int = 100_000,
                 **_ignored) -> None:
        super().__init__()
        members: Dict[str, str] = {}
        for a in [s.strip() for s in addresses.split(",") if s.strip()]:
            members[a] = a  # node_id IS the address (stable + unique)
        self._address = address or (next(iter(members)) if members else
                                    "127.0.0.1:0")
        if self._address not in members:
            members[self._address] = self._address
        self.node = RaftNode(
            node_id or self._address, members, folder,
            election_timeout_ms=election_timeout_ms,
            heartbeat_interval_ms=heartbeat_interval_ms,
            apply_fn=self._apply,
            snapshot_fn=lambda: {name: c.snapshot()
                                 for name, c in self._components.items()},
            restore_fn=self._restore_components,
            snapshot_period_entries=snapshot_period_entries)
        self._server = None
        self._seq_lock = threading.Lock()
        self._alloc_high = 0
        self._started = False

    def _restore_components(self, comps: dict) -> None:
        for name, comp in self._components.items():
            if name in comps:
                comp.restore(comps[name])
            else:
                comp.reset_state()

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._started:
            return
        from alluxio_tpu.rpc.core import RpcServer

        host, _, port = self._address.rpartition(":")
        self._server = RpcServer(bind_host=host or "0.0.0.0",
                                 port=int(port))
        self._server.add_service(raft_journal_service(self.node))
        self._server.start()
        self.node.start()
        self._started = True

    def gain_primacy(self) -> None:
        """Block until this node wins an election and its barrier commits.
        With peers down in a fresh quorum this can wait; callers that want
        standby behavior use ``standby_start`` + a selector instead."""
        self.start()
        while not self.node.leader_ready():
            if self.node._stopped:
                raise JournalClosedError("raft node stopped during election")
            time.sleep(0.02)

    def standby_start(self) -> None:
        self.start()

    def gain_primacy_from_standby(self) -> None:
        self.gain_primacy()

    def catch_up(self) -> int:
        return 0  # replication applies continuously; nothing to tail

    def lose_primacy(self) -> None:
        with self.node.lock:
            if self.node.state == LEADER:
                self.node._become_follower(self.node.log.term, None)

    def stop(self) -> None:
        self.node.stop()
        if self._server is not None:
            self._server.stop()
            self._server = None
        self._started = False

    def is_primary(self) -> bool:
        return self.node.is_leader()

    # -- writing --------------------------------------------------------------
    def allocate_entry(self, entry_type: str, payload: dict) -> JournalEntry:
        # provisional; propose() order defines the authoritative log
        # order, and apply tracks max(seq) so a new leader never reuses one
        with self._seq_lock:
            with self.node.lock:
                seq = max(self.node.applied_seq, self._alloc_high) + 1
            self._alloc_high = seq
            return JournalEntry(seq, entry_type, payload)

    def write_and_flush(self, entries: List[JournalEntry]) -> None:
        if not entries:
            return
        self.node.propose(entries)

    # -- maintenance ----------------------------------------------------------
    def checkpoint(self) -> None:
        self.node.take_snapshot()

    def checkpoint_standby(self) -> None:
        self.node.take_snapshot()

    @property
    def sequence(self) -> int:
        with self.node.lock:
            return self.node.applied_seq

    @property
    def last_checkpoint_sequence(self) -> int:
        return 0

    def write_backup(self, backup_dir: str) -> str:
        os.makedirs(backup_dir, exist_ok=True)
        with self.node.lock:
            snap = {
                "sequence": self.node.applied_seq,
                "components": {name: comp.snapshot()
                               for name, comp in self._components.items()},
            }
        stamp = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(backup_dir,
                            f"atpu-backup-{stamp}-{snap['sequence']}.bak")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(snap, use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def quorum_info(self) -> dict:
        return self.node.quorum_info()

    def transfer_leadership(self, target_id: str) -> bool:
        return self.node.transfer_leadership(target_id)


class RaftPrimarySelector(PrimarySelector):
    """Adapts a RaftNode to the PrimarySelector SPI: primacy == elected
    leadership (reference: ``RaftPrimarySelector.java``)."""

    def __init__(self, journal: EmbeddedJournalSystem) -> None:
        self._journal = journal

    def start(self) -> None:
        self._journal.start()

    def try_acquire(self) -> bool:
        return self._journal.node.leader_ready()

    def is_primary(self) -> bool:
        return self._journal.node.is_leader()

    def release(self) -> None:
        self._journal.lose_primacy()
