"""Journal entry encoding.

Re-design of the reference's journal-entry union
(``core/transport/src/main/proto/proto/journal/{journal,file,block,meta}.proto``)
and segment format (``core/server/common/.../journal/ufs/UfsJournalLogWriter``):
entries are ``(sequence, type, payload-dict)`` records, framed as
``[u32 length][u32 crc32][msgpack bytes]``. The crc makes torn tail writes
detectable so replay can stop cleanly at the last durable record — the same
contract the reference gets from its protobuf delimited stream + length
checks.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Dict, Iterator, Optional

import msgpack

_HEADER = struct.Struct("<II")  # length, crc32


def map_or_read(f: BinaryIO):
    """A contiguous view of a log file: mmap when possible (zero heap
    copy on multi-GB recovery), ``f.read()`` fallback (pipes, empty
    files — mmapping zero bytes raises). The two paths would disagree
    for a pre-seeked file (mmap maps from 0, read() from ``tell()``),
    so callers must pass freshly-opened or rewound files — checked
    here (when the stream can tell at all) rather than papered over
    with a sliced view the cleanup sites couldn't ``close()``."""
    import mmap

    if f.seekable() and f.tell() != 0:
        raise ValueError("map_or_read requires position 0 "
                         "(pre-seeked file would decode differently "
                         "on the mmap vs read() path)")
    try:
        return mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    except (ValueError, OSError):
        return f.read()


def iter_frames(data: bytes) -> "Iterator[tuple]":
    """Yield ``(body_offset, body_length)`` for each valid
    ``[u32 len][u32 crc32][body]`` frame in ``data``; stops cleanly at
    the torn tail (short header/body, zero-length zero-padding guard,
    or CRC mismatch). The ONE framing scanner for every log in the
    system (journal segments and the Raft log share the layout) —
    native (``alluxio_tpu.native``, zero-copy, no per-frame
    allocations) when built, Python fallback otherwise."""
    from alluxio_tpu import native

    scan = native.scan_frames(data)
    if scan is not None:
        yield from scan[0]
        return
    pos, n = 0, len(data)
    while pos + _HEADER.size <= n:
        length, crc = _HEADER.unpack_from(data, pos)
        body = data[pos + _HEADER.size:pos + _HEADER.size + length]
        if length == 0 or len(body) < length or zlib.crc32(body) != crc:
            return  # torn tail — replay stops at last durable frame
        yield pos + _HEADER.size, length
        pos += _HEADER.size + length


class EntryType:
    """Catalog of journal entry types (union members in the reference's
    ``journal.proto``). String-typed for forward compatibility."""

    # file.proto equivalents
    INODE_FILE = "inode_file"
    INODE_DIRECTORY = "inode_directory"
    NEW_BLOCK = "new_block"
    UPDATE_INODE = "update_inode"
    UPDATE_INODE_FILE = "update_inode_file"
    COMPLETE_FILE = "complete_file"
    DELETE_FILE = "delete_file"
    RENAME = "rename"
    SET_ACL = "set_acl"
    SET_ATTRIBUTE = "set_attribute"
    ADD_MOUNT_POINT = "add_mount_point"
    DELETE_MOUNT_POINT = "delete_mount_point"
    PERSIST_FILE = "persist_file"
    ASYNC_PERSIST_REQUEST = "async_persist_request"
    UPDATE_UFS_MODE = "update_ufs_mode"
    #: client-cache invalidation with no metadata entry of its own
    #: (block-location drift: worker loss/quarantine, re-replication,
    #: free) — journaled so the invalidation-log version stays a pure
    #: function of the applied journal on primary AND standbys
    #: (docs/ha.md)
    INVALIDATE_PATH = "invalidate_path"
    # block.proto equivalents
    BLOCK_CONTAINER_ID = "block_container_id"
    BLOCK_INFO = "block_info"
    DELETE_BLOCK = "delete_block"
    # meta.proto equivalents
    CLUSTER_INFO = "cluster_info"
    PATH_PROPERTIES = "path_properties"
    REMOVE_PATH_PROPERTIES = "remove_path_properties"
    # file.proto active-sync equivalents
    ADD_SYNC_POINT = "add_sync_point"
    REMOVE_SYNC_POINT = "remove_sync_point"
    # table.proto equivalents
    ATTACH_DB = "attach_db"
    DETACH_DB = "detach_db"
    ADD_TABLE = "add_table"
    REMOVE_TABLE = "remove_table"
    ADD_TRANSFORM_JOB_INFO = "add_transform_job_info"
    REMOVE_TRANSFORM_JOB_INFO = "remove_transform_job_info"


@dataclass
class JournalEntry:
    sequence: int
    type: str
    payload: Dict[str, Any] = field(default_factory=dict)

    def encode(self) -> bytes:
        body = msgpack.packb((self.sequence, self.type, self.payload),
                             use_bin_type=True)
        return _HEADER.pack(len(body), zlib.crc32(body)) + body

    @staticmethod
    def decode_stream(f: BinaryIO) -> Iterator["JournalEntry"]:
        """Yield entries until EOF or a torn/corrupt record (clean stop)."""
        data = map_or_read(f)
        try:
            for off, length in iter_frames(data):
                seq, etype, payload = msgpack.unpackb(
                    data[off:off + length], raw=False)
                yield JournalEntry(seq, etype, payload)
        finally:
            if hasattr(data, "close"):
                data.close()


class Journaled:
    """A state-machine component whose mutations flow through the journal
    (reference: ``journal/Journaled.java``). Components must be
    deterministic: ``process_entry`` replayed in sequence order rebuilds
    exactly the same state."""

    #: stable name used to namespace checkpoint snapshots
    journal_name: str = ""

    def process_entry(self, entry: JournalEntry) -> bool:
        """Apply one entry; return False if the type is not ours."""
        raise NotImplementedError

    def snapshot(self) -> Dict[str, Any]:
        """Serialize full state for a checkpoint."""
        raise NotImplementedError

    def restore(self, snap: Dict[str, Any]) -> None:
        """Reset state from a checkpoint snapshot."""
        raise NotImplementedError

    def reset_state(self) -> None:
        self.restore(self._empty_snapshot())

    def _empty_snapshot(self) -> Dict[str, Any]:
        return {}
