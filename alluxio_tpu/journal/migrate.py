"""Offline journal migration: LOCAL/UFS WAL <-> embedded Raft quorum.

Re-design of ``core/server/common/src/main/java/alluxio/master/journal/
JournalUpgrader.java:61`` + the flow proven by
``tests/.../ft/journal/JournalMigrationIntegrationTest.java``: an
operator on the single-writer LOCAL (or shared-UFS) journal adopts an HA
Raft quorum — or backs out of one — WITHOUT replaying through live
masters. The migration is entry-level:

  LOCAL -> EMBEDDED
    checkpoint        -> per-member Raft snapshot  (state as-is)
    segment entries   -> Raft log records at term 1 (applied by the
                         real masters when the quorum first boots)
  EMBEDDED -> LOCAL
    latest snapshot   -> LOCAL checkpoint
    log entries past it -> one closed LOCAL segment

Both layouts carry a ``VERSION`` marker file (the reference tracks
journal layout versions via the v0/v1 folder structure; a frame-header
version would break every existing log + the native scanner, so the
folder-level marker is the compatible equivalent). The tool refuses to
migrate formats newer than it understands.

Offline means offline: run with every master stopped. The LOCAL reader
uses the same torn-tail-tolerant scan as recovery, so an unclean
shutdown migrates exactly what a restart would have recovered.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import msgpack

from alluxio_tpu.journal.format import JournalEntry
from alluxio_tpu.journal.system import (
    CKPT_DIR, LOG_DIR, latest_checkpoint_name, sorted_segments,
)

FORMAT_VERSION = 1
_VERSION_FILE = "VERSION"

#: entries per Raft record written during migration (a record is one
#: group-commit batch; bounding it keeps single frames small)
_BATCH = 512


class MigrationError(Exception):
    pass


def _read_version(folder: str) -> int:
    try:
        with open(os.path.join(folder, _VERSION_FILE)) as f:
            return int(f.read().strip() or 1)
    except (FileNotFoundError, ValueError):
        return 1  # pre-marker folders are format 1

def _write_version(folder: str) -> None:
    os.makedirs(folder, exist_ok=True)
    with open(os.path.join(folder, _VERSION_FILE), "w") as f:
        f.write(f"{FORMAT_VERSION}\n")


def _check_version(folder: str) -> None:
    v = _read_version(folder)
    if v > FORMAT_VERSION:
        raise MigrationError(
            f"journal at {folder} is format v{v}; this tool understands "
            f"up to v{FORMAT_VERSION} — upgrade the software first")


# ---------------------------------------------------------------- readers
def read_local_state(local_folder: str) -> Tuple[
        Optional[dict], int, List[JournalEntry]]:
    """-> (checkpoint components | None, checkpoint seq, tail entries)."""
    _check_version(local_folder)
    ckpt_dir = os.path.join(local_folder, CKPT_DIR)
    log_dir = os.path.join(local_folder, LOG_DIR)
    comps: Optional[dict] = None
    start_seq = 0
    ck = latest_checkpoint_name(ckpt_dir)
    if ck:
        with open(os.path.join(ckpt_dir, ck), "rb") as f:
            snap = msgpack.unpackb(f.read(), raw=False,
                                   strict_map_key=False)
        comps = snap["components"]
        start_seq = snap["sequence"]
    entries: List[JournalEntry] = []
    for seg in sorted_segments(log_dir):
        with open(os.path.join(log_dir, seg), "rb") as f:
            for entry in JournalEntry.decode_stream(f):
                if entry.sequence > start_seq:
                    entries.append(entry)
    entries.sort(key=lambda e: e.sequence)
    return comps, start_seq, entries


def read_embedded_state(raft_folder: str, node_id: str) -> Tuple[
        Optional[dict], int, List[JournalEntry]]:
    """-> (snapshot components | None, snapshot seq, tail entries) for
    one quorum member's directory."""
    _check_version(raft_folder)
    node_dir = os.path.join(raft_folder, "raft", node_id)
    if not os.path.isdir(node_dir):
        raise MigrationError(f"no raft member state at {node_dir}")
    comps: Optional[dict] = None
    snap_seq = 0
    snap_dir = os.path.join(node_dir, "snapshots")
    if os.path.isdir(snap_dir):
        snaps = [f for f in os.listdir(snap_dir) if f.endswith(".snap")]
        if snaps:
            latest = max(snaps, key=lambda f: int(
                f.split("_")[1].split(".")[0], 16))
            with open(os.path.join(snap_dir, latest), "rb") as f:
                snap = msgpack.unpackb(f.read(), raw=False,
                                       strict_map_key=False)
            comps, snap_seq = snap["components"], snap["seq"]
    entries: List[JournalEntry] = []
    log_path = os.path.join(node_dir, "log.bin")
    if os.path.exists(log_path):
        from alluxio_tpu.journal.format import iter_frames, map_or_read

        with open(log_path, "rb") as f:
            data = map_or_read(f)
            for off, length in iter_frames(data):
                rec = msgpack.unpackb(bytes(data[off:off + length]),
                                      raw=False, strict_map_key=False)
                for seq, etype, payload in rec[2]:
                    if seq > snap_seq:
                        entries.append(JournalEntry(seq, etype, payload))
            if hasattr(data, "close"):
                data.close()
    entries.sort(key=lambda e: e.sequence)
    return comps, snap_seq, entries


def members_of(raft_folder: str) -> List[str]:
    d = os.path.join(raft_folder, "raft")
    return sorted(os.listdir(d)) if os.path.isdir(d) else []


def freshest_member(raft_folder: str) -> str:
    """Pick the member with the highest (snapshot seq, last entry seq)."""
    best, best_key = "", (-1, -1)
    for m in members_of(raft_folder):
        try:
            _, snap_seq, entries = read_embedded_state(raft_folder, m)
        except MigrationError:
            continue
        key = (snap_seq, entries[-1].sequence if entries else snap_seq)
        if key > best_key:
            best, best_key = m, key
    if not best:
        raise MigrationError(f"no readable raft member under {raft_folder}")
    return best


# ---------------------------------------------------------------- writers
def _fsync_write(path: str, blob: bytes) -> None:
    tmp = path + ".migtmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_embedded_member(raft_folder: str, node_id: str,
                          comps: Optional[dict], snap_seq: int,
                          entries: List[JournalEntry]) -> None:
    """Materialize one quorum member's directory: snapshot + log at
    term 1. The member dirs are byte-identical across the quorum, which
    is a valid Raft state (all logs match; first election proceeds
    normally)."""
    node_dir = os.path.join(raft_folder, "raft", node_id)
    os.makedirs(node_dir, exist_ok=True)
    base_index = 0
    if comps is not None and snap_seq > 0:
        snap_dir = os.path.join(node_dir, "snapshots")
        os.makedirs(snap_dir, exist_ok=True)
        base_index = snap_seq  # any positive base works; seq is natural
        blob = msgpack.packb(
            {"term": 1, "index": base_index, "seq": snap_seq,
             "components": comps}, use_bin_type=True)
        _fsync_write(os.path.join(
            snap_dir, f"{1:08x}_{base_index:016x}.snap"), blob)
    # log records: one batch per _BATCH entries, indices base+1..
    import struct
    import zlib

    frames = bytearray()
    index = base_index
    for i in range(0, len(entries), _BATCH):
        batch = entries[i:i + _BATCH]
        index += 1
        body = msgpack.packb(
            [1, index, [[e.sequence, e.type, e.payload] for e in batch]],
            use_bin_type=True)
        frames += struct.pack("<II", len(body), zlib.crc32(body)) + body
    if frames:
        _fsync_write(os.path.join(node_dir, "log.bin"), bytes(frames))
    _fsync_write(os.path.join(node_dir, "meta.bin"), msgpack.packb(
        {"term": 1, "voted_for": None, "start_index": base_index + 1},
        use_bin_type=True))


def local_to_embedded(local_folder: str, raft_folder: str,
                      addresses: List[str]) -> dict:
    """LOCAL/UFS journal -> a fresh Raft quorum's initial state."""
    if not addresses:
        raise MigrationError("need the quorum member addresses "
                             "(atpu.master.embedded.journal.addresses)")
    for m in members_of(raft_folder):
        raise MigrationError(
            f"raft state already exists at {raft_folder}/raft/{m}; "
            f"refusing to overwrite a quorum")
    comps, snap_seq, entries = read_local_state(local_folder)
    if comps is None and not entries:
        raise MigrationError(f"nothing to migrate in {local_folder}")
    if comps is not None and snap_seq <= 0:
        # a checkpoint at sequence 0 cannot become a Raft snapshot
        # (index 0 means "none") and its covered segments may be GC'd —
        # never risk silently dropping it
        raise MigrationError(
            f"checkpoint at {local_folder} has sequence {snap_seq}; "
            f"cannot anchor a Raft snapshot — take a fresh checkpoint "
            f"on the source journal first")
    for addr in addresses:
        write_embedded_member(raft_folder, addr, comps, snap_seq, entries)
    _write_version(raft_folder)
    return {"members": list(addresses), "checkpoint_seq": snap_seq,
            "entries": len(entries)}


def embedded_to_local(raft_folder: str, local_folder: str,
                      node_id: str = "") -> dict:
    """One quorum member's state -> a LOCAL/UFS journal folder."""
    node_id = node_id or freshest_member(raft_folder)
    comps, snap_seq, entries = read_embedded_state(raft_folder, node_id)
    ckpt_dir = os.path.join(local_folder, CKPT_DIR)
    log_dir = os.path.join(local_folder, LOG_DIR)
    if latest_checkpoint_name(ckpt_dir) or sorted_segments(log_dir):
        raise MigrationError(
            f"{local_folder} already holds journal state; refusing to "
            f"overwrite")
    os.makedirs(ckpt_dir, exist_ok=True)
    os.makedirs(log_dir, exist_ok=True)
    if comps is not None and snap_seq > 0:
        _fsync_write(
            os.path.join(ckpt_dir, f"{snap_seq:016x}.ckpt"),
            msgpack.packb({"sequence": snap_seq, "components": comps},
                          use_bin_type=True))
    if entries:
        blob = bytearray()
        for e in entries:
            blob += e.encode()
        first, last = entries[0].sequence, entries[-1].sequence
        _fsync_write(os.path.join(log_dir, f"{first:016x}-{last:016x}.log"),
                     bytes(blob))
    _write_version(local_folder)
    return {"source_member": node_id, "checkpoint_seq": snap_seq,
            "entries": len(entries)}
