"""RPC core: msgpack-over-gRPC with typed error propagation.

Re-design of the reference's transport layer (``core/common/.../grpc/
{GrpcServerBuilder,GrpcChannelBuilder,GrpcConnectionPool.java:46}`` + 26
generated proto services). Design departure, on purpose: instead of protoc
codegen we register **generic gRPC handlers** keyed by method name with
msgpack message bodies — same HTTP/2 transport, flow control and streaming
semantics as the reference, zero generated code, and messages are the same
dicts the wire types already serialize to. The reference's zero-copy
marshalling trick (``GrpcSerializationUtils.java:39``) is unnecessary here:
bulk data rides raw ``bytes`` fields in msgpack (no protobuf copy), and the
truly hot local path bypasses RPC entirely via shm short-circuit.

Errors: handlers raising ``AlluxioTpuError`` are mapped onto gRPC status +
a serialized typed payload in trailing metadata; clients re-raise the exact
exception class (reference: ``exception/status`` <-> ``io.grpc.Status``).
"""

from __future__ import annotations

import logging
import threading
from concurrent import futures
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import grpc
import msgpack

from alluxio_tpu.utils.exceptions import (
    AlluxioTpuError, ResourceExhaustedError, UnavailableError,
)
from alluxio_tpu.utils.tracing import (
    TRACEPARENT_KEY, bind_remote_parent, current_span,
    current_traceparent, reset_remote_parent, tracer,
)

LOG = logging.getLogger(__name__)

_ERROR_KEY = "atpu-error-bin"


def _bind_trace(context: grpc.ServicerContext):
    """Extract an inbound traceparent and bind it as this handler's
    parent context, so the server span joins the caller's trace.
    Returns a reset token (None when tracing is off / no header)."""
    if not tracer().enabled:
        return None
    for k, v in (context.invocation_metadata() or ()):
        if k == TRACEPARENT_KEY:
            return bind_remote_parent(v)
    return None

_CODE_TO_GRPC = {
    "NOT_FOUND": grpc.StatusCode.NOT_FOUND,
    "ALREADY_EXISTS": grpc.StatusCode.ALREADY_EXISTS,
    "INVALID_ARGUMENT": grpc.StatusCode.INVALID_ARGUMENT,
    "PERMISSION_DENIED": grpc.StatusCode.PERMISSION_DENIED,
    "UNAUTHENTICATED": grpc.StatusCode.UNAUTHENTICATED,
    "FAILED_PRECONDITION": grpc.StatusCode.FAILED_PRECONDITION,
    "RESOURCE_EXHAUSTED": grpc.StatusCode.RESOURCE_EXHAUSTED,
    "UNAVAILABLE": grpc.StatusCode.UNAVAILABLE,
    "DEADLINE_EXCEEDED": grpc.StatusCode.DEADLINE_EXCEEDED,
    "CANCELLED": grpc.StatusCode.CANCELLED,
    "ABORTED": grpc.StatusCode.ABORTED,
    "UNIMPLEMENTED": grpc.StatusCode.UNIMPLEMENTED,
    "INTERNAL": grpc.StatusCode.INTERNAL,
}


def pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


def _bind_user(context: grpc.ServicerContext, authenticator):
    """Authenticate request metadata and bind the user contextvar; returns
    a reset token (or None). Raises AlluxioTpuError on rejection."""
    if authenticator is None:
        return None
    from alluxio_tpu.security.user import set_authenticated_user

    md = {k: v for k, v in (context.invocation_metadata() or ())}
    user = authenticator.authenticate(md)
    return set_authenticated_user(user)


def _unbind_user(token) -> None:
    if token is not None:
        from alluxio_tpu.security.user import reset_authenticated_user

        reset_authenticated_user(token)


def check_admission(admission, context, method_key: str,
                    principal_hint: Optional[str] = None) -> None:
    """Per-dispatch QoS gate, shared by the gRPC wrappers and the
    fastpath server: the conf-gated fault hook first (so shedding can
    be chaos-drilled with no admission controller and no flood), then
    the per-principal token bucket.  Raises a typed
    ``ResourceExhaustedError`` carrying ``retry_after_s`` — the RPC is
    SHED, never queued (see qos/admission.py).  ``principal_hint``:
    transport-specific identity fallback for servers without a gRPC
    context (the fastpath passes its hello-frame ``atpu-user``)."""
    from alluxio_tpu.utils import faults

    if faults.armed():
        # the chaos drill honors the same exemptions real admission
        # does — shedding registration/heartbeats would destabilize
        # the cluster the drill is observing
        from alluxio_tpu.qos.admission import DEFAULT_EXEMPT

        exempt = admission.conf.exempt if admission is not None \
            else DEFAULT_EXEMPT
        if method_key.rsplit(".", 1)[-1] not in exempt:
            ra = faults.injector().take_rpc_reject(method_key)
            if ra:
                err = ResourceExhaustedError(
                    f"injected rpc reject for {method_key}; retry "
                    f"after {ra:.3f}s")
                err.retry_after_s = ra
                raise err
    if admission is None:
        return
    principal = principal_hint
    from alluxio_tpu.security.user import authenticated_user

    user = authenticated_user()
    if user is not None:
        principal = user.name
    elif principal is None and context is not None:
        # NOSASL server: fall back to the identity metadata clients
        # attach anyway, so admission can still separate principals
        for k, v in (context.invocation_metadata() or ()):
            if k == "atpu-user":
                principal = v
                break
    admission.check(principal, method_key.rsplit(".", 1)[-1])


def _timed_admission(sp, admission, context, span_name: str) -> None:
    """check_admission, recording its cost as the server span's
    ``admission`` phase when the dispatch is traced."""
    if sp is None:
        check_admission(admission, context, span_name)
        return
    import time as _time

    t0 = _time.perf_counter()
    check_admission(admission, context, span_name)
    sp.phase("admission", (_time.perf_counter() - t0) * 1000.0)


def _wrap_unary(fn: Callable[[dict], Any], authenticator=None,
                span_name: str = "", admission=None) -> Callable:
    def handler(request: dict, context: grpc.ServicerContext):
        token = None
        trace_token = _bind_trace(context)
        try:
            with tracer().span(span_name or "rpc.unary") as sp:
                token = _bind_user(context, authenticator)
                _timed_admission(sp, admission, context, span_name)
                return fn(request or {})
        except AlluxioTpuError as e:
            context.set_trailing_metadata(((_ERROR_KEY, pack(e.to_wire())),))
            context.abort(_CODE_TO_GRPC.get(e.code, grpc.StatusCode.INTERNAL),
                          str(e))
        except Exception as e:  # noqa: BLE001
            LOG.exception("unhandled error in RPC handler")
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")
        finally:
            _unbind_user(token)
            reset_remote_parent(trace_token)

    return handler


def _wrap_stream_out(fn: Callable[[dict], Iterator[Any]],
                     authenticator=None, span_name: str = "",
                     admission=None) -> Callable:
    def handler(request: dict, context: grpc.ServicerContext):
        token = None
        trace_token = _bind_trace(context)
        try:
            with tracer().span(span_name or "rpc.stream_out") as sp:
                token = _bind_user(context, authenticator)
                _timed_admission(sp, admission, context, span_name)
                yield from fn(request or {})
        except AlluxioTpuError as e:
            context.set_trailing_metadata(((_ERROR_KEY, pack(e.to_wire())),))
            context.abort(_CODE_TO_GRPC.get(e.code, grpc.StatusCode.INTERNAL),
                          str(e))
        except Exception as e:  # noqa: BLE001
            LOG.exception("unhandled error in streaming RPC handler")
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")
        finally:
            _unbind_user(token)
            reset_remote_parent(trace_token)

    return handler


def _wrap_stream_in(fn: Callable[[Iterator[Any]], Any],
                    authenticator=None, span_name: str = "",
                    admission=None) -> Callable:
    def handler(request_iterator, context: grpc.ServicerContext):
        token = None
        trace_token = _bind_trace(context)
        try:
            with tracer().span(span_name or "rpc.stream_in") as sp:
                token = _bind_user(context, authenticator)
                _timed_admission(sp, admission, context, span_name)
                return fn(request_iterator)
        except AlluxioTpuError as e:
            context.set_trailing_metadata(((_ERROR_KEY, pack(e.to_wire())),))
            context.abort(_CODE_TO_GRPC.get(e.code, grpc.StatusCode.INTERNAL),
                          str(e))
        except Exception as e:  # noqa: BLE001
            LOG.exception("unhandled error in client-streaming RPC handler")
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")
        finally:
            _unbind_user(token)
            reset_remote_parent(trace_token)

    return handler


class ServiceDefinition:
    """A named service: method name -> (callable, kind)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.methods: Dict[str, Tuple[Callable, str]] = {}

    def unary(self, method: str, fn: Callable[[dict], Any]) -> None:
        self.methods[method] = (fn, "unary")

    def stream_out(self, method: str, fn: Callable[[dict], Iterator[Any]]) -> None:
        self.methods[method] = (fn, "stream_out")

    def stream_in(self, method: str, fn: Callable[[Iterator[Any]], Any]) -> None:
        self.methods[method] = (fn, "stream_in")


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, services: Dict[str, ServiceDefinition],
                 authenticator=None, admission=None) -> None:
        self._services = services
        self._auth = authenticator
        self._admission = admission

    def service(self, handler_call_details):
        # method path: /<service>/<method>
        _, _, rest = handler_call_details.method.partition("/")
        service_name, _, method = rest.partition("/")
        svc = self._services.get(service_name)
        if svc is None:
            return None
        entry = svc.methods.get(method)
        if entry is None:
            return None
        fn, kind = entry
        span = f"{service_name}.{method}"
        if kind == "unary":
            return grpc.unary_unary_rpc_method_handler(
                _wrap_unary(fn, self._auth, span, self._admission),
                request_deserializer=unpack,
                response_serializer=pack)
        if kind == "stream_out":
            return grpc.unary_stream_rpc_method_handler(
                _wrap_stream_out(fn, self._auth, span, self._admission),
                request_deserializer=unpack, response_serializer=pack)
        if kind == "stream_in":
            return grpc.stream_unary_rpc_method_handler(
                _wrap_stream_in(fn, self._auth, span, self._admission),
                request_deserializer=unpack, response_serializer=pack)
        return None


class RpcServer:
    """gRPC server hosting ServiceDefinitions
    (reference: ``GrpcServerBuilder`` + ``GrpcDataServer.java:50``)."""

    def __init__(self, bind_host: str = "0.0.0.0", port: int = 0,
                 max_workers: int = 16,
                 domain_socket_path: Optional[str] = None,
                 authenticator=None, admission=None) -> None:
        """``authenticator``: a ``security.authentication.Authenticator``;
        when set, every RPC is authenticated and the resolved user is bound
        for handlers to read via ``security.authenticated_user()``.
        ``admission``: a ``qos.admission.AdmissionController``; when set,
        every dispatch passes its per-principal token bucket and
        over-limit calls are shed with a typed retry-after."""
        self._services: Dict[str, ServiceDefinition] = {}
        self._authenticator = authenticator
        self._admission = admission
        options = [
            ("grpc.max_send_message_length", 64 << 20),
            ("grpc.max_receive_message_length", 64 << 20),
            ("grpc.so_reuseport", 0),
        ]
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=options)
        self._bind = f"{bind_host}:{port}"
        self.port = port
        self._domain_socket_path = domain_socket_path
        self._started = False

    def add_service(self, svc: ServiceDefinition) -> None:
        self._services[svc.name] = svc

    def service(self, name: str) -> Optional[ServiceDefinition]:
        """Registered service by name — dispatch reads the definition's
        method map per call, so callers may wrap handlers in place even
        after ``start()`` (the HA primacy fence does)."""
        return self._services.get(name)

    def start(self) -> int:
        self._server.add_generic_rpc_handlers(
            (_GenericHandler(self._services, self._authenticator,
                             self._admission),))
        self.port = self._server.add_insecure_port(self._bind)
        if self._domain_socket_path:
            # UDS endpoint for same-host traffic without TCP
            # (reference: GrpcDataServer.java:72-95 Netty domain sockets)
            self._server.add_insecure_port(
                f"unix://{self._domain_socket_path}")
        self._server.start()
        self._started = True
        return self.port

    def stop(self, grace_s: float = 0.5) -> None:
        if self._started:
            self._server.stop(grace_s).wait(timeout=5)


def _raise_typed(err: grpc.RpcError) -> None:
    md = dict(err.trailing_metadata() or ())
    blob = md.get(_ERROR_KEY)
    if blob is not None:
        raise AlluxioTpuError.from_wire(unpack(blob)) from None
    if err.code() == grpc.StatusCode.UNAVAILABLE:
        raise UnavailableError(err.details() or "server unavailable") from None
    raise AlluxioTpuError(
        f"{err.code().name}: {err.details()}") from None


def default_client_metadata() -> Tuple[Tuple[str, str], ...]:
    """Identity attached to calls when the caller supplies none: the OS
    user under SIMPLE auth (reference: LoginUser)."""
    from alluxio_tpu.security.user import get_os_user

    return (("atpu-user", get_os_user()),)


class StreamCall:
    """A cancellable server-stream: iterate for decoded messages, call
    :meth:`cancel` to abort the underlying HTTP/2 stream mid-flight
    (hedged reads cancel the losing transfer instead of draining it).
    A self-cancelled stream ends iteration quietly; every other gRPC
    error is re-raised typed like the plain ``call_stream`` path.

    When the stream was opened under a live span, per-chunk msgpack
    decode time accumulates in ``decode_cell`` and lands on that span
    as ONE ``serialize`` phase when iteration ends (per-chunk phase
    events would bloat a large read's span)."""

    __slots__ = ("_call", "cancelled", "_span", "_decode_cell")

    def __init__(self, call, span=None, decode_cell=None) -> None:
        self._call = call
        self.cancelled = False
        self._span = span
        self._decode_cell = decode_cell

    def cancel(self) -> None:
        self.cancelled = True
        self._call.cancel()

    def __iter__(self) -> Iterator[Any]:
        try:
            yield from self._call
        except grpc.RpcError as e:
            if self.cancelled and e.code() == grpc.StatusCode.CANCELLED:
                return
            _raise_typed(e)
        finally:
            if self._span is not None and self._decode_cell is not None \
                    and self._decode_cell[0] > 0.0:
                self._span.phase("serialize", self._decode_cell[0])
                self._decode_cell[0] = 0.0


class RpcChannel:
    """A pooled channel + method invokers (reference: GrpcConnectionPool
    multiplexes channels per NetworkGroup; grpc-python already multiplexes
    streams on one HTTP/2 connection, so one channel per address suffices
    — except for the parallel data plane, where ``pool_index`` > 0 mints
    additional channels with their own subchannel pool, i.e. their own
    TCP connections, so striped reads are not serialized behind one
    connection's flow-control window).
    ``metadata``: identity/credential tuples attached to every call
    (reference: the SASL-authenticated channel carrying the user)."""

    _pool: Dict[str, grpc.Channel] = {}
    _pool_lock = threading.Lock()

    def __init__(self, address: str,
                 metadata: Optional[Tuple[Tuple[str, str], ...]] = None,
                 pool_index: int = 0) -> None:
        self.address = address
        self.metadata = tuple(metadata) if metadata is not None \
            else default_client_metadata()
        key = address if pool_index == 0 else f"{address}#{pool_index}"
        with RpcChannel._pool_lock:
            ch = RpcChannel._pool.get(key)
            if ch is None:
                options = [
                    ("grpc.max_send_message_length", 64 << 20),
                    ("grpc.max_receive_message_length", 64 << 20),
                ]
                if pool_index:
                    # opt out of gRPC's global subchannel sharing:
                    # identical-args channels would otherwise coalesce
                    # onto the same TCP connection, defeating the pool
                    options.append(("grpc.use_local_subchannel_pool", 1))
                ch = grpc.insecure_channel(address, options=options)
                RpcChannel._pool[key] = ch
            self._channel = ch

    def _call_metadata(self) -> Tuple[Tuple[str, str], ...]:
        """Per-call metadata: the channel identity plus the caller's
        trace context, so the server span joins the caller's trace."""
        tp = current_traceparent()
        if tp is None:
            return self.metadata
        return self.metadata + ((TRACEPARENT_KEY, tp),)

    def call(self, service: str, method: str, request: dict,
             timeout: Optional[float] = 30.0) -> Any:
        fn = self._channel.unary_unary(
            f"/{service}/{method}", request_serializer=pack,
            response_deserializer=unpack)
        try:
            return fn(request, timeout=timeout,
                      metadata=self._call_metadata())
        except grpc.RpcError as e:
            _raise_typed(e)

    def call_stream(self, service: str, method: str, request: dict,
                    timeout: Optional[float] = 300.0) -> Iterator[Any]:
        fn = self._channel.unary_stream(
            f"/{service}/{method}", request_serializer=pack,
            response_deserializer=unpack)
        try:
            yield from fn(request, timeout=timeout,
                          metadata=self._call_metadata())
        except grpc.RpcError as e:
            _raise_typed(e)

    def open_stream(self, service: str, method: str, request: dict,
                    timeout: Optional[float] = 300.0) -> StreamCall:
        """Like :meth:`call_stream` but returns the live call wrapped as
        a :class:`StreamCall`, so the caller can ``cancel()`` it — the
        parallel read path races stripe transfers and must be able to
        abort the losers without draining them.

        Under a live span the request pack and the per-chunk decodes
        are timed into the span's ``serialize`` phase: the pack happens
        eagerly here (grpc gets the pre-packed blob via an identity
        serializer, so nothing is encoded twice) and decode time is
        accumulated by the deserializer closure until the stream ends."""
        sp = current_span()
        if sp is None:
            fn = self._channel.unary_stream(
                f"/{service}/{method}", request_serializer=pack,
                response_deserializer=unpack)
            return StreamCall(fn(request, timeout=timeout,
                                 metadata=self._call_metadata()))
        import time as _time

        clock = _time.perf_counter
        t0 = clock()
        blob = pack(request)
        sp.phase("serialize", (clock() - t0) * 1000.0)
        cell = [0.0]

        def _timed_unpack(data: bytes):
            t = clock()
            obj = unpack(data)
            cell[0] += (clock() - t) * 1000.0
            return obj

        fn = self._channel.unary_stream(
            f"/{service}/{method}",
            request_serializer=lambda _r: blob,
            response_deserializer=_timed_unpack)
        return StreamCall(fn(request, timeout=timeout,
                             metadata=self._call_metadata()),
                          span=sp, decode_cell=cell)

    def call_stream_in(self, service: str, method: str,
                       requests: Iterator[dict],
                       timeout: Optional[float] = 300.0) -> Any:
        fn = self._channel.stream_unary(
            f"/{service}/{method}", request_serializer=pack,
            response_deserializer=unpack)
        try:
            return fn(requests, timeout=timeout,
                      metadata=self._call_metadata())
        except grpc.RpcError as e:
            _raise_typed(e)

    @classmethod
    def shutdown_pool(cls) -> None:
        with cls._pool_lock:
            for ch in cls._pool.values():
                ch.close()
            cls._pool.clear()
