"""Table master RPC surface + client.

Re-design of ``core/transport/src/main/proto/grpc/table/
table_master.proto`` (AttachDatabase/GetAllDatabases/GetAllTables/
GetTable/SyncDatabase/Transform*) on the msgpack plane.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from alluxio_tpu.rpc.core import RpcChannel, ServiceDefinition
from alluxio_tpu.utils.retry import ExponentialTimeBoundedRetry, retry

TABLE_SERVICE = "table_master"


def table_master_service(table_master,
                         permission_checker=None) -> ServiceDefinition:
    """Catalog mutations (attach/detach/sync/transform) are superuser-
    gated, exactly as the meta admin RPCs are (``master_service.py``
    ``check_superuser``): an arbitrary authenticated user must not be able
    to attach UDBs, rewrite data under ``_transformed/``, or detach the
    catalog. Reads stay open."""
    svc = ServiceDefinition(TABLE_SERVICE)

    def _require_admin() -> None:
        if permission_checker is not None:
            from alluxio_tpu.security.user import authenticated_user

            permission_checker.check_superuser(authenticated_user())

    def _attach(r):
        _require_admin()
        return {"db": table_master.attach_database(
            r["udb_type"], r["connection"], r.get("db_name", ""),
            options=r.get("options") or {})}

    def _detach(r):
        _require_admin()
        table_master.detach_database(r["db"])
        return {}

    def _sync(r):
        _require_admin()
        return {"tables": table_master.sync_database(r["db"])}

    def _transform(r):
        _require_admin()
        return {"job_id": table_master.transform_table(
            r["db"], r["table"],
            definition=r.get("definition", "compact"),
            options=r.get("options"))}

    svc.unary("attach_database", _attach)
    svc.unary("detach_database", _detach)
    svc.unary("sync_database", _sync)
    svc.unary("get_all_databases", lambda r: {
        "dbs": table_master.list_databases()})
    svc.unary("get_all_tables", lambda r: {
        "tables": table_master.list_tables(r["db"])})
    svc.unary("get_table", lambda r: {
        "table": table_master.get_table(r["db"], r["table"])})
    svc.unary("transform_table", _transform)
    svc.unary("transform_status", lambda r: {
        "info": table_master.transform_status(r["job_id"])})
    return svc


class TableMasterClient:
    """Typed retrying client (reference: ``table/client/.../
    RetryHandlingTableMasterClient.java``)."""

    service = TABLE_SERVICE

    def __init__(self, address: str, *,
                 retry_duration_s: "Optional[float]" = None,
                 metadata=None, conf=None) -> None:
        """``retry_duration_s`` falls back to ``conf``'s
        ``atpu.user.rpc.retry.duration`` (30s default) — the previously
        hard-coded constant, now tunable for overload drills."""
        from alluxio_tpu.rpc.clients import resolve_retry_duration_s

        self._channel = RpcChannel(address, metadata=metadata)
        self._retry_duration_s = resolve_retry_duration_s(
            retry_duration_s, conf)

    def _call(self, method: str, request: dict, timeout: float = 60.0):
        return retry(
            lambda: self._channel.call(self.service, method, request,
                                       timeout=timeout),
            ExponentialTimeBoundedRetry(self._retry_duration_s, 0.05, 3.0))

    def attach_database(self, udb_type: str, connection: str,
                        db_name: str = "", options: dict = None) -> str:
        return self._call("attach_database", {
            "udb_type": udb_type, "connection": connection,
            "db_name": db_name, "options": options or {}})["db"]

    def detach_database(self, db: str) -> None:
        self._call("detach_database", {"db": db})

    def sync_database(self, db: str) -> int:
        return self._call("sync_database", {"db": db})["tables"]

    def get_all_databases(self) -> List[str]:
        return self._call("get_all_databases", {})["dbs"]

    def get_all_tables(self, db: str) -> List[str]:
        return self._call("get_all_tables", {"db": db})["tables"]

    def get_table(self, db: str, table: str) -> Dict[str, Any]:
        return self._call("get_table", {"db": db, "table": table})["table"]

    def transform_table(self, db: str, table: str, *,
                        definition: str = "compact",
                        options: Optional[Dict[str, Any]] = None) -> int:
        return self._call("transform_table", {
            "db": db, "table": table, "definition": definition,
            "options": options})["job_id"]

    def transform_status(self, job_id: int) -> Dict[str, Any]:
        return self._call("transform_status", {"job_id": job_id})["info"]
