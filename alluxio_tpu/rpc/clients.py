"""Typed, retrying RPC clients.

Re-design of ``client/file/RetryHandlingFileSystemMasterClient.java``,
``client/block/RetryHandlingBlockMasterClient.java`` and
``AbstractMasterClient``: every call runs under an exponential time-bounded
retry on transient errors; surfaces mirror the in-process adapters so the
rest of the stack cannot tell transport from direct calls.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from alluxio_tpu.rpc.core import RpcChannel
from alluxio_tpu.rpc.master_service import (
    BLOCK_SERVICE, FS_SERVICE, META_SERVICE,
)
from alluxio_tpu.rpc.worker_service import WORKER_SERVICE
from alluxio_tpu.utils.retry import ExponentialTimeBoundedRetry, retry
from alluxio_tpu.utils.wire import (
    BlockInfo, FileBlockInfo, FileInfo, MountPointInfo, WorkerInfo,
    WorkerNetAddress,
)

#: (registry, counters) cache — the failover counters sit on every RPC
#: attempt, so resolve them once per registry generation, not per call
#: (tests swap the registry via reset_metrics, hence the identity key)
_failover_metrics_cache: Tuple[object, object] = (None, None)


def _failover_metrics():
    global _failover_metrics_cache
    from alluxio_tpu.metrics import metrics

    reg = metrics()
    cached_reg, counters = _failover_metrics_cache
    if cached_reg is not reg:
        counters = (reg.counter("Client.FailoverRedirects"),
                    reg.counter("Client.FailoverRotations"),
                    reg.counter("Client.StandbyReads"))
        _failover_metrics_cache = (reg, counters)
    return counters


def resolve_retry_duration_s(value: Optional[float] = None,
                             conf=None) -> float:
    """The client RPC retry budget: an explicit value wins, else the
    ``atpu.user.rpc.retry.duration`` conf key, else the historical
    30s constant.  One resolver for every typed client (fs/block/meta,
    job, table) so overload drills shorten give-up time everywhere by
    setting one key."""
    if value is not None:
        return float(value)
    if conf is not None:
        from alluxio_tpu.conf import Keys

        return float(conf.get_duration_s(Keys.USER_RPC_RETRY_MAX_DURATION))
    return 30.0


class _BaseClient:
    """Multi-endpoint master client (reference: ``MasterInquireClient`` +
    ``AbstractMasterClient`` re-resolving the leader across the
    configured masters).  ``address`` may be a comma-separated list for
    HA deployments; the client then

    - follows **leader hints**: a standby's typed ``NotPrimaryError``
      names the current primary, and the client jumps straight to it
      without consuming a retry attempt (``retry.note_redirect``);
    - **rotates** with full-jitter backoff on connection loss /
      hint-less unavailability, so a dead primary's clients fan out
      over the survivors instead of stampeding one;
    - optionally routes **reads to standbys**
      (``atpu.user.standby.reads.enabled``): read-marked RPCs
      round-robin across the non-active members (endpoints that
      recently failed sit out a short cooldown), keeping GetStatus/
      ListStatus load off the primary (docs/ha.md)."""

    service = ""

    #: seconds a failed endpoint sits out of standby-read rotation
    _DOWN_COOLDOWN_S = 3.0

    def __init__(self, address: str, *,
                 retry_duration_s: Optional[float] = None,
                 base_sleep_s: float = 0.05, max_sleep_s: float = 3.0,
                 metadata=None, fastpath: bool = True,
                 fastpath_dir: Optional[str] = None, conf=None,
                 standby_reads: bool = False) -> None:
        """``fastpath_dir``: where master fastpath sockets live; pass the
        ``atpu.master.fastpath.dir`` property when a Configuration is at
        hand (FileSystem does) — otherwise the env override or /tmp.
        ``retry_duration_s`` defaults from ``conf``'s
        ``atpu.user.rpc.retry.duration`` (30s)."""
        import os as _os

        self._use_fast = fastpath and \
            not _os.environ.get("ATPU_FASTPATH_DISABLE")
        self._fast_dir = fastpath_dir or \
            _os.environ.get("ATPU_MASTER_FASTPATH_DIR", "/tmp")
        self._channels = []
        self._addresses: List[str] = []
        for a in str(address).split(","):
            if not a.strip():
                continue
            self._channels.append(self._make_channel(a.strip(), metadata))
            self._addresses.append(a.strip())
        self._active = 0
        self._standby_reads = bool(standby_reads)
        self._read_rr = 0
        self._down_until: Dict[int, float] = {}
        self._endpoints_lock = threading.Lock()
        self._metadata = metadata
        self._retry_duration_s = resolve_retry_duration_s(
            retry_duration_s, conf)
        self._base_sleep_s = base_sleep_s
        self._max_sleep_s = max_sleep_s

    def _make_channel(self, address: str, metadata):
        from alluxio_tpu.rpc.fastpath import HybridChannel

        ch = RpcChannel(address, metadata=metadata)
        if self._use_fast:
            # probes <dir>/atpu-master-<port>.sock; silently stays
            # pure-gRPC when the master is remote or fastpath is off
            ch = HybridChannel(ch, fastpath_dir=self._fast_dir)
        return ch

    @property
    def _channel(self) -> RpcChannel:
        return self._channels[self._active]

    def _rotate(self) -> None:
        self._active = (self._active + 1) % len(self._channels)

    def _follow_leader(self, leader: str) -> None:
        """Point the active (write) endpoint at the hinted primary,
        minting a channel when the hint names a master outside the
        configured list (e.g. a replacement member)."""
        leader = leader.strip()
        with self._endpoints_lock:
            try:
                self._active = self._addresses.index(leader)
            except ValueError:
                self._channels.append(
                    self._make_channel(leader, self._metadata))
                self._addresses.append(leader)
                self._active = len(self._channels) - 1

    def _mark_down(self, idx: int) -> None:
        self._down_until[idx] = time.monotonic() + self._DOWN_COOLDOWN_S

    def _handle_not_primary(self, leader, idx: int) -> None:
        """Shared redirect/rotate bookkeeping for every not-primary
        path (unary handler, strong-read conversion, stream
        establishment — keep them identical): a hinted failure follows
        the leader (the retry policy's free redirect); a hint-less one
        rotates off the endpoint, so a standby that cannot name a
        leader (mid-election, partitioned) is not re-picked for the
        whole retry budget."""
        redirects, rotations, _ = _failover_metrics()
        if leader:
            self._follow_leader(leader)
            redirects.inc()
        elif len(self._channels) > 1:
            if idx == self._active:
                self._rotate()
            rotations.inc()

    def _pick(self, read: bool) -> int:
        """Endpoint for this attempt: writes (and single-endpoint
        clients) go to the believed leader; standby-routed reads
        round-robin the OTHER members, falling back to the leader when
        every standby is cooling down."""
        if not (read and self._standby_reads and len(self._channels) > 1):
            return self._active
        now = time.monotonic()
        n = len(self._channels)
        for _ in range(n):
            self._read_rr = (self._read_rr + 1) % n
            i = self._read_rr
            if i == self._active:
                continue
            if self._down_until.get(i, 0.0) <= now:
                return i
        return self._active

    def _call(self, method: str, request: dict, timeout: float = 30.0, *,
              read: bool = False):
        from alluxio_tpu.utils.exceptions import (
            AlluxioTpuError, NotPrimaryError, UnavailableError,
        )

        def attempt():
            idx = self._pick(read)
            try:
                out = self._channels[idx].call(
                    self.service, method, request, timeout=timeout)
                if read and isinstance(out, dict) and \
                        out.pop("standby", False):
                    hint = out.pop("leader", None)
                    if not self._standby_reads and \
                            len(self._channels) > 1:
                        # a standby served a read this client expected
                        # read-your-writes from — convert the mark back
                        # into a redirect (single-endpoint clients
                        # pointed AT a standby asked for what they got)
                        raise NotPrimaryError(
                            "read served by a standby", leader=hint)
            except NotPrimaryError as e:
                self._handle_not_primary(e.leader, idx)
                raise
            except UnavailableError:
                self._mark_down(idx)
                if idx == self._active and len(self._channels) > 1:
                    self._rotate()
                    _failover_metrics()[1].inc()
                raise
            except AlluxioTpuError as e:
                if read and e.standby and not self._standby_reads and \
                        len(self._channels) > 1:
                    # a standby answered a strong read with an ERROR off
                    # its bounded-stale state (e.g. NOT_FOUND for a path
                    # the primary just acked): as untrustworthy as a
                    # stale result — retry on the primary
                    self._handle_not_primary(e.leader, idx)
                    raise NotPrimaryError(
                        "standby answered a strong read",
                        leader=e.leader) from e
                raise
            if read and idx != self._active:
                _failover_metrics()[2].inc()
            return out

        return retry(
            attempt,
            ExponentialTimeBoundedRetry(self._retry_duration_s,
                                        self._base_sleep_s,
                                        self._max_sleep_s))


class FsMasterClient(_BaseClient):
    service = FS_SERVICE

    def get_status(self, path: str, sync_interval_ms: int = -1, *,
                   want_version: bool = False):
        """``want_version=True`` -> ``(FileInfo, stamp)`` where stamp is
        the master's metadata-invalidation version taken BEFORE the
        lookup (None against a server predating the stamp protocol) —
        what the client metadata cache stores (docs/metadata.md)."""
        resp = self._call(
            "get_status", {"path": str(path),
                           "sync_interval_ms": sync_interval_ms},
            read=True)
        stamp = resp.pop("md_version", None)
        info = FileInfo.from_wire(resp)
        return (info, stamp) if want_version else info

    def exists(self, path: str) -> bool:
        return self._call("exists", {"path": str(path)},
                          read=True)["exists"]

    @staticmethod
    def _decode_columnar(cols: dict) -> List[FileInfo]:
        """Struct-of-arrays listing wire format -> FileInfo rows (the
        one decoder for both the unary and streamed paths)."""
        if not cols:
            return []
        keys = tuple(cols)
        return [FileInfo.from_wire(dict(zip(keys, row)))
                for row in zip(*(cols[k] for k in keys))]

    def list_status(self, path: str, recursive: bool = False,
                    sync_interval_ms: int = -1, *,
                    want_version: bool = False):
        """``want_version=True`` -> ``(infos, stamp)`` — see
        :meth:`get_status`."""
        resp = self._call("list_status", {
            "path": str(path), "recursive": recursive,
            "sync_interval_ms": sync_interval_ms, "columnar": True},
            read=True)
        stamp = resp.get("md_version")
        col = resp.get("columnar")
        if col is None:  # server predates the columnar listing format
            infos = [FileInfo.from_wire(d) for d in resp["infos"]]
        else:
            infos = self._decode_columnar(col["cols"])
        return (infos, stamp) if want_version else infos

    def iter_status(self, path: str, recursive: bool = False,
                    sync_interval_ms: int = -1,
                    batch_size: int = 500):
        """Streamed listing (reference: partial-response ListStatus):
        yields FileInfo in server-side batches — constant client
        memory per batch however large the directory.

        Stream ESTABLISHMENT (up to the first chunk) rides the same
        retry + HA-rotation machinery as the unary calls; a failure
        mid-stream propagates — entries already yielded cannot be
        transparently replayed without a resume cursor."""
        from alluxio_tpu.utils.exceptions import UnavailableError

        request = {"path": str(path), "recursive": recursive,
                   "sync_interval_ms": sync_interval_ms,
                   "batch_size": batch_size, "columnar": True}

        def attempt():
            from alluxio_tpu.utils.exceptions import NotPrimaryError

            idx = self._pick(read=True)
            it = self._channels[idx].call_stream(
                self.service, "list_status_stream", request)
            try:
                first = next(it)
            except StopIteration:
                return None, it
            except NotPrimaryError as e:
                # must precede the UnavailableError arm (its subclass):
                # a deposed leader's fence or a not-yet-caught-up
                # standby names the leader — follow the hint instead of
                # cooling down a healthy member and blind-rotating
                self._handle_not_primary(e.leader, idx)
                raise
            except UnavailableError:
                self._mark_down(idx)
                if idx == self._active and len(self._channels) > 1:
                    self._rotate()
                raise
            if isinstance(first, dict) and first.get("standby") and \
                    not self._standby_reads and len(self._channels) > 1:
                # same strong-read contract as the unary path: a
                # standby-served stream redirects instead of feeding a
                # stale listing to a read-your-writes client
                hint = first.get("leader")
                self._handle_not_primary(hint, idx)
                raise NotPrimaryError("read served by a standby",
                                      leader=hint)
            return first, it

        first, it = retry(
            attempt,
            ExponentialTimeBoundedRetry(self._retry_duration_s,
                                        self._base_sleep_s,
                                        self._max_sleep_s))
        from itertools import chain

        chunks = it if first is None else chain([first], it)
        for chunk in chunks:
            cols = chunk.get("cols")
            if cols is not None:  # columnar batch (struct-of-arrays)
                yield from self._decode_columnar(cols)
            else:  # row-dict batch (pre-columnar server)
                for d in chunk.get("infos", []):
                    yield FileInfo.from_wire(d)

    def create_file(self, path: str, **opts) -> FileInfo:
        return FileInfo.from_wire(self._call(
            "create_file", {"path": str(path), **opts}))

    def create_directory(self, path: str, **opts) -> FileInfo:
        return FileInfo.from_wire(self._call(
            "create_directory", {"path": str(path), **opts}))

    def get_new_block_id(self, path: str) -> int:
        return self._call("get_new_block_id", {"path": str(path)})["block_id"]

    def complete_file(self, path: str, length: Optional[int] = None,
                      ufs_fingerprint: str = "") -> None:
        self._call("complete_file", {"path": str(path), "length": length,
                                     "ufs_fingerprint": ufs_fingerprint})

    def delete(self, path: str, recursive: bool = False,
               alluxio_only: bool = False) -> None:
        self._call("delete", {"path": str(path), "recursive": recursive,
                              "alluxio_only": alluxio_only})

    def rename(self, src: str, dst: str) -> None:
        self._call("rename", {"src": str(src), "dst": str(dst)})

    def free(self, path: str, recursive: bool = False,
             forced: bool = False) -> List[int]:
        return self._call("free", {"path": str(path), "recursive": recursive,
                                   "forced": forced})["freed_blocks"]

    def mount(self, path: str, ufs_uri: str, *, read_only: bool = False,
              shared: bool = False,
              properties: Optional[Dict[str, str]] = None) -> None:
        self._call("mount", {"path": str(path), "ufs_uri": ufs_uri,
                             "read_only": read_only, "shared": shared,
                             "properties": properties})

    def unmount(self, path: str) -> None:
        self._call("unmount", {"path": str(path)})

    def get_mount_points(self) -> List[MountPointInfo]:
        resp = self._call("get_mount_points", {})
        return [MountPointInfo.from_wire(d) for d in resp["mounts"]]

    def set_attribute(self, path: str, **opts) -> None:
        self._call("set_attribute", {"path": str(path), **opts})

    def get_file_block_info_list(self, path: str) -> List[FileBlockInfo]:
        resp = self._call("get_file_block_info_list", {"path": str(path)})
        return [FileBlockInfo.from_wire(d) for d in resp["infos"]]

    def schedule_async_persistence(self, path: str) -> None:
        self._call("schedule_async_persistence", {"path": str(path)})

    def get_pinned_file_ids(self) -> List[int]:
        return self._call("get_pinned_file_ids", {})["ids"]

    def sync_metadata(self, path: str) -> bool:
        return self._call("sync_metadata", {"path": str(path)})["changed"]

    def set_acl(self, path: str, entries: List[str], *,
                default: bool = False, recursive: bool = False) -> None:
        self._call("set_acl", {"path": str(path), "entries": entries,
                               "default": default, "recursive": recursive})

    def get_acl(self, path: str) -> dict:
        return self._call("get_acl", {"path": str(path)})

    def start_sync(self, path: str) -> None:
        self._call("start_sync", {"path": str(path)})

    def stop_sync(self, path: str) -> None:
        self._call("stop_sync", {"path": str(path)})

    def get_sync_path_list(self) -> List[str]:
        return self._call("get_sync_path_list", {})["paths"]

    def mark_persisted(self, path: str, ufs_fingerprint: str = "") -> None:
        self._call("mark_persisted", {"path": str(path),
                                      "ufs_fingerprint": ufs_fingerprint})

    def commit_persist(self, path: str, temp_ufs_path: str,
                       expected_id: int = 0) -> str:
        return self._call("commit_persist", {
            "path": str(path), "temp_ufs_path": temp_ufs_path,
            "expected_id": expected_id})["fingerprint"]

    def file_system_heartbeat(self, worker_id: int,
                              persisted_files: List[int]) -> None:
        self._call("file_system_heartbeat", {
            "worker_id": worker_id, "persisted_files": persisted_files})


class BlockMasterClient(_BaseClient):
    """Surface-compatible with ``InProcessBlockMasterClient``."""

    service = BLOCK_SERVICE

    def get_worker_id(self, address: WorkerNetAddress) -> int:
        return self._call("get_worker_id",
                          {"address": address.to_wire()})["worker_id"]

    def register(self, worker_id: int, capacity: Dict[str, int],
                 used: Dict[str, int], blocks: Dict[str, List[int]],
                 address: Optional[WorkerNetAddress] = None) -> None:
        self._call("register", {
            "worker_id": worker_id, "capacity": capacity, "used": used,
            "blocks": blocks,
            "address": address.to_wire() if address else None})

    def heartbeat(self, worker_id: int, used: Dict[str, int],
                  added: Dict[str, List[int]], removed: List[int],
                  metrics_snapshot: Optional[Dict[str, float]] = None) -> dict:
        return self._call("heartbeat", {
            "worker_id": worker_id, "used": used, "added": added,
            "removed": removed, "metrics": metrics_snapshot})

    def commit_block(self, worker_id: int, used_on_tier: int, tier: str,
                     block_id: int, length: int) -> None:
        self._call("commit_block", {
            "worker_id": worker_id, "used_on_tier": used_on_tier,
            "tier": tier, "block_id": block_id, "length": length})

    def get_block_info(self, block_id: int) -> BlockInfo:
        return BlockInfo.from_wire(self._call("get_block_info",
                                              {"block_id": block_id}))

    def report_device_blocks(self, host: str,
                             mesh_blocks: "Dict[int, List[int]]") -> None:
        """Report this client's HBM warm set (mesh pos -> block ids);
        replaces the previous report from the same host."""
        self._call("report_device_blocks", {
            "host": host,
            "mesh_blocks": {str(k): [int(b) for b in v]
                            for k, v in mesh_blocks.items()}})

    def clear_device_blocks(self, host: str) -> None:
        self.report_device_blocks(host, {})

    def device_block_map(self) -> "Dict[int, Dict[int, str]]":
        resp = self._call("device_block_map", {})
        return {int(bid): {int(p): h for p, h in m.items()}
                for bid, m in resp["map"].items()}

    def get_block_infos(self, block_ids: List[int]) -> List[BlockInfo]:
        resp = self._call("get_block_infos", {"block_ids": block_ids})
        return [BlockInfo.from_wire(d) for d in resp["infos"]]

    def get_worker_infos(self, include_lost: bool = False,
                         include_quarantined: bool = False
                         ) -> List[WorkerInfo]:
        """Default view excludes quarantined workers — it is the
        placement listing; admin/report callers opt them back in."""
        resp = self._call("get_worker_infos",
                          {"include_lost": include_lost,
                           "include_quarantined": include_quarantined})
        return [WorkerInfo.from_wire(d) for d in resp["infos"]]

    def get_capacity(self) -> Dict[str, Dict[str, int]]:
        """Returns ``{"capacity": {tier: bytes}, "used": {tier: bytes}}``."""
        return self._call("get_capacity", {})


class MetaMasterClient(_BaseClient):
    service = META_SERVICE

    def get_configuration(self, *, sources: bool = False) -> dict:
        return self._call("get_configuration", {"sources": sources})

    def get_config_hash(self) -> str:
        return self._call("get_config_hash", {})["hash"]

    def get_master_info(self) -> dict:
        return self._call("get_master_info", {})

    def get_metastore_info(self) -> dict:
        """Metastore backend shape for ``fsadmin report metastore``:
        {"stats": {kind, inodes, and on LSM memtable/run/compaction
        counters + cache hit ratio}}."""
        return self._call("get_metastore_info", {})

    def get_metrics(self) -> Dict[str, float]:
        return self._call("get_metrics", {})["metrics"]

    def set_log_level(self, level: str, logger: str = "") -> dict:
        return self._call("set_log_level", {"logger": logger,
                                            "level": level})

    def get_log_level(self, logger: str = "") -> dict:
        return self._call("get_log_level", {"logger": logger})

    def set_trace_enabled(self, enabled: bool, *,
                          clear: bool = False) -> dict:
        return self._call("set_trace_enabled",
                          {"enabled": enabled, "clear": clear})

    def get_trace(self, *, limit: int = 500, prefix: str = "",
                  trace_id: str = "") -> dict:
        return self._call("get_trace", {"limit": limit, "prefix": prefix,
                                        "trace_id": trace_id})

    def get_trace_profile(self, *, trace_id: str = "", prefix: str = "",
                          root_prefix: str = "", limit: int = 4000,
                          max_traces: int = 256) -> dict:
        """Critical-path analysis over the master's stitched traces:
        with ``trace_id`` the blocking chain of that one trace, without
        it the aggregate per-phase read-path profile."""
        return self._call("get_trace_profile", {
            "trace_id": trace_id, "prefix": prefix,
            "root_prefix": root_prefix, "limit": limit,
            "max_traces": max_traces})

    def get_quorum_info(self) -> dict:
        return self._call("get_quorum_info", {})

    def get_masters(self) -> dict:
        """Quorum view for ``fsadmin report masters``: per-master role,
        term, last-applied sequence, tailer lag and last contact
        (docs/ha.md).  Read-marked: standbys answer it too, so the view
        survives a dead primary."""
        return self._call("get_masters", {}, read=True)

    def transfer_quorum_leadership(self, target: str) -> dict:
        return self._call("transfer_quorum_leadership",
                          {"target": target})

    def set_path_conf(self, path: str, properties: Dict[str, str]) -> None:
        self._call("set_path_conf", {"path": str(path),
                                     "properties": properties})

    def remove_path_conf(self, path: str,
                         keys: Optional[List[str]] = None) -> None:
        self._call("remove_path_conf", {"path": str(path), "keys": keys})

    def get_path_conf(self) -> dict:
        """{"properties": {path: {k: v}}, "hash": str}"""
        return self._call("get_path_conf", {})

    def register_node_conf(self, node_id: str,
                           config: Dict[str, str]) -> None:
        self._call("register_node_conf", {"node_id": node_id,
                                          "config": config})

    def metrics_heartbeat(self, source: str,
                          metrics: Dict[str, float],
                          spans: Optional[List[dict]] = None,
                          md_cache_version: Optional[int] = None,
                          want_md_invalidations: bool = False,
                          profile: Optional[dict] = None) -> dict:
        """Ship a node's metric snapshot — and any completed trace spans
        drained from its ring — for cluster aggregation / trace
        stitching (reference: ``metric_master.proto`` ClientMasterSync).
        The response may carry a remediation config overlay
        (``conf_overlay`` + ``conf_overlay_version``) the client is
        expected to apply — see docs/self_healing.md — and, when
        ``want_md_invalidations`` is set, the metadata-cache
        invalidation batch since ``md_cache_version``
        (``md_invalidations`` — docs/metadata.md)."""
        req = {"source": source, "metrics": metrics, "spans": spans or []}
        if profile is not None:
            # merged flame data from the node's stack sampler
            # (utils/profiler.py) rides the same heartbeat
            req["profile"] = profile
        if want_md_invalidations:
            req["want_md_invalidations"] = True
            req["md_cache_version"] = md_cache_version
        return self._call("metrics_heartbeat", req)

    def get_metrics_history(self, name: str = "", *, source: str = "",
                            resolution: str = "raw", since: float = 0.0,
                            rate: bool = False, limit: int = 0,
                            prefix: str = "") -> dict:
        """Time-resolved metric series from the master's history store.
        No ``name`` -> ``{"names": [...], "stats": {...}}``; with one ->
        ``{"series": [{source, name, resolution, points, ended_at}],
        "stats": {...}}``."""
        return self._call("get_metrics_history", {
            "name": name, "source": source, "resolution": resolution,
            "since": since, "rate": rate, "limit": limit,
            "prefix": prefix})

    def get_health(self, *, evaluate: bool = True) -> dict:
        """Ranked alerts from the master's health-rule engine
        (cluster doctor)."""
        return self._call("get_health", {"evaluate": evaluate})

    def get_qos(self) -> dict:
        """Admission-control state + per-principal shed/admit rows +
        cluster Qos metrics (`fsadmin report qos`)."""
        return self._call("get_qos", {})

    def get_config_report(self) -> dict:
        return self._call("get_config_report", {})

    def checkpoint(self) -> None:
        self._call("checkpoint", {}, timeout=300.0)

    def backup(self, directory: Optional[str] = None) -> dict:
        return self._call("backup", {"directory": directory}, timeout=600.0)


class WorkerClient(_BaseClient):
    """Data-plane client for one worker (reference: block streams +
    short-circuit RPCs in ``client/block/stream``).

    Beyond the default channel, the client can mint **pooled channels**
    — distinct TCP connections to the same worker — so the striped
    remote-read path fans stripes of one block out over several
    connections instead of serializing them behind one HTTP/2 flow-
    control window (reference: GrpcConnectionPool's per-NetworkGroup
    channel multiplicity)."""

    service = WORKER_SERVICE

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._pooled: Dict[int, "RpcChannel"] = {}
        self._pooled_lock = threading.Lock()

    def pooled_channel(self, index: int) -> RpcChannel:
        """Channel for pool slot ``index`` (0 = the default channel).
        Channels are created lazily and cached for the client's life;
        the process-wide gRPC channel pool dedupes across clients."""
        if index == 0:
            return self._channel
        with self._pooled_lock:
            ch = self._pooled.get(index)
            if ch is None:
                ch = RpcChannel(self._channels[0].address,
                                metadata=self._metadata, pool_index=index)
                self._pooled[index] = ch
            return ch

    def read_block(self, block_id: int, *, offset: int = 0, length: int = -1,
                   chunk_size: int = 1 << 20,
                   ufs: Optional[dict] = None,
                   cache: bool = True) -> Iterator[dict]:
        return self._channel.call_stream(self.service, "read_block", {
            "block_id": block_id, "offset": offset, "length": length,
            "chunk_size": chunk_size, "ufs": ufs, "cache": cache})

    def read_block_stream(self, block_id: int, *, offset: int = 0,
                          length: int = -1, chunk_size: int = 1 << 20,
                          ufs: Optional[dict] = None, cache: bool = True,
                          channel: int = 0):
        """Cancellable ``read_block`` range stream over pool slot
        ``channel`` — the striped read path's transport (it must abort
        hedge losers mid-transfer, which plain ``read_block`` cannot)."""
        return self.pooled_channel(channel).open_stream(
            self.service, "read_block", {
                "block_id": block_id, "offset": offset, "length": length,
                "chunk_size": chunk_size, "ufs": ufs, "cache": cache})

    def read_block_bytes(self, block_id: int, **kwargs) -> bytes:
        return b"".join(msg["data"] for msg in
                        self.read_block(block_id, **kwargs))

    def read_many(self, block_id: int, offsets, sizes) -> dict:
        """Scatter/gather batch read: N small reads of one block in ONE
        RPC — ``{data: <concatenated bytes>, lengths: [..], source}``.
        The caller slices per-op views out of ``data`` (the response
        lands in one buffer; no per-op payloads to reassemble)."""
        return self._call("read_many", {
            "block_id": block_id, "offsets": list(offsets),
            "sizes": list(sizes)})

    def shm_open(self, session_id: int, block_id: int) -> dict:
        """Lease the block's same-host SHM segment:
        ``{lease_id, path, length, ttl_s}``. Raises typed
        ShmLeaseDeniedError / ShmSegmentUnavailableError — the caller's
        cue to fall back to the remote path (shm/)."""
        return self._call("shm_open", {"session_id": session_id,
                                       "block_id": block_id})

    def shm_renew(self, session_id: int, lease_id: int) -> dict:
        return self._call("shm_renew", {"session_id": session_id,
                                        "lease_id": lease_id})

    def shm_release(self, session_id: int, lease_id: int) -> None:
        # advisory like close_local_block: the worker's TTL reclaims it
        # anyway — short deadline, no retry against a dead worker
        self._channel.call(self.service, "shm_release",
                           {"session_id": session_id,
                            "lease_id": lease_id}, timeout=2.0)

    def write_block(self, block_id: int, session_id: int, data: bytes, *,
                    tier: str = "", chunk_size: int = 1 << 20,
                    pinned: bool = False) -> int:
        def gen():
            yield {"block_id": block_id, "session_id": session_id,
                   "tier": tier, "size_hint": len(data), "pinned": pinned}
            for i in range(0, len(data), chunk_size):
                yield {"data": data[i:i + chunk_size]}

        resp = self._channel.call_stream_in(self.service, "write_block", gen())
        return resp["length"]

    def open_local_block(self, session_id: int, block_id: int) -> dict:
        return self._call("open_local_block", {"session_id": session_id,
                                               "block_id": block_id})

    def close_local_block(self, session_id: int, block_id: int) -> None:
        # advisory lease release: the worker's session cleanup expires it
        # anyway, so NO retry and a short deadline — a GC-time close of a
        # leaked stream against a dead cluster must not block for the
        # full retry window (observed: 30s stalls on the caller's thread)
        self._channel.call(self.service, "close_local_block",
                           {"session_id": session_id,
                            "block_id": block_id}, timeout=2.0)

    def create_local_block(self, session_id: int, block_id: int, *,
                           size_hint: int, tier: str = "") -> str:
        return self._call("create_local_block", {
            "session_id": session_id, "block_id": block_id,
            "size_hint": size_hint, "tier": tier})["path"]

    def complete_local_block(self, session_id: int, block_id: int, *,
                             cancel: bool = False,
                             pinned: bool = False) -> None:
        self._call("complete_local_block", {
            "session_id": session_id, "block_id": block_id,
            "cancel": cancel, "pinned": pinned})

    def async_cache(self, block_id: int, ufs_path: str, offset: int,
                    length: int, mount_id: int = 0,
                    qos_class: str = "") -> bool:
        """``qos_class``: "ASYNC_FILL" (default) or "PREFETCH" — with
        worker QoS on, speculative loads drain after client-issued
        fills and on-demand reads."""
        return self._call("async_cache", {
            "block_id": block_id, "ufs_path": ufs_path, "offset": offset,
            "length": length, "mount_id": mount_id,
            "qos_class": qos_class})["accepted"]

    def prefetch_pin(self, block_id: int, ttl_s: float = 600.0) -> bool:
        """Eviction shield for a clairvoyantly-placed block (held until
        ``prefetch_unpin`` or TTL expiry — the worker reclaims pins of
        clients that died without unpinning; no lease to keep alive)."""
        return self._call("prefetch_pin", {"block_id": block_id,
                                           "ttl_s": ttl_s})["pinned"]

    def prefetch_unpin(self, block_id: int) -> None:
        self._call("prefetch_unpin", {"block_id": block_id})

    def remove_block(self, block_id: int) -> None:
        self._call("remove_block", {"block_id": block_id})

    def move_block(self, block_id: int, tier: str) -> None:
        self._call("move_block", {"block_id": block_id, "tier": tier})

    def cleanup_session(self, session_id: int) -> None:
        self._call("cleanup_session", {"session_id": session_id})

    def persist_file(self, ufs_path: str, block_ids: List[int],
                     mount_id: int = 0) -> str:
        return self._call("persist_file", {
            "ufs_path": ufs_path, "block_ids": block_ids,
            "mount_id": mount_id}, timeout=300.0)["fingerprint"]
