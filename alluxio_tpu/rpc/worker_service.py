"""Worker data-server RPC service.

Re-design of ``core/server/worker/.../grpc/{GrpcDataServer.java:50,
BlockReadHandler.java:59,BlockWriteHandler,ShortCircuitBlockReadHandler,
ShortCircuitBlockWriteHandler}.java`` + ``grpc/block_worker.proto:13-29``:

- ``read_block``: server-stream of chunks; cold blocks fall back to UFS
  read-through when the request carries a UFS descriptor. gRPC's own HTTP/2
  flow control replaces the reference's hand-rolled ``offset_received``
  receipts.
- ``write_block``: client-stream (header, chunks..., commit) -> length.
- ``open_local_block`` / ``close_local_block``: short-circuit **path
  leases** for same-host clients; the server holds the shared block lock
  until the lease closes, exactly like the reference's lease stream.
- ``async_cache``, ``remove_block``, ``move_block``: unary control ops.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, Tuple

from alluxio_tpu.rpc.core import ServiceDefinition
from alluxio_tpu.utils.exceptions import (
    BlockDoesNotExistError, InvalidArgumentError, best_effort,
)
from alluxio_tpu.worker.process import BlockWorker
from alluxio_tpu.worker.ufs_io import UfsBlockDescriptor

WORKER_SERVICE = "atpu.BlockWorker"

DEFAULT_CHUNK = 1 << 20
#: Worker.ReadBlockTime (per-MiB warm produce time, feeds the
#: read-latency-p99-regression health rule) is only sampled for reads
#: of at least this many bytes served in chunks of at least this size:
#: below either bound, the fixed per-read-call cost dominates the
#: normalized figure and bills a client's configuration to the host
P99_SAMPLE_MIN_BYTES = 1 << 18
P99_SAMPLE_MIN_CHUNK = 1 << 16


class _LeaseRegistry:
    def __init__(self) -> None:
        self._leases: Dict[Tuple[int, int], object] = {}
        self._lock = threading.Lock()

    def put(self, session_id: int, block_id: int, lease) -> None:
        with self._lock:
            old = self._leases.pop((session_id, block_id), None)
            self._leases[(session_id, block_id)] = lease
        if old is not None:
            old.close()

    def close(self, session_id: int, block_id: int) -> bool:
        with self._lock:
            lease = self._leases.pop((session_id, block_id), None)
        if lease is not None:
            lease.close()
            return True
        return False

    def close_session(self, session_id: int) -> None:
        with self._lock:
            victims = [k for k in self._leases if k[0] == session_id]
            leases = [self._leases.pop(k) for k in victims]
        for lease in leases:
            lease.close()


def _principal() -> str:
    """The authenticated caller's name, for per-tenant QoS accounting;
    empty (one anonymous tenant) when the worker runs no authenticator
    (QoS disabled) or the call is in-process."""
    from alluxio_tpu.security.user import authenticated_user

    user = authenticated_user()
    return user.name if user is not None else ""


def worker_service(worker: BlockWorker) -> ServiceDefinition:
    svc = ServiceDefinition(WORKER_SERVICE)
    leases = _LeaseRegistry()
    worker._short_circuit_leases = leases  # session cleanup hook

    # ---------------------------------------------------------- read stream
    def read_block(req: dict) -> Iterator[dict]:
        """Chunks carry ``source`` — the serving tier alias (MEM/SSD/...)
        or ``UFS`` for a cold read-through — so clients can attribute
        every byte to the tier that produced it (input doctor).
        Warm serving speed is timed into ``Worker.ReadBlockTime``: its
        per-worker ``.p99`` rides the metrics heartbeat and is what the
        master's read-latency-regression health rule compares against
        the fleet median, so the sample must isolate *this host's*
        serving speed — only the tier ``r.read`` calls are timed (per-
        chunk RPC framing is excluded, or a client's small-chunk config
        would inflate this host's number), one sample per stream
        normalized to seconds-per-MiB, excluding yield suspension (the
        client paces its own drain), the post-last-chunk cache-fill
        commit wait, and UFS-sourced chunks (cold read-through latency
        is the UFS's, tracked by ``Worker.UfsFetch*``).  One generator,
        no wrapper: stream cancel (hedged remote reads cancel losers
        routinely) closes it directly, the ``with`` releases the block
        reader's eviction pin NOW, and the ``finally`` still records
        the partial progress."""
        import time as _time

        from alluxio_tpu.metrics import metrics
        from alluxio_tpu.utils import faults
        from alluxio_tpu.utils.tracing import current_span

        clock = _time.monotonic
        fault_host = worker.address.tiered_identity.value("host") \
            or worker.address.host
        block_id = req["block_id"]
        offset = req.get("offset", 0)
        length = req.get("length", -1)
        # clamp: chunk_size<=0 from a buggy client would spin the
        # cached-tier loop forever without advancing pos
        chunk = max(1, req.get("chunk_size", DEFAULT_CHUNK))
        m = metrics()
        # the server span (opened by the RPC wrapper) stays live across
        # the generator's resumptions on this thread; phase timings are
        # accumulated locally and emitted ONCE at stream end
        sp = current_span()
        if worker.store.has_block(block_id):
            produce_s = 0.0
            produced_b = 0
            wire_s = 0.0
            try:
                # open_reader emits the ``lock_wait`` phase itself
                # (tiered_store.get_reader times the block-lock acquire)
                with worker.open_reader(block_id) as r:
                    tier = r.tier_alias or "MEM"
                    m.counter(f"Worker.BlocksServed.{tier}").inc()
                    served = m.counter(f"Worker.BytesServed.{tier}")
                    end = r.length if length < 0 \
                        else min(r.length, offset + length)
                    pos = offset
                    while pos < end:  # the reference's hot loop
                        n = min(chunk, end - pos)
                        t0 = clock()
                        data = r.read(pos, n)
                        if faults.armed():
                            # inside the timed region on purpose: the
                            # injected straggler must show up in
                            # Worker.ReadBlockTime (and thus in the
                            # p99-regression rule) like a real one
                            faults.injector().maybe_sleep_read(
                                fault_host)
                        produce_s += clock() - t0
                        produced_b += len(data)
                        if sp is None:
                            yield {"data": data, "offset": pos,
                                   "source": tier}
                        else:
                            # yield suspension = grpc serialize + send
                            # + HTTP/2 flow control: the per-op RPC
                            # overhead the microscope exists to expose
                            t_y = clock()
                            yield {"data": data, "offset": pos,
                                   "source": tier}
                            wire_s += clock() - t_y
                        served.inc(n)
                        pos += n
            finally:
                if sp is not None:
                    sp.phase("tier_read", produce_s * 1000.0)
                    sp.phase("wire", wire_s * 1000.0)
                # sample only reads whose per-MiB figure the fixed
                # per-read-call overhead cannot skew: a client-chosen
                # tiny chunk size multiplies that fixed cost into
                # ms/MiB (1 KiB chunks = 1024 calls/MiB), and a tiny
                # read scales one call's cost by up to 2^20/bytes —
                # either would false-fire the p99 fleet-regression
                # rule against a healthy host
                if produced_b >= P99_SAMPLE_MIN_BYTES and \
                        chunk >= P99_SAMPLE_MIN_CHUNK:
                    m.timer("Worker.ReadBlockTime").update(
                        produce_s * ((1 << 20) / produced_b))
            return
        ufs = req.get("ufs")
        if not ufs:
            raise BlockDoesNotExistError(
                f"block {block_id} not cached and no UFS fallback given")
        desc = UfsBlockDescriptor(
            block_id=block_id, ufs_path=ufs["ufs_path"],
            offset=ufs["offset"], length=ufs["length"],
            mount_id=ufs.get("mount_id", 0))
        # streaming read-through: chunks go out as stripes land, so the
        # client's first byte costs one stripe, not the whole block; the
        # tiered-store fill proceeds in parallel inside the fetch.
        # A blocked reader is ON_DEMAND — it overtakes (and, when
        # coalescing, promotes) queued background fills — and carries
        # the caller's principal for the per-tenant stripe caps
        fetch = worker.open_ufs_fetch(desc, cache=req.get("cache", True),
                                      tenant=_principal())
        m.counter("Worker.BlocksServed.UFS").inc()
        served = m.counter("Worker.BytesServed.UFS")
        end = desc.length if length < 0 else min(desc.length,
                                                 offset + length)
        pos = offset
        wire_s = 0.0
        for data in fetch.iter_range(offset, max(0, end - offset),
                                     chunk_size=chunk):
            if sp is None:
                yield {"data": data, "offset": pos, "source": "UFS"}
            else:
                t_y = clock()
                yield {"data": data, "offset": pos, "source": "UFS"}
                wire_s += clock() - t_y
            served.inc(len(data))
            pos += len(data)
        if sp is not None:
            sp.phase("wire", wire_s * 1000.0)
        # the cache-fill commit trails the last stripe; close the
        # stream only once it lands so "read completed" keeps implying
        # "block cached" for clients and heartbeats (seed semantics).
        # A fetch that FAILED after serving this sub-range fails the
        # stream too (the old whole-block path failed such reads); a
        # slow commit alone (timeout, error is None) stays best-effort
        if not fetch.wait_done(30.0) and fetch.error is not None:
            raise fetch.error if isinstance(fetch.error, Exception) \
                else IOError(str(fetch.error))

    svc.stream_out("read_block", read_block)

    # -------------------------------------------------- scatter/gather read
    def read_many(req: dict) -> dict:
        """Batch of small reads against ONE block, served in one RPC:
        ``{block_id, offsets: [..], sizes: [..]}`` -> one concatenated
        payload + per-op lengths. One reader open, one block lock, one
        serialization — the per-op RPC cost the random-4k drill showed
        dominating (``wire`` ~85% of self-time) is paid once per batch
        instead of once per read. Ops are served in request order; a
        short read (op past EOF) yields a short slice, matching what
        the same per-op ``read_block`` calls would return."""
        import time as _time

        from alluxio_tpu.metrics import metrics
        from alluxio_tpu.utils.tracing import current_span

        block_id = req["block_id"]
        offsets = req["offsets"]
        sizes = req["sizes"]
        if len(offsets) != len(sizes):
            raise InvalidArgumentError(
                f"read_many: {len(offsets)} offsets vs {len(sizes)} sizes")
        m = metrics()
        sp = current_span()
        t0 = _time.perf_counter()
        lengths = []
        parts = []
        with worker.open_reader(block_id) as r:
            tier = r.tier_alias or "MEM"
            served = m.counter(f"Worker.BytesServed.{tier}")
            for off, size in zip(offsets, sizes):
                data = r.read(off, max(0, size))
                parts.append(data)
                lengths.append(len(data))
                served.inc(len(data))
        m.counter(f"Worker.BlocksServed.{tier}").inc()
        m.counter("Worker.BatchReadOps").inc(len(offsets))
        if sp is not None:
            # the whole gather is one tier_read burst; batch_read is the
            # assembly slice the critical-path analyzer attributes to
            # this subsystem
            sp.phase("batch_read", (_time.perf_counter() - t0) * 1000.0)
        return {"data": b"".join(parts), "lengths": lengths,
                "source": tier}

    svc.unary("read_many", read_many)

    # ------------------------------------------------------ shm lease plane
    def shm_open(req: dict) -> dict:
        return worker.shm_store.open(req["session_id"], req["block_id"])

    def shm_renew(req: dict) -> dict:
        return worker.shm_store.renew(req["session_id"], req["lease_id"])

    def shm_release(req: dict) -> dict:
        return {"released": worker.shm_store.release(
            req["session_id"], req["lease_id"])}

    svc.unary("shm_open", shm_open)
    svc.unary("shm_renew", shm_renew)
    svc.unary("shm_release", shm_release)

    # ---------------------------------------------------------- write stream
    def write_block(requests: Iterator[dict]) -> dict:
        header = next(requests)
        block_id = header["block_id"]
        session_id = header["session_id"]
        tier = header.get("tier", "")
        worker.create_block(session_id, block_id,
                            initial_bytes=header.get("size_hint", DEFAULT_CHUNK),
                            tier_alias=tier)
        length = 0
        try:
            with worker.get_temp_writer(session_id, block_id) as w:
                for msg in requests:
                    if msg.get("cancel"):
                        raise InvalidArgumentError("write cancelled")
                    data = msg.get("data")
                    if data:
                        w.append(data)
                        length += len(data)
            worker.commit_block(session_id, block_id,
                                pinned=header.get("pinned", False))
        except BaseException:
            best_effort("write abort", worker.abort_block,
                        session_id, block_id)
            raise
        return {"length": length}

    svc.stream_in("write_block", write_block)

    # ------------------------------------------------------- short circuit
    def open_local_block(req: dict) -> dict:
        lease = worker.open_local_block(req["block_id"])
        leases.put(req["session_id"], req["block_id"], lease)
        return {"path": lease.path, "length": lease.length}

    def close_local_block(req: dict) -> dict:
        return {"closed": leases.close(req["session_id"], req["block_id"])}

    def create_local_block(req: dict) -> dict:
        path = worker.create_block(
            req["session_id"], req["block_id"],
            initial_bytes=req.get("size_hint", DEFAULT_CHUNK),
            tier_alias=req.get("tier", ""))
        return {"path": path}

    def complete_local_block(req: dict) -> dict:
        if req.get("cancel"):
            worker.abort_block(req["session_id"], req["block_id"])
        else:
            worker.commit_block(req["session_id"], req["block_id"],
                                pinned=req.get("pinned", False))
        return {}

    svc.unary("open_local_block", open_local_block)
    svc.unary("close_local_block", close_local_block)
    svc.unary("create_local_block", create_local_block)
    svc.unary("complete_local_block", complete_local_block)

    # -------------------------------------------------------------- control
    def async_cache(r: dict) -> dict:
        """``qos_class`` (optional wire string, default ASYNC_FILL)
        lets the prefetch agent tag its speculative loads PREFETCH so
        they drain after client-issued fills and on-demand reads."""
        from alluxio_tpu.qos import priority_from_name

        return {"accepted": worker.async_cache.submit(
            UfsBlockDescriptor(
                block_id=r["block_id"], ufs_path=r["ufs_path"],
                offset=r["offset"], length=r["length"],
                mount_id=r.get("mount_id", 0)),
            priority=priority_from_name(r.get("qos_class", "")),
            tenant=_principal())}

    svc.unary("async_cache", async_cache)
    svc.unary("prefetch_pin", lambda r: {
        "pinned": worker.store.pin_prefetch(r["block_id"],
                                            r.get("ttl_s", 600.0))})
    svc.unary("prefetch_unpin", lambda r: (
        worker.store.unpin_prefetch(r["block_id"]), {})[-1])
    svc.unary("remove_block", lambda r: (
        worker.store.remove_block(r["block_id"]), {})[-1])
    svc.unary("move_block", lambda r: (
        worker.store.move_block(r["block_id"], r["tier"]), {})[-1])
    svc.unary("session_heartbeat", lambda r: {})
    svc.unary("persist_file", lambda r: {"fingerprint": worker.persist_file(
        r["ufs_path"], r["block_ids"], r.get("mount_id", 0))})

    def cleanup_session(req: dict) -> dict:
        leases.close_session(req["session_id"])
        worker.cleanup_session(req["session_id"])
        return {}

    svc.unary("cleanup_session", cleanup_session)
    return svc
