"""RPC layer (reference: ``core/common/.../grpc`` + proto services)."""

from alluxio_tpu.rpc.core import RpcChannel, RpcServer, ServiceDefinition  # noqa: F401
from alluxio_tpu.rpc.clients import (  # noqa: F401
    BlockMasterClient, FsMasterClient, MetaMasterClient, WorkerClient,
)
