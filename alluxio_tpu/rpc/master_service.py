"""Master-side RPC services.

Re-design of the reference's master service handlers
(``file/FileSystemMaster{Client,Worker,Job}ServiceHandler.java``,
``block/BlockMasterClientServiceHandler`` + ``grpc/file_system_master.proto
:475-676``, ``grpc/block_master.proto:120-286``, ``grpc/meta_master.proto``):
thin translation between wire dicts and the master objects, with per-RPC
metrics (the reference's ``RpcUtils`` wrappers).
"""

from __future__ import annotations

from typing import Optional

from alluxio_tpu.conf import Configuration, Source
from alluxio_tpu.master.block_master import BlockMaster
from alluxio_tpu.master.file_master import FileSystemMaster
from alluxio_tpu.metrics import metrics
from alluxio_tpu.rpc.core import RpcServer, ServiceDefinition
from alluxio_tpu.utils.wire import WorkerNetAddress

FS_SERVICE = "atpu.FileSystemMaster"
BLOCK_SERVICE = "atpu.BlockMaster"
META_SERVICE = "atpu.MetaMaster"

#: FS RPCs a standby master serves off its tailing journal apply
#: (docs/ha.md).  Metadata sync is forced off for them — a standby
#: cannot journal the sync's effects — and everything NOT in this set
#: is refused with a typed NotPrimaryError + leader hint.
STANDBY_FS_READS = frozenset({
    "get_status", "exists", "list_status", "list_status_stream",
})

#: Meta RPCs a standby answers itself: cluster/config introspection and
#: the quorum view — the surfaces an operator needs exactly when the
#: primary is down.
STANDBY_META_READS = frozenset({
    "get_configuration", "get_config_hash", "get_master_info",
    "get_masters", "get_quorum_info", "get_metrics",
})


def _timed(name: str, fn, journal=None):
    """Per-RPC timing + (when a journal is given) deferred durability:
    every journal context the handler opens applies state immediately
    but fsyncs ONCE here, after all master locks are released — one
    group-committed flush per mutating RPC instead of one per context
    (reference: RpcUtils wrappers + AsyncJournalWriter)."""
    timer = metrics().timer(f"Master.rpc.{name}")  # resolve once

    if journal is None:
        def wrapper(req):
            with timer.time():
                return fn(req)
    else:
        def wrapper(req):
            with timer.time(), journal.deferred_durability():
                return fn(req)

    return wrapper


def fs_master_service(fsm: FileSystemMaster,
                      active_sync=None,
                      audit_writer=None) -> ServiceDefinition:
    svc = ServiceDefinition(FS_SERVICE)

    def u(name, fn, register=True):
        """Wrap ``fn`` with timing + audit; ``register=False`` returns
        the wrapped callable instead of registering a unary method
        (stream handlers reuse the same discipline for their resolve
        step)."""
        timed = _timed(name, fn, journal=fsm._journal)
        if audit_writer is None:
            if register:
                svc.unary(name, timed)
            return timed

        def audited(req):
            from alluxio_tpu.security.audit import AuditContext
            from alluxio_tpu.security.user import authenticated_user
            from alluxio_tpu.utils.exceptions import PermissionDeniedError

            user = authenticated_user()
            ctx = AuditContext(
                command=name, src_path=str(req.get("path")
                                           or req.get("src") or ""),
                dst_path=str(req.get("dst") or ""),
                user=user.name if user else "")
            try:
                return timed(req)
            except PermissionDeniedError:
                ctx.allowed = ctx.succeeded = False
                raise
            except Exception:
                ctx.succeeded = False
                raise
            finally:
                audit_writer.append(ctx)

        if register:
            svc.unary(name, audited)
        return audited

    u("set_acl", lambda r: (fsm.set_acl(
        r["path"], r.get("entries", []),
        default=r.get("default", False),
        recursive=r.get("recursive", False)), {})[-1])
    u("get_acl", lambda r: fsm.get_acl(r["path"]))

    if active_sync is not None:
        u("start_sync", lambda r: (
            active_sync.add_sync_point(r["path"]), {})[-1])
        u("stop_sync", lambda r: (
            active_sync.remove_sync_point(r["path"]), {})[-1])
        u("get_sync_path_list", lambda r: {
            "paths": active_sync.sync_points()})

    def _get_status(r):
        # stamp BEFORE the lookup: the payload is then at least as new
        # as the stamp, so any later mutation carries a larger version
        # and reaches the client as a heartbeat invalidation — the
        # client metadata cache's coherence invariant (docs/metadata.md)
        v = fsm.invalidations.version
        out = fsm.get_status(
            r["path"], sync_interval_ms=r.get("sync_interval_ms",
                                              -1)).to_wire()
        out["md_version"] = v
        return out

    u("get_status", _get_status)
    u("exists", lambda r: {"exists": fsm.exists(r["path"])})
    def _list_status_stream(r: dict):
        """Partial-response listing (reference: the streamed ListStatus
        of ``file_system_master.proto:475-590``): the full listing
        resolves once against the version-guarded cache, then ships in
        batches so a million-entry directory never rides one frame.
        Columnar-requesting clients get struct-of-arrays batches
        (sliced views of the memoized transpose — same encode win as
        the unary columnar path); recursive listings fall back to row
        dicts. Timed + audited like the unary RPCs: the listing
        resolves (and is audited) before the first chunk goes out;
        batching itself is transport work.

        ``paged=True`` (non-recursive only) switches to cursor paging:
        every batch is its own ``list_status_page`` call — own short
        lock scope, straight off the store's range scan — so a
        million-entry LSM directory streams without the master ever
        materializing it (weakly consistent across pages, stamped with
        ``md_version`` per page)."""
        batch = max(1, int(r.get("batch_size", 500)))
        if r.get("paged") and not r.get("recursive"):
            cursor = r.get("start_after")
            offset = 0
            while True:
                page = fsm.list_status_page(r["path"], start_after=cursor,
                                            limit=batch)
                yield {"infos": page["infos"], "offset": offset,
                       "md_version": page["md_version"],
                       "next": page["next"]}
                if page["next"] is None:
                    return
                offset += len(page["infos"])
                cursor = page["next"]
        res = _audited_resolve(r)
        if isinstance(res, dict):  # columnar {"n": N, "cols": {...}}
            cols, n = res["cols"], res.get("n", 0)
            keys = list(cols)
            for i in range(0, n, batch):
                yield {"cols": {k: cols[k][i:i + batch] for k in keys},
                       "offset": i, "total": n}
        else:
            for i in range(0, len(res), batch):
                yield {"infos": res[i:i + batch],
                       "offset": i, "total": len(res)}

    def _resolve(r: dict):
        if r.get("columnar") and not r.get("recursive"):
            return fsm.list_status(
                r["path"], sync_interval_ms=r.get("sync_interval_ms",
                                                  -1), columnar=True)
        return fsm.list_status(
            r["path"], recursive=r.get("recursive", False),
            sync_interval_ms=r.get("sync_interval_ms", -1), wire=True)

    _audited_resolve = u("list_status_stream.resolve", _resolve,
                         register=False)
    svc.stream_out("list_status_stream", _list_status_stream)
    def _list_status(r):
        v = fsm.invalidations.version  # stamp-before-lookup, as above
        if r.get("columnar"):
            out = {"columnar": fsm.list_status(
                r["path"], recursive=r.get("recursive", False),
                sync_interval_ms=r.get("sync_interval_ms", -1),
                columnar=True)}
        else:
            out = {"infos": fsm.list_status(
                r["path"], recursive=r.get("recursive", False),
                sync_interval_ms=r.get("sync_interval_ms", -1), wire=True)}
        out["md_version"] = v
        return out

    u("list_status", _list_status)
    u("create_file", lambda r: fsm.create_file(
        r["path"], block_size_bytes=r.get("block_size_bytes"),
        recursive=r.get("recursive", True), ttl=r.get("ttl", -1),
        ttl_action=r.get("ttl_action", "DELETE"), mode=r.get("mode"),
        owner=r.get("owner", ""), group=r.get("group", ""),
        replication_min=r.get("replication_min", 0),
        replication_max=r.get("replication_max", -1),
        cacheable=r.get("cacheable", True),
        persist_on_complete=r.get("persist_on_complete", False),
        overwrite=r.get("overwrite", False)).to_wire())
    u("create_directory", lambda r: fsm.create_directory(
        r["path"], recursive=r.get("recursive", True),
        allow_exists=r.get("allow_exists", False),
        mode=r.get("mode")).to_wire())
    u("get_new_block_id", lambda r: {
        "block_id": fsm.get_new_block_id_for_file(r["path"])})
    u("complete_file", lambda r: (
        fsm.complete_file(r["path"], length=r.get("length"),
                          ufs_fingerprint=r.get("ufs_fingerprint", "")),
        {})[-1])
    u("delete", lambda r: (
        fsm.delete(r["path"], recursive=r.get("recursive", False),
                   alluxio_only=r.get("alluxio_only", False)), {})[-1])
    u("rename", lambda r: (fsm.rename(r["src"], r["dst"]), {})[-1])
    u("free", lambda r: {"freed_blocks": fsm.free(
        r["path"], recursive=r.get("recursive", False),
        forced=r.get("forced", False))})
    u("mount", lambda r: (fsm.mount(
        r["path"], r["ufs_uri"], read_only=r.get("read_only", False),
        shared=r.get("shared", False),
        properties=r.get("properties")), {})[-1])
    u("unmount", lambda r: (fsm.unmount(r["path"]), {})[-1])
    u("get_mount_points", lambda r: {
        "mounts": [m.to_wire() for m in fsm.get_mount_points()]})
    u("set_attribute", lambda r: (fsm.set_attribute(
        r["path"], pinned=r.get("pinned"),
        pinned_media=r.get("pinned_media"), ttl=r.get("ttl"),
        ttl_action=r.get("ttl_action"), mode=r.get("mode"),
        owner=r.get("owner"), group=r.get("group"),
        replication_min=r.get("replication_min"),
        replication_max=r.get("replication_max"),
        recursive=r.get("recursive", False),
        xattr=r.get("xattr")), {})[-1])
    u("get_file_block_info_list", lambda r: {"infos": [
        i.to_wire() for i in fsm.get_file_block_info_list(r["path"])]})
    u("schedule_async_persistence", lambda r: (
        fsm.schedule_async_persistence(r["path"]), {})[-1])
    u("get_pinned_file_ids", lambda r: {
        "ids": sorted(fsm.get_pinned_file_ids())})
    u("sync_metadata", lambda r: {"changed": fsm.sync_metadata(r["path"])})
    u("mark_persisted", lambda r: (
        fsm.mark_persisted(r["path"],
                           ufs_fingerprint=r.get("ufs_fingerprint", "")),
        {})[-1])
    u("commit_persist", lambda r: {"fingerprint": fsm.commit_persist(
        r["path"], r["temp_ufs_path"],
        expected_id=r.get("expected_id", 0))})
    u("file_system_heartbeat", lambda r: (
        fsm.file_system_heartbeat(r["worker_id"],
                                  r.get("persisted_files", [])), {})[-1])
    return svc


def block_master_service(bm: BlockMaster) -> ServiceDefinition:
    svc = ServiceDefinition(BLOCK_SERVICE)

    def u(name, fn):
        svc.unary(name, _timed(name, fn, journal=bm._journal))

    u("get_worker_id", lambda r: {"worker_id": bm.get_worker_id(
        WorkerNetAddress.from_wire(r["address"]))})
    u("register", lambda r: (bm.worker_register(
        r["worker_id"], r["capacity"], r["used"], r["blocks"],
        WorkerNetAddress.from_wire(r["address"]) if r.get("address")
        else None), {})[-1])
    u("heartbeat", lambda r: bm.worker_heartbeat(
        r["worker_id"], r["used"], r.get("added", {}),
        r.get("removed", []), r.get("metrics")))
    u("commit_block", lambda r: (bm.commit_block(
        r["worker_id"], r["used_on_tier"], r["tier"], r["block_id"],
        r["length"]), {})[-1])
    u("get_block_info", lambda r: bm.get_block_info(r["block_id"]).to_wire())
    u("get_block_infos", lambda r: {"infos": [
        b.to_wire() for b in bm.get_block_infos(r["block_ids"])]})
    u("report_device_blocks", lambda r: (bm.report_device_blocks(
        r["host"], {int(k): v for k, v in r["mesh_blocks"].items()}),
        {})[-1])
    u("device_block_map", lambda r: {"map": {
        str(bid): m for bid, m in bm.device_block_map().items()}})
    # wire default EXCLUDES quarantined workers: remote callers of this
    # listing are placement choosers (write policy, UFS read-through
    # pick, prefetch agent) and quarantine works by disappearing from
    # their view; admin surfaces opt back in with include_quarantined
    u("get_worker_infos", lambda r: {"infos": [
        w.to_wire() for w in bm.get_worker_infos(
            include_lost=r.get("include_lost", False),
            include_quarantined=r.get("include_quarantined", False))]})
    u("get_capacity", lambda r: {"capacity": bm.capacity_bytes_on_tiers(),
                                 "used": bm.used_bytes_on_tiers()})
    return svc


def meta_master_service(conf: Configuration, *, cluster_id: str = "",
                        start_time_ms: int = 0,
                        safe_mode_fn=lambda: False,
                        journal=None,
                        path_properties=None,
                        config_checker=None,
                        permission_checker=None,
                        metrics_master=None,
                        health_monitor=None,
                        remediation_engine=None,
                        admission=None,
                        invalidation_log=None,
                        masters_fn=None,
                        metastore_stats_fn=None,
                        role_fn=lambda: "PRIMARY") -> ServiceDefinition:
    """Config distribution + cluster info + admin ops
    (reference: ``meta_master.proto:143-211`` — cluster-default config,
    config-hash handshake ``ConfigHashSync.java:36``, and the checkpoint
    trigger used by ``fsadmin journal checkpoint``).

    Admin ops (backup / checkpoint / path-conf mutation) are gated behind
    superuser, as the reference gates them behind admin privilege."""
    svc = ServiceDefinition(META_SERVICE)

    def _require_admin() -> None:
        if permission_checker is not None:
            from alluxio_tpu.security.user import authenticated_user

            permission_checker.check_superuser(authenticated_user())
    svc.unary("get_configuration", lambda r: {
        "properties": conf.to_map(min_source=Source.SITE_PROPERTY),
        "sources": {k: conf.source(k).name for k in
                    conf.to_map(min_source=Source.SITE_PROPERTY)}
        if r.get("sources") else {},
        "hash": conf.hash()})
    svc.unary("get_config_hash", lambda r: {"hash": conf.hash()})
    svc.unary("get_master_info", lambda r: {
        "cluster_id": cluster_id, "start_time_ms": start_time_ms,
        "safe_mode": bool(safe_mode_fn()), "role": str(role_fn())})
    # metastore backend shape (`fsadmin report metastore`, statuspage):
    # backend kind, inode population, and — on LSM — memtable/run/
    # compaction debt plus the hot-set cache hit ratio
    svc.unary("get_metastore_info", lambda r: {
        "stats": dict(metastore_stats_fn())
        if metastore_stats_fn is not None else {}})

    def _get_masters(r):
        """Quorum view behind ``fsadmin report masters`` (docs/ha.md):
        per-master role, term, applied sequence, lag and last contact,
        merged from the shared-journal registry and (EMBEDDED) live
        Raft state."""
        if masters_fn is None:
            from alluxio_tpu.utils.exceptions import FailedPreconditionError

            raise FailedPreconditionError(
                "this master does not serve a quorum view")
        return masters_fn()

    svc.unary("get_masters", _get_masters)

    def _set_log_level(r):
        """Runtime log-level control (reference:
        ``shell/src/main/java/alluxio/cli/LogLevel.java`` — the logLevel
        CLI flips log4j levels over the web port at runtime)."""
        import logging as _logging

        _require_admin()
        name = r.get("logger") or ""
        level = r["level"].upper()
        if level not in ("DEBUG", "INFO", "WARNING", "WARN", "ERROR",
                         "CRITICAL", "NOTSET"):
            from alluxio_tpu.utils.exceptions import InvalidArgumentError

            raise InvalidArgumentError(f"unknown log level {level!r}")
        level = "WARNING" if level == "WARN" else level
        _logging.getLogger(name or None).setLevel(level)
        return {"logger": name or "root", "level": level}

    def _get_log_level(r):
        import logging as _logging

        logger = _logging.getLogger(r.get("logger") or None)
        return {"logger": logger.name,
                "level": _logging.getLevelName(
                    logger.getEffectiveLevel())}

    svc.unary("set_log_level", _set_log_level)
    svc.unary("get_log_level", _get_log_level)

    def _set_trace_enabled(r):
        from alluxio_tpu.utils.tracing import set_tracing_enabled, tracer

        _require_admin()
        on = bool(r.get("enabled"))
        set_tracing_enabled(on)
        if r.get("clear"):
            tracer().clear()
        return {"enabled": on}

    def _get_trace(r):
        from alluxio_tpu.utils.tracing import stitch_spans, tracer

        stitched = stitch_spans(
            metrics_master.traces if metrics_master is not None else None,
            limit=int(r.get("limit") or 500),
            prefix=r.get("prefix") or "",
            trace_id=r.get("trace_id") or "",
            local_source="master")
        return {"enabled": tracer().enabled, **stitched}

    def _get_trace_profile(r):
        """Critical-path analysis over stitched traces: one trace id ->
        its blocking chain; no id -> the aggregate per-phase read-path
        profile (what ``fsadmin report readpath`` renders)."""
        from alluxio_tpu.utils.critical_path import analyze_trace, profile
        from alluxio_tpu.utils.tracing import stitch_spans, tracer

        trace_id = r.get("trace_id") or ""
        stitched = stitch_spans(
            metrics_master.traces if metrics_master is not None else None,
            limit=int(r.get("limit") or 4000),
            prefix=r.get("prefix") or "",
            trace_id=trace_id,
            local_source="master")
        if trace_id:
            return {"enabled": tracer().enabled,
                    "critical_path": analyze_trace(stitched["spans"])}
        return {"enabled": tracer().enabled,
                "profile": profile(
                    stitched["spans"],
                    root_prefix=r.get("root_prefix") or "",
                    max_traces=int(r.get("max_traces") or 256))}

    svc.unary("set_trace_enabled", _set_trace_enabled)
    svc.unary("get_trace", _get_trace)
    svc.unary("get_trace_profile", _get_trace_profile)
    def _get_metrics(r):
        snap = metrics().snapshot()
        if metrics_master is not None:
            snap = metrics_master.merged_snapshot(snap)
        return {"metrics": snap}

    def _metrics_heartbeat(r):
        """Worker/client metric snapshots -> cluster aggregation
        (reference: DefaultMetricsMaster + metric_master.proto).
        Requires an authenticated caller — an anonymous client must not
        be able to forge sources and inflate Cluster.* aggregates."""
        if metrics_master is not None:
            if permission_checker is not None:
                from alluxio_tpu.security.user import authenticated_user
                from alluxio_tpu.utils.exceptions import UnauthenticatedError

                if authenticated_user() is None:
                    raise UnauthenticatedError(
                        "metrics_heartbeat requires an authenticated user")
            resp = metrics_master.handle_heartbeat(r)
            if remediation_engine is not None:
                # piggyback the retuning overlay: no extra RPC, and
                # every reporting client converges within one
                # heartbeat interval of a push or revert
                overlay, version = remediation_engine.heartbeat_overlay()
                if overlay:
                    resp["conf_overlay"] = overlay
                resp["conf_overlay_version"] = version
            if invalidation_log is not None and \
                    r.get("want_md_invalidations"):
                # metadata-cache push invalidation rides the same
                # channel (docs/metadata.md): prefixes invalidated
                # since the client's applied version
                resp["md_invalidations"] = invalidation_log.since(
                    r.get("md_cache_version"))
            return resp
        return {}

    def _get_metrics_history(r):
        """Time-resolved series out of the master's history store
        (`fsadmin report history`, /api/v1/master/metrics/history).
        Without a ``name`` it lists the recorded metric names + store
        stats; with one it returns matching series at the requested
        resolution, optionally derived as a per-second rate."""
        from alluxio_tpu.utils.exceptions import FailedPreconditionError

        if metrics_master is None or metrics_master.history is None:
            raise FailedPreconditionError(
                "metrics history is disabled on this master "
                "(atpu.master.metrics.history.enabled)")
        return metrics_master.history_report(r)

    def _get_health(r):
        """Ranked health verdicts from the continuous rule engine.
        ``evaluate`` (default true) runs a fresh evaluation pass first
        so the report never serves a stale lifecycle state."""
        from alluxio_tpu.utils.exceptions import FailedPreconditionError

        if health_monitor is None:
            raise FailedPreconditionError(
                "the health-rule engine is disabled on this master "
                "(atpu.master.health.enabled)")
        resp = health_monitor.fresh_report(bool(r.get("evaluate", True)))
        if remediation_engine is not None:
            # the remediation timeline rides the health report: cause
            # (alert) and effect (action) belong on one screen
            resp["remediation"] = remediation_engine.report()
        return resp

    def _get_qos(r):
        """QoS posture in one response: admission-control state +
        per-principal rows, plus every Qos/RpcAdmission metric across
        the cluster aggregates (`fsadmin report qos`)."""
        resp = {"admission": admission.report() if admission is not None
                else {"enabled": False}}
        snap = metrics().snapshot()
        if metrics_master is not None:
            snap = metrics_master.merged_snapshot(snap)
        resp["metrics"] = {k: v for k, v in snap.items()
                           if "Qos" in k or "RpcAdmission" in k}
        return resp

    svc.unary("get_metrics", _get_metrics)
    svc.unary("metrics_heartbeat", _metrics_heartbeat)
    svc.unary("get_metrics_history", _get_metrics_history)
    svc.unary("get_health", _get_health)
    svc.unary("get_qos", _get_qos)

    def _checkpoint(r):
        _require_admin()
        if journal is None:
            from alluxio_tpu.utils.exceptions import FailedPreconditionError

            raise FailedPreconditionError(
                "this master has no journal to checkpoint")
        journal.checkpoint()
        return {}

    svc.unary("checkpoint", _checkpoint)

    def _quorum_info(r):
        """Quorum membership/roles (reference: journal_master.proto
        GetQuorumInfo behind ``fsadmin journal quorum``)."""
        if journal is None or not hasattr(journal, "quorum_info"):
            from alluxio_tpu.utils.exceptions import FailedPreconditionError

            raise FailedPreconditionError(
                "quorum info requires the EMBEDDED journal")
        return journal.quorum_info()

    def _transfer_leadership(r):
        _require_admin()
        if journal is None or not hasattr(journal, "transfer_leadership"):
            from alluxio_tpu.utils.exceptions import FailedPreconditionError

            raise FailedPreconditionError(
                "leadership transfer requires the EMBEDDED journal")
        ok = journal.transfer_leadership(str(r["target"]))
        return {"transferred": bool(ok)}

    svc.unary("get_quorum_info", _quorum_info)
    svc.unary("transfer_quorum_leadership", _transfer_leadership)

    def _backup(r):
        _require_admin()
        if journal is None or not hasattr(journal, "write_backup"):
            from alluxio_tpu.utils.exceptions import FailedPreconditionError

            raise FailedPreconditionError(
                "this master's journal does not support backups")
        import os

        from alluxio_tpu.conf import Keys
        from alluxio_tpu.utils.exceptions import InvalidArgumentError

        root = str(conf.get(Keys.MASTER_BACKUP_DIR))
        backup_dir = r.get("directory") or root
        # confine request-supplied dirs under the configured backup root:
        # a remote admin must not write tarballs to arbitrary master paths
        resolved = os.path.realpath(str(backup_dir))
        root_resolved = os.path.realpath(root)
        if resolved != root_resolved and \
                not resolved.startswith(root_resolved + os.sep):
            raise InvalidArgumentError(
                f"backup directory {backup_dir!r} escapes the configured "
                f"backup root {root!r}")
        path = journal.write_backup(resolved)
        return {"backup_uri": path,
                "entry_count": getattr(journal, "sequence", 0)}

    svc.unary("backup", _backup)

    def _set_path_conf(r):
        _require_admin()
        path_properties.add(r["path"], r["properties"])
        return {}

    def _remove_path_conf(r):
        _require_admin()
        path_properties.remove(r["path"], r.get("keys"))
        return {}

    if path_properties is not None:
        svc.unary("set_path_conf", _set_path_conf)
        svc.unary("remove_path_conf", _remove_path_conf)
        svc.unary("get_path_conf", lambda r: {
            "properties": path_properties.get_all(),
            "hash": path_properties.hash()})
    if config_checker is not None:
        svc.unary("register_node_conf", lambda r: (
            config_checker.register(r["node_id"], r.get("config", {})),
            {})[-1])
        svc.unary("get_config_report", lambda r: config_checker.report())
    return svc


# --------------------------------------------------------------------------
# Standby serving (docs/ha.md): the SAME service names as the primary, with
# read handlers served off the tailing journal apply and everything else
# refused by a typed NotPrimaryError carrying the current leader hint — a
# client never sees a bare UNIMPLEMENTED from a standby, it sees a redirect.
# --------------------------------------------------------------------------

def _not_primary_rejector(name: str, leader_fn):
    def reject(_request):
        from alluxio_tpu.utils.exceptions import NotPrimaryError

        raise NotPrimaryError(
            f"{name} requires the primary master",
            leader=leader_fn() or None)

    return reject


def _reject_non_reads(svc: ServiceDefinition, reads: frozenset,
                      leader_fn) -> ServiceDefinition:
    for name, (fn, kind) in list(svc.methods.items()):
        if name not in reads:
            svc.methods[name] = (
                _not_primary_rejector(f"{svc.name}.{name}", leader_fn),
                kind)
    return svc


def standby_fs_service(fsm: FileSystemMaster, leader_fn,
                       active_sync=None) -> ServiceDefinition:
    """The FS surface a standby serves: GetStatus/ListStatus/Exists off
    the tailed state — stamped with the standby's own journal-
    deterministic ``md_version`` — with metadata sync forced OFF (a
    standby cannot journal a sync's effects); every mutating RPC is a
    :class:`NotPrimaryError` redirect.

    Every served read is additionally marked ``standby: true`` (plus the
    current leader hint): a multi-endpoint client that did NOT opt into
    standby reads converts the mark back into a redirect client-side, so
    strong read-your-writes clients can never be silently fed a stale
    read by an endpoint they mistook for the primary (docs/ha.md)."""
    svc = fs_master_service(fsm, active_sync=active_sync)

    def read_wrap(fn):
        # leader hint resolved ONCE per request: under the shared-
        # journal flavor leader_fn scans the registry directory, and a
        # streamed listing would otherwise re-scan per chunk
        def mark(out, leader):
            if isinstance(out, dict):
                out = {**out, "standby": True}
                if leader:
                    out["leader"] = leader
            return out

        def mark_error(e, leader):
            """A read ERROR off tailed state is as stale as a read
            result — a NOT_FOUND for a path the primary just acked is
            the dangerous case.  Tag it (plus the leader hint) so a
            strong client retries on the primary instead of trusting
            it (docs/ha.md)."""
            from alluxio_tpu.utils.exceptions import AlluxioTpuError

            if isinstance(e, AlluxioTpuError):
                e.standby = True
                if e.leader is None:
                    e.leader = leader or None
            return e

        def redirect_journal_write(e, leader):
            """A read that tried to JOURNAL (a UFS metadata load for a
            path not yet in the namespace) hit the tail-only journal:
            that is not an error in the namespace, it is work only the
            primary can do — redirect instead of surfacing
            JournalClosedError as an unavailable standby."""
            from alluxio_tpu.utils.exceptions import (
                JournalClosedError, NotPrimaryError,
            )

            if isinstance(e, JournalClosedError):
                return NotPrimaryError(
                    "read requires a metadata load only the primary "
                    "can journal", leader=leader or None)
            return None

        def stream(gen, leader):
            try:
                for chunk in gen:
                    yield mark(chunk, leader)
            except Exception as e:  # noqa: BLE001 - re-raised marked
                raise redirect_journal_write(e, leader) or \
                    mark_error(e, leader)

        def handler(r):
            leader = leader_fn()
            if fsm.inode_tree.root is None:
                # fresh standby before any journal entry arrived: there
                # is nothing coherent to serve yet — send the client on
                from alluxio_tpu.utils.exceptions import NotPrimaryError

                raise NotPrimaryError(
                    "standby has not applied a journal yet",
                    leader=leader or None)
            try:
                out = fn({**(r or {}), "sync_interval_ms": -1})
            except Exception as e:  # noqa: BLE001 - re-raised marked
                raise redirect_journal_write(e, leader) or \
                    mark_error(e, leader)
            if isinstance(out, dict):
                return mark(out, leader)
            return stream(out, leader)  # streamed listing

        return handler

    for name, (fn, kind) in list(svc.methods.items()):
        if name in STANDBY_FS_READS:
            svc.methods[name] = (read_wrap(fn), kind)
    return _reject_non_reads(svc, STANDBY_FS_READS, leader_fn)


def standby_block_service(bm: BlockMaster, leader_fn) -> ServiceDefinition:
    """Block-master surface on a standby: all redirects.  Block
    LOCATIONS are soft state rebuilt from worker heartbeats, which only
    the primary receives — a standby's map would be empty, and serving
    it would read as 'no replicas anywhere'."""
    return _reject_non_reads(block_master_service(bm), frozenset(),
                             leader_fn)


def standby_meta_service(conf: Configuration, *, leader_fn,
                         cluster_id: str = "", start_time_ms: int = 0,
                         journal=None, masters_fn=None,
                         permission_checker=None) -> ServiceDefinition:
    """Meta surface on a standby: config/cluster introspection and the
    quorum view stay live (they matter MOST while the primary is down);
    admin mutations, backups, checkpoints and the metrics heartbeat
    (which carries cache invalidations and conf overlays only the
    primary can compute) redirect."""
    svc = meta_master_service(
        conf, cluster_id=cluster_id, start_time_ms=start_time_ms,
        journal=journal, permission_checker=permission_checker,
        masters_fn=masters_fn, role_fn=lambda: "STANDBY")
    return _reject_non_reads(svc, STANDBY_META_READS, leader_fn)
