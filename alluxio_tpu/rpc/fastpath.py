"""Same-host metadata fast path: framed msgpack over a Unix socket.

The reference's transport ladder ends at gRPC-over-domain-sockets for
same-host traffic (``GrpcDataServer.java:72-95``); the HTTP/2 framing it
keeps costs more CPU per call than a small metadata RPC's payload is
worth (~1.5 ms/call round measured in Python on the master bench). This
module takes the ladder one rung further for the METADATA plane: the
same ``ServiceDefinition`` registry the gRPC server hosts, exposed over
a Unix stream socket with ``[u32 len][msgpack body]`` frames — no
codegen, no HTTP/2, no per-call executor hop. Data-plane streams stay on
gRPC (flow control matters there; see ``rpc/core.py``).

Protocol (all frames are ``[u32 little-endian length][msgpack]``):
  hello   client->server  {"metadata": {k: v}}    authenticated once per
                          connection (the gRPC path fixes metadata per
                          channel, so per-connection auth is equivalent)
          server->client  {"ok": true} | {"err": wire}
  call    client->server  [service, method, request]
          server->client  {"ok": result} | {"err": wire}

Discovery is by convention: a master serving RPC port P binds
``<dir>/atpu-master-P.sock`` (dir from ``atpu.master.fastpath.dir``,
default ``/tmp``). A client whose master address resolves to this host
probes that path and silently falls back to gRPC when absent — the same
"short-circuit if local, stream if not" decision the block-read ladder
makes (reference: ``BlockInStream.java:80-124``).
"""

from __future__ import annotations

import logging
import os
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, Optional, Tuple

import msgpack

from alluxio_tpu.utils.exceptions import AlluxioTpuError, UnavailableError

LOG = logging.getLogger(__name__)

_LEN = struct.Struct("<I")
_MAX_FRAME = 256 << 20


def socket_path_for(address: str, directory: str = "/tmp") -> str:
    """Conventional socket path for a master RPC ``host:port`` address."""
    _, _, port = address.rpartition(":")
    return os.path.join(directory, f"atpu-master-{port}.sock")


def is_local_host(host: str) -> bool:
    if host in ("localhost", "127.0.0.1", "::1", "0.0.0.0", ""):
        return True
    try:
        return host in (socket.gethostname(), socket.getfqdn())
    except OSError:
        return False


def _read_frame(rfile) -> Optional[bytes]:
    hdr = rfile.read(_LEN.size)
    if len(hdr) < _LEN.size:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > _MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds cap {_MAX_FRAME}")
    body = rfile.read(n)
    if len(body) < n:
        return None
    return body


def _send_frame(sock: socket.socket, obj: Any) -> None:
    body = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(_LEN.pack(len(body)) + body)


class FastPathServer:
    """Serves a ``{service-name: ServiceDefinition}`` registry over a
    Unix socket. Unary methods only — streaming methods are simply not
    registered here, so clients keep using gRPC for them."""

    def __init__(self, uds_path: str, authenticator=None,
                 admission=None) -> None:
        self._uds_path = uds_path
        self._auth = authenticator
        self._admission = admission
        #: (service, method) -> fn, resolved once at registration
        self._methods: Dict[Tuple[str, str], Any] = {}
        self._server: Optional[socketserver.ThreadingUnixStreamServer] = None
        self._thread: Optional[threading.Thread] = None
        #: live connections, severed on stop() — a DEPOSED master must
        #: not keep answering local clients over established sockets
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    def add_service(self, svc) -> None:
        for method, (fn, kind) in svc.methods.items():
            if kind == "unary":
                self._methods[(svc.name, method)] = fn

    def start(self) -> str:
        from alluxio_tpu.rpc.core import check_admission

        methods = self._methods
        authenticator = self._auth
        admission = self._admission
        conns, conns_lock = self._conns, self._conns_lock

        class Handler(socketserver.StreamRequestHandler):
            def setup(self) -> None:
                super().setup()
                with conns_lock:
                    conns.add(self.connection)

            def finish(self) -> None:
                with conns_lock:
                    conns.discard(self.connection)
                super().finish()

            def handle(self) -> None:
                from alluxio_tpu.security.user import (
                    reset_authenticated_user, set_authenticated_user,
                )

                token = None
                try:
                    hello = _read_frame(self.rfile)
                    if hello is None:
                        return
                    md = msgpack.unpackb(hello, raw=False).get(
                        "metadata") or {}
                    # NOSASL identity fallback for admission: without
                    # it every UDS principal would collapse into one
                    # anonymous bucket and a flooding tenant would
                    # shed its victims too
                    principal_hint = md.get("atpu-user")
                    if authenticator is not None:
                        try:
                            user = authenticator.authenticate(md)
                        except AlluxioTpuError as e:
                            _send_frame(self.connection,
                                        {"err": e.to_wire()})
                            return
                        token = set_authenticated_user(user)
                    _send_frame(self.connection, {"ok": True})
                    while True:
                        frame = _read_frame(self.rfile)
                        if frame is None:
                            return  # clean disconnect
                        parts = msgpack.unpackb(
                            frame, raw=False, strict_map_key=False)
                        service, method, request = parts[:3]
                        # optional 4th element: the caller's traceparent
                        traceparent = parts[3] if len(parts) > 3 else None
                        fn = methods.get((service, method))
                        if fn is None:
                            _send_frame(self.connection, {"err": {
                                "code": "UNIMPLEMENTED",
                                "message": f"{service}/{method} has no "
                                           f"fastpath handler"}})
                            continue
                        try:
                            from alluxio_tpu.utils.tracing import (
                                bind_remote_parent, reset_remote_parent,
                                tracer,
                            )

                            # span parity with the gRPC wrapper: admin
                            # tracing must see fastpath RPCs too, joined
                            # to the caller's trace
                            trace_token = bind_remote_parent(traceparent)
                            try:
                                with tracer().span(f"{service}.{method}"):
                                    # admission parity too: a local
                                    # flood must not bypass the gate
                                    # by riding the Unix socket
                                    check_admission(
                                        admission, None,
                                        f"{service}.{method}",
                                        principal_hint=principal_hint)
                                    result = fn(request or {})
                            finally:
                                reset_remote_parent(trace_token)
                            _send_frame(self.connection, {"ok": result})
                        except AlluxioTpuError as e:
                            _send_frame(self.connection,
                                        {"err": e.to_wire()})
                        except Exception as e:  # noqa: BLE001
                            LOG.exception("fastpath handler error")
                            _send_frame(self.connection, {"err": {
                                "code": "INTERNAL",
                                "message": f"{type(e).__name__}: {e}"}})
                except (ConnectionError, ValueError, OSError):
                    pass  # peer went away mid-frame
                finally:
                    if token is not None:
                        reset_authenticated_user(token)

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        try:
            os.unlink(self._uds_path)
        except FileNotFoundError:
            pass
        except OSError as e:
            # e.g. a foreign-owned path squatting the conventional name
            # in sticky /tmp: the fast path is an optimization — never
            # let it abort master startup
            LOG.warning("fastpath disabled: cannot claim %s (%s)",
                        self._uds_path, e)
            return ""
        try:
            self._server = Server(self._uds_path, Handler)
        except OSError as e:
            LOG.warning("fastpath disabled: cannot bind %s (%s)",
                        self._uds_path, e)
            self._server = None
            return ""
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="master-fastpath",
            daemon=True)
        self._thread.start()
        return self._uds_path

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        with self._conns_lock:
            live = list(self._conns)
        for conn in live:  # sever: no serving past deposition
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        try:
            os.unlink(self._uds_path)
        except FileNotFoundError:
            pass


class FastPathChannel:
    """Client side: one persistent connection PER THREAD (no lock on the
    call path; bench threads never contend), lazily (re)connected.
    ``call`` has the same signature/behavior as ``RpcChannel.call``
    including typed-error re-raise."""

    def __init__(self, uds_path: str,
                 metadata: Tuple[Tuple[str, str], ...] = ()) -> None:
        self._uds_path = uds_path
        self._metadata = dict(metadata)
        self._tl = threading.local()

    def _connect(self, timeout: Optional[float]) -> socket.socket:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout if timeout else 30.0)
        sock.connect(self._uds_path)
        rfile = sock.makefile("rb", buffering=64 << 10)
        _send_frame(sock, {"metadata": self._metadata})
        resp = _read_frame(rfile)
        if resp is None:
            raise UnavailableError("fastpath hello: connection closed")
        resp = msgpack.unpackb(resp, raw=False, strict_map_key=False)
        if "err" in resp:
            raise AlluxioTpuError.from_wire(resp["err"])
        self._tl.sock, self._tl.rfile = sock, rfile
        self._tl.timeout = timeout
        return sock

    def close_thread_connection(self) -> None:
        sock = getattr(self._tl, "sock", None)
        if sock is not None:
            try:
                self._tl.rfile.close()
                sock.close()
            except OSError:
                pass
            self._tl.sock = self._tl.rfile = None

    def call(self, service: str, method: str, request: dict,
             timeout: Optional[float] = 30.0) -> Any:
        sock = getattr(self._tl, "sock", None)
        try:
            if sock is None:
                sock = self._connect(timeout)
            elif timeout != getattr(self._tl, "timeout", None):
                # per-call deadline, matching the gRPC path's semantics
                sock.settimeout(timeout if timeout else 30.0)
                self._tl.timeout = timeout
            from alluxio_tpu.utils.tracing import current_traceparent

            # optional 4th frame element: the caller's trace context.
            # Safe to extend the frame shape: fastpath is SAME-HOST by
            # construction (socket discovery), so client and server
            # always come from the same install
            tp = current_traceparent()
            _send_frame(sock, [service, method, request] +
                        ([tp] if tp else []))
            resp = _read_frame(self._tl.rfile)
        except (ConnectionError, socket.timeout, OSError) as e:
            self.close_thread_connection()
            raise UnavailableError(f"fastpath: {e}") from None
        if resp is None:
            self.close_thread_connection()
            raise UnavailableError("fastpath: server closed connection")
        resp = msgpack.unpackb(resp, raw=False, strict_map_key=False)
        err = resp.get("err")
        if err is not None:
            raise AlluxioTpuError.from_wire(err)
        return resp.get("ok")


class HybridChannel:
    """gRPC channel + optional fastpath: unary calls ride the Unix
    socket when the master is local and serving one; anything else (or a
    broken socket) falls back to gRPC. Mirrors the short-circuit /
    remote decision of the block-read ladder, for metadata."""

    def __init__(self, grpc_channel, fastpath_dir: str = "/tmp") -> None:
        self._grpc = grpc_channel
        self.address = grpc_channel.address
        self._fast: Optional[FastPathChannel] = None
        self._fast_dead = False
        host, _, _ = grpc_channel.address.rpartition(":")
        path = socket_path_for(grpc_channel.address, fastpath_dir)
        if is_local_host(host) and self._trusted_socket(path):
            self._fast = FastPathChannel(path,
                                         metadata=grpc_channel.metadata)

    @staticmethod
    def _trusted_socket(path: str) -> bool:
        """The conventional path lives in (usually sticky) /tmp: only
        trust a socket owned by our own uid or root, so a local user
        squatting the name cannot harvest clients' auth metadata."""
        try:
            st = os.stat(path)
        except OSError:
            return False
        return st.st_uid in (os.geteuid(), 0)

    def call(self, service: str, method: str, request: dict,
             timeout: Optional[float] = 30.0) -> Any:
        fast = self._fast
        if fast is not None and not self._fast_dead:
            try:
                return fast.call(service, method, request, timeout=timeout)
            except UnavailableError:
                # socket-level failure: the server may be gone entirely
                # or only the fastpath is — let gRPC decide from here on
                self._fast_dead = True
        return self._grpc.call(service, method, request, timeout=timeout)

    def call_stream(self, *args, **kwargs):
        return self._grpc.call_stream(*args, **kwargs)

    def open_stream(self, *args, **kwargs):
        return self._grpc.open_stream(*args, **kwargs)

    def call_stream_in(self, *args, **kwargs):
        return self._grpc.call_stream_in(*args, **kwargs)

    @property
    def metadata(self):
        return self._grpc.metadata
