"""Job master RPC service + client.

Re-design of ``core/transport/src/main/proto/grpc/job_master.proto``:
client surface (Run/Cancel/GetJobStatus/ListAll ``:165-195``) and
job-worker surface (RegisterJobWorker + Heartbeat with piggybacked task
commands ``:225-230``) on the shared msgpack-gRPC core.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from alluxio_tpu.job.wire import JobInfo
from alluxio_tpu.rpc.clients import resolve_retry_duration_s
from alluxio_tpu.rpc.core import RpcChannel, ServiceDefinition
from alluxio_tpu.utils.retry import ExponentialTimeBoundedRetry, retry

JOB_SERVICE = "JobMasterService"


def job_master_service(job_master) -> ServiceDefinition:
    svc = ServiceDefinition(JOB_SERVICE)
    svc.unary("run", lambda r: {"job_id": job_master.run(r["config"])})
    svc.unary("cancel", lambda r: (job_master.cancel(r["job_id"]), {})[1])
    svc.unary("get_status",
              lambda r: job_master.get_status(r["job_id"]).to_wire())
    svc.unary("list_jobs", lambda r: {
        "jobs": [j.to_wire() for j in job_master.list_jobs()]})
    svc.unary("list_plan_types",
              lambda r: {"types": job_master.list_plan_types()})
    svc.unary("register_worker", lambda r: {
        "worker_id": job_master.register_worker(r["hostname"])})
    svc.unary("list_workers", lambda r: {
        "workers": [{"worker_id": w.worker_id,
                     "hostname": w.hostname,
                     "health": w.health.to_wire()}
                    for w in job_master.workers()]})
    svc.unary("worker_heartbeat", lambda r: {
        "commands": job_master.heartbeat(
            r["worker_id"], r.get("health") or {},
            r.get("task_updates") or [])})
    return svc


class JobMasterClient:
    """Typed retrying client (reference: ``job/client/.../
    RetryHandlingJobMasterClient.java``)."""

    service = JOB_SERVICE

    def __init__(self, address: str, *,
                 retry_duration_s: Optional[float] = None,
                 metadata=None, conf=None):
        """``retry_duration_s`` falls back to ``conf``'s
        ``atpu.user.rpc.retry.duration`` (30s default) — the previously
        hard-coded constant, now tunable for overload drills."""
        self._channel = RpcChannel(address, metadata=metadata)
        self._retry_duration_s = resolve_retry_duration_s(
            retry_duration_s, conf)

    def _call(self, method: str, request: dict, timeout: float = 30.0):
        return retry(
            lambda: self._channel.call(self.service, method, request,
                                       timeout=timeout),
            ExponentialTimeBoundedRetry(self._retry_duration_s, 0.05, 3.0))

    # -- client surface -----------------------------------------------------
    def run(self, config: Dict[str, Any]) -> int:
        return self._call("run", {"config": config})["job_id"]

    def cancel(self, job_id: int) -> None:
        self._call("cancel", {"job_id": job_id})

    def get_status(self, job_id: int) -> JobInfo:
        return JobInfo.from_wire(self._call("get_status",
                                            {"job_id": job_id}))

    def list_jobs(self) -> List[JobInfo]:
        return [JobInfo.from_wire(j)
                for j in self._call("list_jobs", {})["jobs"]]

    def list_workers(self) -> List[Dict[str, Any]]:
        """Registered job workers with their latest health report
        (reference: the worker-health section of
        ``fsadmin report jobservice``)."""
        return self._call("list_workers", {})["workers"]

    def list_plan_types(self) -> List[str]:
        return self._call("list_plan_types", {})["types"]

    # -- worker surface -----------------------------------------------------
    def register_worker(self, hostname: str) -> int:
        return self._call("register_worker",
                          {"hostname": hostname})["worker_id"]

    def heartbeat(self, worker_id: int, health: Dict[str, Any],
                  task_updates: List[Dict[str, Any]]) -> List[dict]:
        return self._call("worker_heartbeat", {
            "worker_id": worker_id, "health": health,
            "task_updates": task_updates})["commands"]

    def wait_for_job(self, job_id: int, timeout_s: float = 120.0,
                     poll_s: float = 0.05) -> JobInfo:
        """Poll until the job finishes (test/CLI convenience)."""
        import time

        from alluxio_tpu.job.wire import Status
        from alluxio_tpu.utils.exceptions import DeadlineExceededError

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            info = self.get_status(job_id)
            if Status.is_finished(info.status):
                return info
            time.sleep(poll_s)
        raise DeadlineExceededError(
            f"job {job_id} not finished within {timeout_s}s")
