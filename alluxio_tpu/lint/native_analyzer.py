"""Analyzer 6: native ABI discipline.

The ctypes boundary has no type checker: a prototype in
``native/__init__.py`` that names a symbol the compiled ``.so`` does
not export fails at ``lib()`` attach time (silently disabling the whole
native layer), and an ``extern "C"`` entry point with no declared
prototype is dead export surface nothing on the Python side can call
safely. Both are signature drift that should fail lint, not segfault
(or silently slow down) a run.

The rule diffs ``native._PROTOTYPES`` — the single source of truth the
loader attaches from — against the defined ``atpu_*`` function symbols
in the compiled library's ELF ``.dynsym`` table (built on demand, same
as the runtime):

- ``native-abi-missing-symbol``     declared prototype with no exported
                                    symbol in the compiled ``.so``
- ``native-abi-undeclared-symbol``  exported ``atpu_*`` symbol with no
                                    ctypes prototype

No toolchain (the build fails exactly like it would at runtime) or an
unparsable ``.so``: stay silent — the runtime falls back to pure
Python there too, so there is no ABI to drift.
"""

from __future__ import annotations

from typing import List

from alluxio_tpu.lint.collect import RepoFacts
from alluxio_tpu.lint.findings import Finding
from alluxio_tpu.lint.model import RepoModel

RULES = ("native-abi-missing-symbol", "native-abi-undeclared-symbol")

_LOADER = "alluxio_tpu/native/__init__.py"


def _line_of(model: RepoModel, needle: str) -> int:
    for pf in model.py_files:
        if pf.path != _LOADER:
            continue
        for i, line in enumerate(pf.text.splitlines(), start=1):
            if f'"{needle}"' in line:
                return i
        break
    return 1


def analyze(model: RepoModel, facts: RepoFacts) -> List[Finding]:
    findings: List[Finding] = []
    if not any(pf.path == _LOADER for pf in model.py_files):
        # partial scan without the loader: nothing to diff against
        return findings
    try:
        from alluxio_tpu import native
    except Exception:  # noqa: BLE001 - broken import is a test failure
        return findings
    symbols = native.exported_symbols()
    if symbols is None:
        # no toolchain / unparsable .so: the runtime falls back to
        # pure Python here too — no ABI exists to drift
        return findings
    declared = set(native._PROTOTYPES)
    exported = set(symbols)
    for name in sorted(declared - exported):
        findings.append(Finding(
            rule="native-abi-missing-symbol", path=_LOADER,
            line=_line_of(model, name), anchor=name,
            message=f"ctypes prototype '{name}' has no exported symbol "
                    f"in the compiled .so — lib() would fail to attach "
                    f"and silently disable the whole native layer; add "
                    f"the extern \"C\" entry point or drop the "
                    f"prototype"))
    for name in sorted(exported - declared):
        findings.append(Finding(
            rule="native-abi-undeclared-symbol", path=_LOADER,
            line=_line_of(model, name), anchor=name,
            message=f"compiled .so exports '{name}' with no ctypes "
                    f"prototype in native._PROTOTYPES — undeclared "
                    f"entry points have no argtypes/restype and "
                    f"segfault on drift; declare it or remove the "
                    f"export"))
    return findings
