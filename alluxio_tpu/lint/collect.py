"""Shared fact collection: one AST walk per file feeding every analyzer.

The walk classifies the string literals the registries care about:

- metric EMITS      first arg of ``.counter/.meter/.timer/.register_gauge``
- span EMITS        first arg of ``.span(`` / ``annotate(`` / ``Span(``
- phase EMITS       first arg of ``.phase(`` (typed phase events inside
                    spans; the catalog is the ``PHASES`` tuple in
                    utils/tracing.py)
- metric CONSUMES   any other full-string instance-prefixed literal
                    (health rules, benches, fsadmin, snapshot keys)
- conf literals     any other full-string ``atpu.*`` literal
- ``Keys.X`` attribute reads (conf-key usage through the typed catalog)

f-strings become glob patterns (each interpolated part -> ``*``) so
dynamic families like ``Worker.BytesServed.{tier}`` stay checkable.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from alluxio_tpu.lint.model import PyFile, RepoModel

METRIC_INSTANCES = ("Master", "Worker", "Client", "Cluster",
                    "JobMaster", "JobWorker", "Process")
#: a full-string literal is metric-ish when it looks like Instance.Name...
METRIC_RE = re.compile(
    r"^(?:%s)(?:\.[A-Za-z0-9_{}*<>-]+)+$" % "|".join(METRIC_INSTANCES))
#: a full-string literal is conf-key-ish when it is atpu.<lowercase...>
#: (service names like atpu.FileSystemMaster are CamelCase -> excluded)
CONF_RE = re.compile(r"^atpu\.[a-z][a-z0-9_.{}*<>-]*$")

_METRIC_EMIT_METHODS = {"counter", "meter", "timer", "register_gauge"}
_SPAN_EMIT_CALLEES = {"span", "annotate", "Span", "start_span"}
_PHASE_EMIT_CALLEES = {"phase"}

#: the typed-phase catalog lives here as ``PHASES = (...)``
_PHASE_CATALOG_PATH = "alluxio_tpu/utils/tracing.py"


@dataclass(frozen=True)
class StrSite:
    value: str    # literal value; '*' marks interpolated f-string parts
    path: str
    line: int
    pattern: bool  # True when value came from an f-string / has globs


#: heartbeat thread names (``Master.TtlCheck``…) look metric-ish but are
#: their own registry; this module defines it
_HEARTBEAT_CATALOG_PATH = "alluxio_tpu/heartbeat/core.py"


@dataclass
class RepoFacts:
    metric_emits: List[StrSite] = field(default_factory=list)
    metric_consumes: List[StrSite] = field(default_factory=list)
    span_emits: List[StrSite] = field(default_factory=list)
    phase_emits: List[StrSite] = field(default_factory=list)
    #: phase name -> (path, line) of its PHASES-tuple catalog entry
    phase_catalog: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    conf_literals: List[StrSite] = field(default_factory=list)
    #: Keys.<ATTR> reads per file (attribute name, path, line)
    keys_attr_reads: List[Tuple[str, str, int]] = field(default_factory=list)
    #: heartbeat thread names from the HeartbeatContext catalog
    heartbeat_names: Set[str] = field(default_factory=set)

    def metric_emit_names(self) -> Set[str]:
        return {s.value for s in self.metric_emits if not s.pattern}

    def metric_emit_globs(self) -> Set[str]:
        return {s.value for s in self.metric_emits}

    def span_names(self) -> Set[str]:
        return {s.value for s in self.span_emits}

    def phase_names(self) -> Set[str]:
        return {s.value for s in self.phase_emits if not s.pattern}


def _joinedstr_glob(node: ast.JoinedStr) -> Optional[str]:
    parts: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append("*")
    glob = "".join(parts)
    return glob if glob.strip("*") else None


def _first_arg_string(call: ast.Call) -> Optional[Tuple[str, bool, int]]:
    """(value, is_pattern, lineno) for a literal/f-string first argument."""
    if not call.args:
        return None
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value, False, a.lineno
    if isinstance(a, ast.JoinedStr):
        glob = _joinedstr_glob(a)
        if glob is not None:
            return glob, True, a.lineno
    return None


def _callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def collect_file(pf: PyFile, facts: RepoFacts) -> None:
    doc_lines = pf.docstring_lines()
    emit_nodes: Set[int] = set()  # id() of first-arg nodes already classified

    if pf.path == _PHASE_CATALOG_PATH:
        # the PHASES tuple IS the phase registry
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Assign) and \
                    any(isinstance(t, ast.Name) and t.id == "PHASES"
                        for t in node.targets) and \
                    isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        facts.phase_catalog[elt.value] = \
                            (pf.path, elt.lineno)
                        emit_nodes.add(id(elt))

    if pf.path == _HEARTBEAT_CATALOG_PATH:
        # class-level string constants there ARE the heartbeat registry
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign) and \
                            isinstance(stmt.value, ast.Constant) and \
                            isinstance(stmt.value.value, str):
                        facts.heartbeat_names.add(stmt.value.value)
                        emit_nodes.add(id(stmt.value))
    fstring_parts: Set[int] = set()  # id() of JoinedStr children: the
    # enclosing JoinedStr is classified as one glob, never its pieces

    for node in ast.walk(pf.tree):
        if isinstance(node, ast.JoinedStr):
            fstring_parts.update(id(v) for v in node.values)
        if isinstance(node, ast.Call):
            callee = _callee_name(node)
            arg = _first_arg_string(node)
            if callee in _METRIC_EMIT_METHODS and arg is not None and \
                    METRIC_RE.match(arg[0].replace("*", "x")):
                value, pattern, line = arg
                facts.metric_emits.append(
                    StrSite(value, pf.path, line, pattern))
                emit_nodes.add(id(node.args[0]))
            elif callee in _SPAN_EMIT_CALLEES and arg is not None:
                value, pattern, line = arg
                facts.span_emits.append(
                    StrSite(value, pf.path, line, pattern))
                emit_nodes.add(id(node.args[0]))
            elif callee in _PHASE_EMIT_CALLEES and arg is not None and \
                    isinstance(node.func, ast.Attribute):
                # attribute form only (sp.phase(...)): a bare phase()
                # is some other function, not a Span phase event
                value, pattern, line = arg
                facts.phase_emits.append(
                    StrSite(value, pf.path, line, pattern))
                emit_nodes.add(id(node.args[0]))
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "Keys":
            facts.keys_attr_reads.append((node.attr, pf.path, node.lineno))

    for node in ast.walk(pf.tree):
        if id(node) in emit_nodes or id(node) in fstring_parts:
            continue
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.lineno in doc_lines:
                continue  # docstrings are prose, not registry references
            v = node.value
            if METRIC_RE.match(v.replace("*", "x").replace("<", "x")
                               .replace(">", "x")):
                facts.metric_consumes.append(
                    StrSite(v, pf.path, node.lineno,
                            "*" in v or "{" in v or "<" in v))
            elif CONF_RE.match(v):
                facts.conf_literals.append(
                    StrSite(v, pf.path, node.lineno,
                            "*" in v or "{" in v or "<" in v))
        elif isinstance(node, ast.JoinedStr):
            glob = _joinedstr_glob(node)
            if glob is None or node.lineno in doc_lines:
                continue
            probe = glob.replace("*", "x")
            if METRIC_RE.match(probe):
                facts.metric_consumes.append(
                    StrSite(glob, pf.path, node.lineno, True))
            elif CONF_RE.match(probe):
                # f-string conf keys are minted at runtime; the analyzer
                # resolves them by literal prefix / template pattern
                facts.conf_literals.append(
                    StrSite(glob, pf.path, node.lineno, True))


def collect(model: RepoModel) -> RepoFacts:
    facts = RepoFacts()
    for pf in model.py_files:
        collect_file(pf, facts)
    return facts


# -- doc-side token extraction ----------------------------------------------

_DOC_TOKEN_RE = re.compile(r"`([^`\n]+)`")
_DOC_CONF_RE = re.compile(r"^atpu\.[a-z][a-z0-9_.{}*<>-]*$")
_DOC_METRIC_RE = METRIC_RE


@dataclass(frozen=True)
class DocToken:
    value: str
    path: str
    line: int


def doc_tokens(model: RepoModel) -> Tuple[List[DocToken], List[DocToken]]:
    """(conf-ish, metric-ish) backticked tokens across all doc files."""
    conf: List[DocToken] = []
    metric: List[DocToken] = []
    for doc in model.doc_files:
        for i, line in enumerate(doc.text.splitlines(), start=1):
            for m in _DOC_TOKEN_RE.finditer(line):
                tok = m.group(1).strip().rstrip(".,;:")
                if tok.rsplit(".", 1)[-1] in (
                        "java", "py", "proto", "md", "sh", "xml", "cc",
                        "h", "json", "yaml"):
                    continue  # a file name, not a registry reference
                if _DOC_CONF_RE.match(tok):
                    conf.append(DocToken(tok, doc.path, i))
                elif _DOC_METRIC_RE.match(
                        tok.replace("*", "x").replace("<", "x")
                        .replace(">", "x")):
                    metric.append(DocToken(tok, doc.path, i))
    return conf, metric
