"""atpu-lint: the repo-native static-analysis suite.

Re-design of the reference's correctness-tooling surface (SURVEY §5.2:
checkstyle/findbugs build gates + TSAN-style lock tests) for a Python
codebase whose three load-bearing registries — ``atpu.*`` conf keys,
instance-prefixed metric/span names, and the typed wire-error map —
grow by dozens of entries per PR and silently rot without a machine
check: a typo'd metric name makes a health rule permanently blind with
zero test failures.

Four AST-based analyzers (run as ``make lint`` /
``python -m alluxio_tpu.lint``):

- ``conf-keys``     every ``atpu.*`` literal resolves to a registered
                    ``PropertyKey`` (or span/service name), every
                    registered key is read somewhere and documented,
                    defaults parse under their declared types
- ``metric-names``  emitters + consumers (health rules, benches, shell,
                    docs) form one registry; near-miss typos, undocumented
                    names and exposition-hostile names are flagged
- ``lock-discipline`` blocking calls (RPC, UFS I/O, ``time.sleep``,
                    unbounded ``.result()``/``.wait()``) made while
                    holding a lock
- ``exceptions``    ``except Exception`` on server dispatch / heartbeat /
                    remediation paths that neither log nor re-raise, and
                    wire-error classes outside the serialization map

Each analyzer honors inline suppressions
(``# lint: allow[rule] -- justification``) and a checked-in baseline
(``alluxio_tpu/lint/baseline.json``) that freezes pre-existing findings;
new findings fail the build.  The companion pytest plugin
(``alluxio_tpu.lint.pytest_lockaudit``) is the dynamic half: it
auto-instruments master/worker/store locks with
``utils.race.LockOrderAuditor`` across every test and fails the run on
any observed lock-order inversion.
"""

from alluxio_tpu.lint.findings import Finding, Suppression  # noqa: F401
from alluxio_tpu.lint.runner import LintReport, run_lint  # noqa: F401
