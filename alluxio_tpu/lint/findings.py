"""Finding/suppression/baseline plumbing shared by every analyzer.

A finding's identity (``ident``) deliberately excludes the line number:
baselines must survive unrelated edits above the finding, so the anchor
is the stable symbol the finding is about (a conf-key name, a metric
name, a ``function#callee`` pair) plus the file path.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: inline suppression:  # lint: allow[rule-a,rule-b] -- justification
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\[([a-z0-9,\s-]+)\]\s*(?:--\s*(.*))?$")


@dataclass(frozen=True)
class Finding:
    rule: str       # analyzer rule id, e.g. "conf-unknown-key"
    path: str       # repo-relative path
    line: int       # 1-based line of the offending site (display only)
    anchor: str     # stable symbol for baseline identity
    message: str

    @property
    def ident(self) -> str:
        return f"{self.rule}:{self.path}:{self.anchor}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass(frozen=True)
class Suppression:
    rules: Tuple[str, ...]
    justification: str
    line: int

    def covers(self, rule: str) -> bool:
        return rule in self.rules or "*" in self.rules


def parse_suppressions(text: str) -> Dict[int, Suppression]:
    """Line-number -> suppression for one source file.

    A suppression on line N covers findings anchored at N or N+1, so
    both trailing-comment and line-above styles work.  A suppression
    with no justification is itself invalid — the caller turns those
    into ``lint-bad-suppression`` findings.
    """
    out: Dict[int, Suppression] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        just = (m.group(2) or "").strip()
        out[i] = Suppression(rules=rules, justification=just, line=i)
    return out


def suppression_for(suppressions: Dict[int, Suppression],
                    rule: str, line: int) -> Optional[Suppression]:
    for ln in (line, line - 1):
        s = suppressions.get(ln)
        if s is not None and s.covers(rule):
            return s
    return None


@dataclass
class Baseline:
    """Checked-in set of frozen findings, each with a written reason.

    Format (``alluxio_tpu/lint/baseline.json``)::

        {"entries": [{"id": "<rule>:<path>:<anchor>",
                      "justification": "why this is frozen, not fixed"}]}
    """

    entries: Dict[str, str] = field(default_factory=dict)  # ident -> why
    path: str = ""

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, "r", encoding="utf-8") as f:
                raw = json.load(f)
        except FileNotFoundError:
            return cls(path=path)
        entries: Dict[str, str] = {}
        bad: List[str] = []
        for e in raw.get("entries", []):
            ident = e.get("id", "")
            just = (e.get("justification") or "").strip()
            if not ident or not just:
                bad.append(ident or "<missing id>")
                continue
            entries[ident] = just
        if bad:
            raise ValueError(
                f"{path}: baseline entries without a justification are "
                f"not allowed: {bad}")
        return cls(entries=entries, path=path)

    def covers(self, finding: Finding) -> bool:
        return finding.ident in self.entries

    def stale(self, findings: List[Finding]) -> List[str]:
        """Baseline idents no current finding matches (candidates for
        pruning — the debt was paid)."""
        live = {f.ident for f in findings}
        return sorted(i for i in self.entries if i not in live)

    @staticmethod
    def write(path: str, findings: List[Finding],
              justification: str) -> None:
        entries = [{"id": f.ident, "justification": justification}
                   for f in sorted(findings, key=lambda f: f.ident)]
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"entries": entries}, f, indent=1, sort_keys=True)
            f.write("\n")
