"""Lint orchestration: build the model once, run every analyzer, apply
suppressions + baseline, report.

Exit codes: 0 clean, 1 new findings (or invalid suppressions), 2 budget
exceeded / bad invocation.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set

from alluxio_tpu.lint import (
    conf_analyzer, exceptions_analyzer, locks_analyzer, metrics_analyzer,
    native_analyzer, phases_analyzer,
)
from alluxio_tpu.lint.collect import RepoFacts, collect
from alluxio_tpu.lint.findings import (
    Baseline, Finding, suppression_for,
)
from alluxio_tpu.lint.model import RepoModel, build_model, changed_paths

ANALYZERS: Dict[str, Callable[[RepoModel, RepoFacts], List[Finding]]] = {
    "conf-keys": conf_analyzer.analyze,
    "metric-names": metrics_analyzer.analyze,
    "phase-names": phases_analyzer.analyze,
    "lock-discipline": locks_analyzer.analyze,
    "exceptions": exceptions_analyzer.analyze,
    "native-abi": native_analyzer.analyze,
}

DEFAULT_BASELINE = "alluxio_tpu/lint/baseline.json"


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)   # everything
    new: List[Finding] = field(default_factory=list)        # fails build
    suppressed: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    bad_suppressions: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.new and not self.bad_suppressions

    def summary(self) -> str:
        by_rule: Dict[str, int] = {}
        for f in self.new:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        parts = [f"{r}={n}" for r, n in sorted(by_rule.items())]
        return (f"lint: {len(self.new)} new finding(s) "
                f"[{', '.join(parts) or 'none'}], "
                f"{len(self.suppressed)} suppressed, "
                f"{len(self.baselined)} baselined, "
                f"{len(self.stale_baseline)} stale baseline entr(ies) "
                f"in {self.elapsed_s:.1f}s")


def run_lint(root: str,
             analyzers: Optional[Sequence[str]] = None,
             only_paths: Optional[Set[str]] = None,
             extra_py: Sequence[str] = (),
             baseline_path: Optional[str] = None,
             report_only: Optional[Set[str]] = None) -> LintReport:
    """``only_paths`` restricts the SCAN (self-contained fixture runs —
    registry-level rules skip); ``report_only`` scans the whole tree so
    cross-file resolution stays correct but reports findings only in the
    given files (the ``--changed`` fast gate)."""
    t0 = time.monotonic()
    model = build_model(root, only_paths=only_paths,
                        extra_py=tuple(extra_py))
    facts = collect(model)

    report = LintReport()
    names = list(analyzers) if analyzers else list(ANALYZERS)
    for name in names:
        fn = ANALYZERS.get(name)
        if fn is None:
            raise ValueError(f"unknown analyzer '{name}'; "
                             f"have: {sorted(ANALYZERS)}")
        report.findings.extend(fn(model, facts))
    if report_only is not None:
        report.findings = [f for f in report.findings
                           if f.path in report_only]

    baseline = Baseline(path="")
    if baseline_path:
        baseline = Baseline.load(baseline_path)

    supp_by_path = {pf.path: pf.suppressions for pf in model.py_files}
    for f in report.findings:
        s = suppression_for(supp_by_path.get(f.path, {}), f.rule, f.line)
        if s is not None:
            if not s.justification:
                report.bad_suppressions.append(Finding(
                    rule="lint-bad-suppression", path=f.path, line=s.line,
                    anchor=f.anchor,
                    message=f"suppression of [{f.rule}] has no "
                            f"justification (use `# lint: allow["
                            f"{f.rule}] -- <why>`)"))
            else:
                report.suppressed.append(f)
            continue
        if baseline.covers(f):
            report.baselined.append(f)
            continue
        report.new.append(f)

    if baseline.entries and not model.is_partial and report_only is None:
        report.stale_baseline = baseline.stale(report.findings)
    report.elapsed_s = time.monotonic() - t0
    return report


def _write_docs(root: str) -> None:
    model = build_model(root)
    facts = collect(model)
    conf_doc = os.path.join(root, "docs", "configuration.md")
    metrics_doc = os.path.join(root, "docs", "metrics.md")
    conf_analyzer.write_conf_doc(conf_doc)
    metrics_analyzer.write_metrics_doc(metrics_doc, facts)
    print(f"wrote {conf_doc}\nwrote {metrics_doc}")


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m alluxio_tpu.lint",
        description="atpu-lint: conf-key / metric-name / lock / "
                    "exception discipline")
    p.add_argument("paths", nargs="*",
                   help="restrict to these repo-relative files "
                        "(per-file rules only)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detect from package)")
    p.add_argument("--changed", action="store_true",
                   help="lint only files changed vs HEAD (fast mode; "
                        "registry-level rules are skipped)")
    p.add_argument("--rule", dest="rules", action="append",
                   help="run only this analyzer (repeatable): "
                        f"{sorted(ANALYZERS)}")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--write-baseline", action="store_true",
                   help="freeze every current new finding into the "
                        "baseline (requires --justification)")
    p.add_argument("--justification", default="",
                   help="justification recorded with --write-baseline")
    p.add_argument("--write-docs", action="store_true",
                   help="regenerate docs/configuration.md + "
                        "docs/metrics.md from the live registries")
    p.add_argument("--budget-s", type=float, default=0.0,
                   help="fail (exit 2) when analysis exceeds this many "
                        "seconds — keeps the gate cheap")
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    if args.write_docs:
        _write_docs(root)
        return 0

    # Path-restricted modes always scan the FULL tree — cross-file name
    # resolution (metric emit universe, span registry) is meaningless on
    # a slice — and filter the REPORT to the requested files instead.
    report_only: Optional[Set[str]] = None
    if args.changed:
        report_only = changed_paths(root)
        if not report_only:
            print("lint: no files changed vs HEAD")
            return 0
    if args.paths:
        report_only = (report_only or set()) | set(args.paths)

    baseline_path = None
    if not args.no_baseline:
        baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)

    extra = tuple(pth for pth in (args.paths or ())
                  if not pth.startswith("alluxio_tpu/"))
    try:
        report = run_lint(root, analyzers=args.rules,
                          extra_py=extra, baseline_path=baseline_path,
                          report_only=report_only)
    except ValueError as e:
        # bad invocation (unknown --rule, malformed baseline), NOT a
        # finding: exit 2 so CI never reads it as new lint debt
        print(f"lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        if not args.justification.strip():
            print("--write-baseline requires --justification "
                  "(baselines without a written reason are rejected)",
                  file=sys.stderr)
            return 2
        Baseline.write(baseline_path or os.path.join(root, DEFAULT_BASELINE),
                       report.new, args.justification.strip())
        print(f"froze {len(report.new)} finding(s) into the baseline")
        return 0

    for f in report.bad_suppressions:
        print(f.render())
    for f in sorted(report.new, key=lambda f: (f.path, f.line)):
        print(f.render())
    if not args.quiet:
        for ident in report.stale_baseline:
            print(f"lint: stale baseline entry (no longer found): {ident}")
        print(report.summary())

    if args.budget_s and report.elapsed_s > args.budget_s:
        print(f"lint: BUDGET EXCEEDED: {report.elapsed_s:.1f}s > "
              f"{args.budget_s:.0f}s — analyzers must stay cheap enough "
              f"to gate every test run", file=sys.stderr)
        return 2
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
