"""Analyzer 5: read-path phase-name discipline.

The typed phase events inside spans (``Span.phase(name, ms)``) feed the
critical-path analyzer, which groups and ranks by name string — an
emit-site typo doesn't crash anything, it silently mints a parallel
phase that never aggregates with its siblings and never shows up where
the operator greps for it. The ``PHASES`` tuple in ``utils/tracing.py``
is the registry; every emit site must use a member of it.

Rules:

- ``phase-typo``          emitted name misses the catalog by edit
                          distance <= 2 of a cataloged phase
- ``phase-unknown``       emitted name with no cataloged counterpart
- ``phase-unused``        cataloged phase no emit site uses (dead
                          vocabulary misleads whoever reads the tuple)
- ``phase-undocumented``  cataloged phase absent from every doc
                          (regenerate docs/metrics.md)
"""

from __future__ import annotations

from typing import List, Set, Tuple

from alluxio_tpu.lint.collect import RepoFacts
from alluxio_tpu.lint.findings import Finding
from alluxio_tpu.lint.metrics_analyzer import _edit_distance
from alluxio_tpu.lint.model import RepoModel

RULES = ("phase-typo", "phase-unknown", "phase-unused",
         "phase-undocumented")


def analyze(model: RepoModel, facts: RepoFacts) -> List[Finding]:
    findings: List[Finding] = []
    catalog = facts.phase_catalog
    if not catalog:
        # partial scan without utils/tracing.py: no registry to check
        # against — emits cannot be classified, so stay silent
        return findings

    # 1) every emit site names a cataloged phase
    flagged: Set[Tuple[str, str]] = set()
    for site in facts.phase_emits:
        if site.pattern or site.value in catalog:
            continue
        key = (site.path, site.value)
        if key in flagged:
            continue
        flagged.add(key)
        best = None
        for known in catalog:
            d = _edit_distance(site.value, known)
            if d > 0 and (best is None or d < best[1]):
                best = (known, d)
        if best is not None and best[1] <= 2:
            findings.append(Finding(
                rule="phase-typo", path=site.path, line=site.line,
                anchor=site.value,
                message=f"phase '{site.value}' is not in "
                        f"tracing.PHASES — did you mean '{best[0]}'? "
                        f"(edit distance {best[1]}); a misspelled "
                        f"phase silently never aggregates"))
        else:
            findings.append(Finding(
                rule="phase-unknown", path=site.path, line=site.line,
                anchor=site.value,
                message=f"phase '{site.value}' is not in "
                        f"tracing.PHASES — add it to the catalog or "
                        f"use an existing phase"))

    # registry-level checks need the whole emit universe
    if model.is_partial:
        return findings

    emitted = facts.phase_names()
    for name, (path, line) in sorted(catalog.items()):
        if name not in emitted:
            findings.append(Finding(
                rule="phase-unused", path=path, line=line, anchor=name,
                message=f"cataloged phase '{name}' has no emit site — "
                        f"drop it from PHASES or wire the emit"))

    doc_blob = "\n".join(d.text for d in model.doc_files)
    for name, (path, line) in sorted(catalog.items()):
        if f"`{name}`" not in doc_blob and \
                f"``{name}``" not in doc_blob:
            findings.append(Finding(
                rule="phase-undocumented", path=path, line=line,
                anchor=name,
                message=f"cataloged phase '{name}' appears in no doc "
                        f"(run `python -m alluxio_tpu.lint "
                        f"--write-docs`)"))
    return findings
