"""Repo model: one parse of every source/doc file, shared by analyzers.

Keeping the walk + ``ast.parse`` in one place is what keeps the full-tree
run inside its <30s budget — each analyzer re-walks the cached trees, it
never re-reads disk.
"""

from __future__ import annotations

import ast
import os
import subprocess
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from alluxio_tpu.lint.findings import Suppression, parse_suppressions

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules",
              "build", "dist"}


@dataclass
class PyFile:
    path: str          # repo-relative, e.g. "alluxio_tpu/master/health.py"
    text: str
    tree: ast.AST
    suppressions: Dict[int, Suppression]

    _docstring_lines: Optional[Set[int]] = field(default=None, repr=False)

    def docstring_lines(self) -> Set[int]:
        """Line numbers occupied by module/class/function docstrings —
        strings there are prose, not registry references."""
        if self._docstring_lines is None:
            lines: Set[int] = set()
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.Module, ast.ClassDef,
                                     ast.FunctionDef, ast.AsyncFunctionDef)):
                    body = getattr(node, "body", [])
                    if body and isinstance(body[0], ast.Expr) and \
                            isinstance(body[0].value, ast.Constant) and \
                            isinstance(body[0].value.value, str):
                        c = body[0].value
                        lines.update(range(c.lineno, c.end_lineno + 1))
            self._docstring_lines = lines
        return self._docstring_lines


@dataclass
class DocFile:
    path: str
    text: str


@dataclass
class RepoModel:
    root: str
    py_files: List[PyFile]
    doc_files: List[DocFile]
    #: paths restricted by --changed / explicit path args (None = full tree);
    #: registry-level rules that need the whole tree consult this to know
    #: whether they may run.
    restricted: Optional[Set[str]] = None

    def py(self, prefix: str = "") -> Iterator[PyFile]:
        for f in self.py_files:
            if f.path.startswith(prefix):
                yield f

    @property
    def is_partial(self) -> bool:
        return self.restricted is not None


def function_index(tree: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(qualname, node) for every function/method, outermost first —
    shared by the analyzers that anchor findings on qualnames (anchors
    feed baseline idents, so there must be exactly ONE walker)."""
    out: List[Tuple[str, ast.AST]] = []

    def rec(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((f"{prefix}{child.name}", child))
                rec(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.ClassDef):
                rec(child, f"{prefix}{child.name}.")
            else:
                rec(child, prefix)

    rec(tree, "")
    return out


def _read(path: str) -> Optional[str]:
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            return f.read()
    except OSError:
        return None


def _walk_files(root: str, rel_dirs: Tuple[str, ...],
                exts: Tuple[str, ...]) -> Iterator[str]:
    for rel_dir in rel_dirs:
        top = os.path.join(root, rel_dir)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(exts):
                    yield os.path.relpath(os.path.join(dirpath, name), root)


#: Python scanned for registry usage + discipline rules.  Tests are NOT
#: scanned by default: fake names there are legitimate (drills, fixtures)
#: and "a key is read somewhere" must mean product code.
PY_ROOTS = ("alluxio_tpu",)
DOC_ROOTS = ("docs",)
DOC_EXTRA = ("README.md", "ROADMAP.md")


def changed_paths(root: str) -> Set[str]:
    """Repo-relative paths touched vs HEAD (staged + unstaged + untracked),
    for the fast ``lint-changed`` mode."""
    out: Set[str] = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            r = subprocess.run(args, cwd=root, capture_output=True,
                               text=True, timeout=30, check=False)
        except (OSError, subprocess.TimeoutExpired):
            continue
        out.update(p.strip() for p in r.stdout.splitlines() if p.strip())
    return out


def build_model(root: str, only_paths: Optional[Set[str]] = None,
                extra_py: Tuple[str, ...] = ()) -> RepoModel:
    """Parse the tree.  ``only_paths`` restricts the *scanned* set (fast
    mode / explicit fixture runs); ``extra_py`` adds python files outside
    ``PY_ROOTS`` (tests pass fixture modules this way)."""
    py_files: List[PyFile] = []
    doc_files: List[DocFile] = []

    py_candidates = list(_walk_files(root, PY_ROOTS, (".py",)))
    py_candidates.extend(extra_py)
    for rel in py_candidates:
        if only_paths is not None and rel not in only_paths and \
                rel not in extra_py:
            continue
        text = _read(os.path.join(root, rel))
        if text is None:
            continue
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError:
            # let the test suite / interpreter report syntax errors;
            # lint only analyzes parseable files
            continue
        py_files.append(PyFile(path=rel, text=text, tree=tree,
                               suppressions=parse_suppressions(text)))

    doc_candidates = list(_walk_files(root, DOC_ROOTS, (".md",)))
    doc_candidates.extend(p for p in DOC_EXTRA
                          if os.path.isfile(os.path.join(root, p)))
    for rel in doc_candidates:
        if only_paths is not None and rel not in only_paths:
            continue
        text = _read(os.path.join(root, rel))
        if text is not None:
            doc_files.append(DocFile(path=rel, text=text))

    return RepoModel(root=root, py_files=py_files, doc_files=doc_files,
                     restricted=set(only_paths) if only_paths is not None
                     else None)
