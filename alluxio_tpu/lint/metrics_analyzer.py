"""Analyzer 2: metric/span-name discipline.

Emitters (``.counter/.meter/.timer/.register_gauge`` call sites) define
the registry; consumers (health rules, remediation, benches, fsadmin,
snapshot keys, docs) must hit it — modulo the derived forms the metrics
system itself mints:

- timer/meter snapshot suffixes (``.p50/.p95/.p99/.mean/.count/.rate1m``)
  plus history-rollup fields (``.min/.max/.last/.sum``)
- ``Cluster.X`` aggregates derived from per-instance ``Worker./Client./
  Master.X`` reports (metrics/history.py synthesizes these)

Rules:

- ``metric-typo``          consumed name misses the registry by edit
                           distance <= 2 of a registered name — the
                           "permanently blind health rule" bug class
- ``metric-unknown``       consumed name with no registered counterpart
- ``metric-undocumented``  emitted name absent from every doc
                           (regenerate docs/metrics.md)
- ``metric-invalid-name``  emitted name that violates the
                           ``Instance.CamelCase`` convention or would
                           collide after Prometheus sanitization

Span names (``atpu.*`` strings) share a namespace with conf keys, so
both code- and doc-side span resolution ride the conf analyzer.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from alluxio_tpu.lint.collect import (
    METRIC_INSTANCES, RepoFacts, StrSite, doc_tokens,
)
from alluxio_tpu.lint.findings import Finding
from alluxio_tpu.lint.model import RepoModel

RULES = ("metric-typo", "metric-unknown", "metric-undocumented",
         "metric-invalid-name")

#: suffixes the registry derives from timers/meters/history rollups
_DERIVED_SUFFIXES = (".p50", ".p95", ".p99", ".mean", ".count", ".rate1m",
                     ".min", ".max", ".last", ".sum")
#: Cluster.X aggregates are synthesized from these instance reports
_CLUSTER_SOURCES = ("Worker.", "Client.", "Master.", "JobMaster.",
                    "JobWorker.")

_VALID_EMIT_RE = re.compile(
    r"^(?:%s)(?:\.[A-Za-z0-9_]+|\.\*)+$" % "|".join(METRIC_INSTANCES))


def _norm_glob(name: str) -> str:
    """Canonical glob: f-string parts and <placeholders> become '*'."""
    s = re.sub(r"<[^>]*>", "*", name)
    s = re.sub(r"\{[^}]*\}", "*", s)
    s = re.sub(r"\*+", "*", s)
    return s


def _prefix(glob: str) -> str:
    return glob.split("*")[0]


def _globs_compatible(consumed: str, emitted: str) -> bool:
    """Loose intersection test on glob pairs: compare the literal
    prefixes before the first wildcard.  Dynamic tails never false-
    positive; a typo in the literal prefix still flags."""
    if "*" not in consumed and "*" not in emitted:
        return consumed == emitted
    pc, pe = _prefix(consumed), _prefix(emitted)
    return pc.startswith(pe) or pe.startswith(pc)


def _edit_distance(a: str, b: str, cap: int = 3) -> int:
    if abs(len(a) - len(b)) > cap:
        return cap + 1
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, start=1):
        cur = [i]
        best = i
        for j, cb in enumerate(b, start=1):
            cur.append(min(prev[j] + 1, cur[-1] + 1,
                           prev[j - 1] + (ca != cb)))
            best = min(best, cur[-1])
        if best > cap:
            return cap + 1
        prev = cur
    return prev[-1]


class MetricRegistry:
    """Resolution over the emitted-name universe."""

    def __init__(self, emits: Sequence[StrSite]) -> None:
        self.exact: Set[str] = {s.value for s in emits if not s.pattern}
        self.globs: Set[str] = {_norm_glob(s.value) for s in emits}

    def _direct(self, glob: str) -> bool:
        if glob in self.exact:
            return True
        return any(_globs_compatible(glob, e) for e in self.globs)

    def _candidates(self, name: str) -> Iterable[str]:
        glob = _norm_glob(name)
        yield glob
        for suf in _DERIVED_SUFFIXES:
            if glob.endswith(suf):
                yield glob[: -len(suf)]
        if glob.startswith("Cluster."):
            rest = glob[len("Cluster."):]
            stems = [rest] + [rest[: -len(suf)]
                              for suf in _DERIVED_SUFFIXES
                              if rest.endswith(suf)]
            for src in _CLUSTER_SOURCES:
                for stem in stems:
                    yield src + stem

    def resolves(self, name: str) -> bool:
        return any(self._direct(c) for c in self._candidates(name))

    def nearest(self, name: str) -> Optional[Tuple[str, int]]:
        glob = _norm_glob(name)
        best: Optional[Tuple[str, int]] = None
        universe = set(self.exact) | {_prefix(g).rstrip(".")
                                      for g in self.globs if "*" in g}
        for cand in self._candidates(name):
            base = _prefix(cand).rstrip(".") if "*" in cand else cand
            for known in universe:
                d = _edit_distance(base, known)
                if d > 0 and (best is None or d < best[1]):
                    best = (known, d)
        del glob
        return best


def analyze(model: RepoModel, facts: RepoFacts) -> List[Finding]:
    findings: List[Finding] = []
    registry = MetricRegistry(facts.metric_emits)
    span_names = facts.span_names()

    # 1) emitted names follow the exposition-safe convention
    seen_invalid: Set[str] = set()
    for site in facts.metric_emits:
        probe = _norm_glob(site.value)
        if not _VALID_EMIT_RE.match(probe.replace("*", "x")) or \
                "__" in probe or probe.endswith("."):
            if site.value not in seen_invalid:
                seen_invalid.add(site.value)
                findings.append(Finding(
                    rule="metric-invalid-name", path=site.path,
                    line=site.line, anchor=site.value,
                    message=f"emitted metric name '{site.value}' violates "
                            f"the Instance.Name convention (letters, "
                            f"digits, '_' per dotted segment)"))

    # 2) consumed names resolve; near-misses are called out as typos
    flagged: Set[Tuple[str, str]] = set()
    for site in facts.metric_consumes:
        if site.value in facts.heartbeat_names:
            continue  # heartbeat thread names are their own registry
        if registry.resolves(site.value):
            continue
        key = (site.path, site.value)
        if key in flagged:
            continue
        flagged.add(key)
        near = registry.nearest(site.value)
        if near is not None and near[1] <= 2:
            findings.append(Finding(
                rule="metric-typo", path=site.path, line=site.line,
                anchor=site.value,
                message=f"'{site.value}' is emitted nowhere — did you "
                        f"mean '{near[0]}'? (edit distance {near[1]})"))
        else:
            findings.append(Finding(
                rule="metric-unknown", path=site.path, line=site.line,
                anchor=site.value,
                message=f"'{site.value}' matches no emitted metric name "
                        f"or family"))

    # doc-side checks compare against the whole emit universe — skip on
    # partial scans where most emitters were not collected
    if model.is_partial:
        return findings

    _conf_toks, metric_toks = doc_tokens(model)
    doc_blob = "\n".join(d.text for d in model.doc_files)
    for tok in metric_toks:
        if tok.value in facts.heartbeat_names:
            continue
        if registry.resolves(tok.value):
            continue
        key = (tok.path, tok.value)
        if key in flagged:
            continue
        flagged.add(key)
        near = registry.nearest(tok.value)
        if near is not None and near[1] <= 2:
            findings.append(Finding(
                rule="metric-typo", path=tok.path, line=tok.line,
                anchor=tok.value,
                message=f"doc mentions '{tok.value}' which is emitted "
                        f"nowhere — did you mean '{near[0]}'?"))
        else:
            findings.append(Finding(
                rule="metric-unknown", path=tok.path, line=tok.line,
                anchor=tok.value,
                message=f"doc mentions '{tok.value}' which matches no "
                        f"emitted metric name or family"))

    # 3) every emitted name is documented somewhere
    doc_globs = {_norm_glob(t.value) for t in metric_toks}
    reported: Set[str] = set()
    for site in facts.metric_emits:
        glob = _norm_glob(site.value)
        if glob in reported:
            continue
        documented = any(_globs_compatible(glob, d) for d in doc_globs) \
            or (not site.pattern and site.value in doc_blob)
        if not documented:
            reported.add(glob)
            findings.append(Finding(
                rule="metric-undocumented", path=site.path, line=site.line,
                anchor=site.value,
                message=f"emitted metric '{site.value}' appears in no doc "
                        f"(run `python -m alluxio_tpu.lint --write-docs`)"))

    del _conf_toks, span_names  # atpu.* (incl. spans) ride the conf analyzer
    return findings


def write_metrics_doc(path: str, facts: RepoFacts) -> None:
    """Regenerate docs/metrics.md: the emitted metric + span catalog."""
    emits: Dict[str, List[StrSite]] = {}
    for site in facts.metric_emits:
        emits.setdefault(_norm_glob(site.value), []).append(site)
    spans = sorted({_norm_glob(s.value) for s in facts.span_emits})

    lines = [
        "# Metrics & span catalog",
        "",
        "Every metric name (and dynamic family, `*` = runtime suffix)",
        "emitted by the codebase, with the module that emits it.",
        "**Generated** by `python -m alluxio_tpu.lint --write-docs`;",
        "`make lint` fails when an emitted name is missing here.",
        "Semantics live in the subsystem docs (observability.md,",
        "remote_reads.md, ufs_cold_reads.md, prefetch.md, qos.md,",
        "self_healing.md).",
        "",
        "| metric | emitted by |",
        "|---|---|",
    ]
    for name in sorted(emits):
        paths = sorted({s.path for s in emits[name]})
        lines.append(f"| `{name}` | {', '.join(paths)} |")
    lines += [
        "",
        "## Trace spans",
        "",
        "| span |",
        "|---|",
    ]
    for name in spans:
        lines.append(f"| `{name}` |")
    phase_sites: Dict[str, List[StrSite]] = {}
    for site in facts.phase_emits:
        phase_sites.setdefault(site.value, []).append(site)
    lines += [
        "",
        "## Read-path phases",
        "",
        "Typed phase events recorded inside spans (`Span.phase`);",
        "the catalog is `PHASES` in `alluxio_tpu/utils/tracing.py` and",
        "the critical-path analyzer ranks read-path time by these names",
        "(`fsadmin report readpath`, docs/observability.md).",
        "",
        "| phase | emitted by |",
        "|---|---|",
    ]
    for name in sorted(facts.phase_catalog):
        paths = sorted({s.path for s in phase_sites.get(name, ())})
        lines.append(f"| `{name}` | {', '.join(paths) or '-'} |")
    lines.append("")
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines))
