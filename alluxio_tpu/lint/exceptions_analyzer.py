"""Analyzer 4: exception discipline.

Two checks:

- ``except-swallow``: an ``except Exception`` (or bare ``except:``)
  handler on a server dispatch / heartbeat / control-loop path whose
  body neither logs nor re-raises.  A swallowed exception on those paths
  is how a worker keeps "heartbeating" while dead, or an RPC fails with
  no trace.  Counting a metric is not enough — nobody can debug a
  counter.  Suppress with
  ``# lint: allow[except-swallow] -- <why silence is correct>``.

- ``wire-error-unregistered``: a class derived from ``AlluxioTpuError``
  defined outside ``utils/exceptions.py`` without a
  ``register_wire_error(...)`` call in its module.  ``from_wire`` resolves
  types by name from the map built in that module; an unregistered
  subclass silently degrades to its base class across the wire, so a
  client ``except SpecificError`` stops matching.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from alluxio_tpu.lint.collect import RepoFacts
from alluxio_tpu.lint.findings import Finding
from alluxio_tpu.lint.model import PyFile, RepoModel, function_index

RULES = ("except-swallow", "wire-error-unregistered")

_EXCEPTIONS_PATH = "alluxio_tpu/utils/exceptions.py"

#: paths where a silent except Exception is a correctness bug, not taste
SCOPE_PREFIXES = ("alluxio_tpu/rpc/", "alluxio_tpu/master/",
                  "alluxio_tpu/worker/", "alluxio_tpu/heartbeat/",
                  "alluxio_tpu/qos/")

_LOGGERISH_RECEIVERS = {"LOG", "log", "logger", "logging", "_log",
                        "warnings", "traceback", "faulthandler"}
_LOGGERISH_METHODS = {"debug", "info", "warning", "warn", "error",
                      "exception", "critical", "log"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names: List[str] = []
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in nodes:
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
    return any(n in ("Exception", "BaseException") for n in names)


def _surfaces(handler: ast.ExceptHandler) -> bool:
    """Does the handler body log, re-raise, or otherwise surface?

    "Surface" also covers handing the bound exception to another
    function (``self._fail(e)`` — the error is routed, not dropped) and
    calling anything named like a logger (``_warn_rate_limited``)."""
    bound = handler.name  # `except Exception as e:` -> "e"
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            if bound and any(
                    isinstance(a, ast.Name) and a.id == bound
                    for a in list(node.args) +
                    [kw.value for kw in node.keywords]):
                return True  # exception object passed onward
            if isinstance(fn, ast.Attribute):
                recv = fn.value
                recv_name = recv.id if isinstance(recv, ast.Name) else \
                    (recv.attr if isinstance(recv, ast.Attribute) else "")
                attr_l = fn.attr.lower()
                if recv_name in _LOGGERISH_RECEIVERS or \
                        fn.attr in _LOGGERISH_METHODS or \
                        "warn" in attr_l or "log" in attr_l:
                    return True
                if fn.attr == "abort" and recv_name == "context":
                    return True  # grpc context.abort raises
            elif isinstance(fn, ast.Name):
                if fn.id in ("print",):  # CLI surfacing
                    return True
    return False


def _swallow_findings(pf: PyFile) -> List[Finding]:
    findings: List[Finding] = []
    for qualname, func in function_index(pf.tree):
        ordinal = 0
        for node in ast.walk(func):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _is_broad(handler):
                    continue
                ordinal += 1
                if _surfaces(handler):
                    continue
                findings.append(Finding(
                    rule="except-swallow", path=pf.path,
                    line=handler.lineno,
                    anchor=f"{qualname}#{ordinal}",
                    message=f"broad except in {qualname} neither logs "
                            f"nor re-raises — a failure here vanishes"))
    return findings


def _wire_findings(model: RepoModel) -> List[Finding]:
    # seed the family with every class defined in the canonical module
    family: Set[str] = set()
    for pf in model.py(_EXCEPTIONS_PATH):
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                family.add(node.name)
    if not family:
        return []  # partial scan without the canonical module

    # transitively find subclasses elsewhere (two passes handle one level
    # of indirection per pass; repeat until stable)
    classes: List[Tuple[PyFile, ast.ClassDef]] = []
    registered: Dict[str, Set[str]] = {}  # path -> names registered there
    for pf in model.py_files:
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                classes.append((pf, node))
                for dec in node.decorator_list:
                    name = dec.id if isinstance(dec, ast.Name) else \
                        getattr(dec, "attr", "")
                    if name == "register_wire_error":
                        registered.setdefault(pf.path, set()).add(node.name)
            elif isinstance(node, ast.Call):
                fn = node.func
                name = fn.id if isinstance(fn, ast.Name) else \
                    getattr(fn, "attr", "")
                if name == "register_wire_error":
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            registered.setdefault(pf.path, set()).add(a.id)

    changed = True
    members: List[Tuple[PyFile, ast.ClassDef]] = []
    while changed:
        changed = False
        for pf, node in classes:
            if node.name in family:
                continue
            bases = [b.id if isinstance(b, ast.Name) else
                     getattr(b, "attr", "") for b in node.bases]
            if any(b in family for b in bases):
                family.add(node.name)
                members.append((pf, node))
                changed = True

    findings: List[Finding] = []
    for pf, node in members:
        if pf.path == _EXCEPTIONS_PATH:
            continue
        if node.name in registered.get(pf.path, set()):
            continue
        findings.append(Finding(
            rule="wire-error-unregistered", path=pf.path, line=node.lineno,
            anchor=node.name,
            message=f"{node.name} subclasses AlluxioTpuError outside "
                    f"utils/exceptions.py and is never passed to "
                    f"register_wire_error(); from_wire() will degrade it "
                    f"to its base class"))
    return findings


def analyze(model: RepoModel, facts: RepoFacts) -> List[Finding]:
    del facts
    findings: List[Finding] = []
    for pf in model.py_files:
        # files outside the package were passed explicitly (fixtures,
        # ad-hoc runs) — scope filtering only applies to the repo walk
        if pf.path.startswith(SCOPE_PREFIXES) or \
                not pf.path.startswith("alluxio_tpu/"):
            findings.extend(_swallow_findings(pf))
    findings.extend(_wire_findings(model))
    return findings
