"""Analyzer 1: conf-key discipline.

Checks, against the live ``PropertyKey`` registry (imported, not
re-parsed — templates and aliases behave exactly as production):

- ``conf-unknown-key``       an ``atpu.*`` literal in code resolves to no
                             registered key, alias, template or span name
- ``conf-unknown-key-doc``   same for backticked doc mentions
- ``conf-dead-key``          a registered key no product code reads
                             (neither ``Keys.X`` nor a string literal)
- ``conf-undocumented-key``  a registered key absent from every doc conf
                             table (regenerate docs/configuration.md)
- ``conf-bad-default``       a declared default its own type fails to parse
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from alluxio_tpu.lint.collect import RepoFacts, doc_tokens
from alluxio_tpu.lint.findings import Finding
from alluxio_tpu.lint.model import RepoModel

RULES = ("conf-unknown-key", "conf-unknown-key-doc", "conf-dead-key",
         "conf-undocumented-key", "conf-bad-default")

_PROPERTY_KEY_PATH = "alluxio_tpu/conf/property_key.py"


def _registry():
    from alluxio_tpu.conf import property_key as pk

    return pk


def _keys_attr_map(model: RepoModel) -> Dict[str, str]:
    """``Keys.<ATTR>`` -> key name, from the catalog module's AST."""
    out: Dict[str, str] = {}
    for pf in model.py(_PROPERTY_KEY_PATH):
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ClassDef) or node.name != "Keys":
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Call) and \
                        stmt.value.args and \
                        isinstance(stmt.value.args[0], ast.Constant):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = stmt.value.args[0].value
    return out


def _resolve(pk, name: str, span_names: Set[str]) -> bool:
    """Does ``name`` (possibly a glob / template form) resolve?"""
    if pk.REGISTRY.get(name) is not None:
        return True
    if name in span_names:
        return True
    if pk.Template.match(name) is not None:
        return True
    if any(ch in name for ch in "*{<"):
        prefix = name
        for ch in "*{<":
            prefix = prefix.split(ch)[0]
        if not prefix:
            return False
        known: List[str] = list(pk.REGISTRY.all_keys())
        known.extend(a for a in getattr(pk.REGISTRY, "_aliases", {}))
        known.extend(span_names)
        known.extend(t.pattern.split("{")[0] for t in pk._TEMPLATES)
        return any(k.startswith(prefix) or prefix.startswith(k.split("{")[0])
                   for k in known)
    return False


def analyze(model: RepoModel, facts: RepoFacts) -> List[Finding]:
    pk = _registry()
    findings: List[Finding] = []
    span_names = facts.span_names()
    attr_map = _keys_attr_map(model)

    # 1) every atpu.* literal in code resolves
    for site in facts.conf_literals:
        if site.path == _PROPERTY_KEY_PATH:
            continue  # the catalog itself (registrations, alias tuples)
        if not _resolve(pk, site.value, span_names):
            findings.append(Finding(
                rule="conf-unknown-key", path=site.path, line=site.line,
                anchor=site.value,
                message=f"'{site.value}' resolves to no registered "
                        f"PropertyKey, alias, template or span name"))

    # 2) doc mentions resolve
    conf_tokens, _ = doc_tokens(model)
    seen_doc: Set[str] = set()
    for tok in conf_tokens:
        seen_doc.add(tok.value)
        if not _resolve(pk, tok.value, span_names):
            findings.append(Finding(
                rule="conf-unknown-key-doc", path=tok.path, line=tok.line,
                anchor=tok.value,
                message=f"doc mentions '{tok.value}' which resolves to no "
                        f"registered PropertyKey, template or span name"))

    # registry-level checks need the whole tree: a --changed run only saw
    # a slice of the usage sites, so "dead" would be meaningless noise
    if model.is_partial:
        return findings

    # Template-minted keys (tieredstore levels, mount options…) enter the
    # live REGISTRY at runtime — e.g. when an earlier test in the same
    # process called Template.format(). They have no static read site by
    # construction, so registry-level checks consider only statically
    # registered keys.
    all_keys = {n: k for n, k in pk.REGISTRY.all_keys().items()
                if pk.Template.match(n) is None}
    aliases: Dict[str, str] = dict(getattr(pk.REGISTRY, "_aliases", {}))

    # 3) every registered key is read by product code
    used: Set[str] = set()
    for attr, path, _line in facts.keys_attr_reads:
        if path == _PROPERTY_KEY_PATH:
            continue
        name = attr_map.get(attr)
        if name:
            used.add(name)
    for site in facts.conf_literals:
        if site.path == _PROPERTY_KEY_PATH:
            continue
        name = site.value
        canonical = aliases.get(name, name)
        if canonical in all_keys:
            used.add(canonical)
        elif site.pattern:
            prefix = name
            for ch in "*{<":
                prefix = prefix.split(ch)[0]
            used.update(k for k in all_keys if k.startswith(prefix))

    key_line = _key_def_lines(model)
    for name in sorted(all_keys):
        if name not in used:
            findings.append(Finding(
                rule="conf-dead-key", path=_PROPERTY_KEY_PATH,
                line=key_line.get(name, 1), anchor=name,
                message=f"registered key '{name}' is read by no product "
                        f"code (wire it through or delete it)"))

    # 4) every registered key appears in a docs conf table
    doc_blob = "\n".join(d.text for d in model.doc_files)
    for name in sorted(all_keys):
        if name not in doc_blob:
            findings.append(Finding(
                rule="conf-undocumented-key", path=_PROPERTY_KEY_PATH,
                line=key_line.get(name, 1), anchor=name,
                message=f"registered key '{name}' appears in no doc "
                        f"(run `python -m alluxio_tpu.lint --write-docs`)"))

    # 5) defaults parse under their declared type
    for name, key in sorted(all_keys.items()):
        if key.default is None:
            continue
        try:
            key.parse(key.default)
        except Exception as e:  # noqa: BLE001 - the failure IS the finding
            findings.append(Finding(
                rule="conf-bad-default", path=_PROPERTY_KEY_PATH,
                line=key_line.get(name, 1), anchor=name,
                message=f"default {key.default!r} of '{name}' fails its "
                        f"declared {key.key_type.name} parser: {e}"))
    return findings


def _key_def_lines(model: RepoModel) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for pf in model.py(_PROPERTY_KEY_PATH):
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Call) and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                fn = node.func
                name = fn.id if isinstance(fn, ast.Name) else \
                    getattr(fn, "attr", "")
                if name == "_k":
                    out[node.args[0].value] = node.lineno
    return out


def write_conf_doc(path: str) -> None:
    """Regenerate docs/configuration.md from the live registry."""
    pk = _registry()
    lines = [
        "# Configuration reference",
        "",
        "Every registered `atpu.*` property key. **Generated** by",
        "`python -m alluxio_tpu.lint --write-docs` from",
        "`alluxio_tpu/conf/property_key.py` — edit the catalog, then",
        "regenerate; `make lint` fails when a key is missing here.",
        "",
        "Parameterized families (per-tier stores, mount options,",
        "impersonation rules) are minted from templates at runtime and",
        "documented where they are used.",
        "",
        "| key | type | default | scope | description |",
        "|---|---|---|---|---|",
    ]
    for name, key in sorted(pk.REGISTRY.all_keys().items()):
        if pk.Template.match(name) is not None:
            continue  # runtime-minted template instance: not cataloged
        desc = " ".join((key.description or "").split())
        default = "" if key.default is None else f"`{key.default}`"
        if key.credentials:
            desc = (desc + " *(credential: masked on display surfaces)*"
                    ).strip()
        scope = str(key.scope).replace("Scope.", "")
        lines.append(f"| `{name}` | {key.key_type.value} | {default} "
                     f"| {scope} | {desc} |")
    lines.append("")
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines))
