import sys

from alluxio_tpu.lint.runner import main

sys.exit(main())
