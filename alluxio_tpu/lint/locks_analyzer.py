"""Analyzer 3: lock discipline — no blocking calls under a held lock.

Static half of the race tooling (``utils/race.py`` is the dynamic half):
per function, track ``with <lock>:`` regions and flag calls that can
block indefinitely while the lock is held — RPC invokes, UFS I/O,
``time.sleep``, stream/subprocess drains, unbounded ``Future.result()``
/ ``.wait()`` / ``Thread.join()``.  A *bounded* call (explicit timeout)
is exempt, mirroring the try-lock rule TSAN applies: a bounded wait
cannot convert a lock into a deadlock, only into latency.

``Condition.wait`` is exempt when the receiver looks like a condition
variable (``cond``/``cv``/``not_empty``/``all_tasks_done``…): waiting on
a condition RELEASES its lock — that is the one blocking-under-lock
pattern that is correct by construction.

Nested ``def``/``lambda`` bodies do not execute inside the region and
are skipped.  Cross-function blocking (helper called under a lock that
itself blocks) is out of scope for the static pass — the runtime
``LockOrderAuditor`` plugin covers what this cannot see.

Suppress with ``# lint: allow[lock-blocking-call] -- <why it is safe>``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from alluxio_tpu.lint.collect import RepoFacts
from alluxio_tpu.lint.findings import Finding
from alluxio_tpu.lint.model import PyFile, RepoModel, function_index

RULES = ("lock-blocking-call",)

#: a with-item guards a lock when its expression's terminal name matches
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mutex)s?$|_lock$", re.I)
_LOCK_CALL_METHODS = {"read_locked", "write_locked"}

#: receivers that look like condition variables (wait releases the lock)
_COND_RECV_RE = re.compile(
    r"(cond|cv$|not_empty|not_full|all_tasks_done|condition)", re.I)

_RPC_METHODS = {"call", "call_stream", "call_stream_in", "open_stream",
                "invoke"}
_UFS_METHODS = {"open", "read", "read_range", "write", "list_status",
                "get_status", "delete", "rename", "mkdirs", "exists",
                "content_length", "open_stream"}
_UFS_RECV_RE = re.compile(r"(^|_)ufs$|^ufs_|_ufs_", re.I)
_SOCKET_METHODS = {"recv", "sendall", "accept", "connect", "makefile"}
_SUBPROCESS_FNS = {"run", "check_output", "check_call", "communicate"}


def _dotted(expr: ast.AST) -> Optional[str]:
    """'self._lock' / 'time.sleep' for Name/Attribute chains, else None."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_expr(expr: ast.AST) -> Optional[str]:
    """Display name of the lock a with-item acquires, or None."""
    if isinstance(expr, ast.Call):
        fn = expr.func
        if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_CALL_METHODS:
            base = _dotted(fn.value) or "<expr>"
            return f"{base}.{fn.attr}()"
        # lock.acquire()-style context managers are not a with-pattern here
        return None
    dotted = _dotted(expr)
    if dotted is not None and _LOCK_NAME_RE.search(dotted.rsplit(".", 1)[-1]):
        return dotted
    return None


def _has_timeout(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout" and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None):
            return True
    return False


def _classify_blocking(call: ast.Call) -> Optional[str]:
    """Why this call blocks (short reason), or None when benign."""
    fn = call.func
    dotted = _dotted(fn) or ""
    attr = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    recv = dotted.rsplit(".", 1)[0] if "." in dotted else ""

    if dotted in ("time.sleep", "sleep"):
        return "time.sleep blocks every other waiter of the lock"
    if attr in _RPC_METHODS and isinstance(fn, ast.Attribute):
        if _has_timeout(call):
            return None
        return f"RPC '.{attr}(...)' holds the lock across a network " \
               f"round trip"
    if attr == "result" and isinstance(fn, ast.Attribute):
        if call.args or _has_timeout(call):
            return None  # bounded result(timeout) cannot deadlock
        return "unbounded Future.result() under a lock can deadlock " \
               "against the executor"
    if attr == "exception" and isinstance(fn, ast.Attribute) and \
            not call.args and not _has_timeout(call):
        return "unbounded Future.exception() under a lock can deadlock " \
               "against the executor"
    if attr == "wait" and isinstance(fn, ast.Attribute):
        if call.args or _has_timeout(call):
            return None
        if _COND_RECV_RE.search(recv):
            return None  # Condition.wait releases the lock
        return "unbounded .wait() under a lock"
    if attr == "join" and isinstance(fn, ast.Attribute) and \
            not call.args and not call.keywords:
        if _COND_RECV_RE.search(recv):
            return None
        return "unbounded .join() under a lock (str.join always has " \
               "an argument; this is a thread/process join)"
    if attr == "communicate" and isinstance(fn, ast.Attribute) and \
            not _has_timeout(call):
        return "subprocess .communicate() without timeout under a lock"
    if attr in _UFS_METHODS and isinstance(fn, ast.Attribute) and \
            _UFS_RECV_RE.search(recv.rsplit(".", 1)[-1] if recv else ""):
        return f"UFS I/O '.{attr}(...)' holds the lock across backing-" \
               f"store latency"
    if attr in _SOCKET_METHODS and isinstance(fn, ast.Attribute) and \
            re.search(r"(sock|socket|conn)$", recv.rsplit(".", 1)[-1]
                      if recv else "", re.I):
        return f"socket '.{attr}(...)' under a lock"
    if dotted.startswith("subprocess.") and attr in _SUBPROCESS_FNS and \
            not _has_timeout(call):
        return f"subprocess.{attr}(...) without timeout under a lock"
    if dotted in ("urllib.request.urlopen", "urlopen") and \
            not _has_timeout(call):
        return "urlopen without timeout under a lock"
    return None


class _FunctionScanner(ast.NodeVisitor):
    """Walks ONE function body tracking the held-lock stack."""

    def __init__(self, pf: PyFile, qualname: str,
                 findings: List[Finding],
                 counters: Dict[str, int]) -> None:
        self._pf = pf
        self._qual = qualname
        self._findings = findings
        self._counters = counters
        self._held: List[str] = []

    # nested defs/lambdas execute later, outside the region
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return None

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return None

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return None

    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            lock = _is_lock_expr(item.context_expr)
            if lock is not None:
                acquired.append(lock)
            else:
                self.visit(item.context_expr)
        self._held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        del self._held[len(self._held) - len(acquired):]

    def visit_Call(self, node: ast.Call) -> None:
        if self._held:
            reason = _classify_blocking(node)
            if reason is not None:
                callee = _dotted(node.func) or "<call>"
                base = f"{self._qual}:{callee}"
                n = self._counters.get(base, 0)
                self._counters[base] = n + 1
                anchor = base if n == 0 else f"{base}#{n}"
                self._findings.append(Finding(
                    rule="lock-blocking-call", path=self._pf.path,
                    line=node.lineno, anchor=anchor,
                    message=f"{reason} (holding {', '.join(self._held)} "
                            f"in {self._qual})"))
        self.generic_visit(node)


def analyze(model: RepoModel, facts: RepoFacts) -> List[Finding]:
    del facts
    findings: List[Finding] = []
    for pf in model.py_files:
        counters: Dict[str, int] = {}
        for qualname, func in function_index(pf.tree):
            scanner = _FunctionScanner(pf, qualname, findings, counters)
            for stmt in func.body:
                scanner.visit(stmt)
    return findings
