"""Always-on lock-order auditing for the test suite.

Promotes ``utils/race.py``'s :class:`LockOrderAuditor` from a
one-test curiosity into a pytest plugin (registered in
``tests/conftest.py``): the coarse master/worker/store locks are
auto-instrumented at construction time, every test runs with a fresh
auditor, and ANY lock pair observed in both orders — on any schedule,
even one that did not deadlock this run — fails that test with both
acquisition stacks.  This is the dynamic complement to the static
``lock-discipline`` analyzer (which cannot see cross-function blocking).

A :class:`~alluxio_tpu.utils.race.Watchdog` arms around every test so a
hang dumps every thread's stack to stderr instead of dying as a silent
CI timeout; the dump is diagnostic-only (the watchdog never fails a
slow-but-finishing test — this CI host steals CPU in multi-second
bursts).

Opt out per-run with ``ATPU_LOCK_AUDIT=0`` (e.g. when bisecting an
unrelated failure) or per-test with ``@pytest.mark.no_lockaudit``;
tune the hang-dump deadline with ``ATPU_LOCK_AUDIT_WATCHDOG_S``.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import List, Optional, Tuple

import pytest

from alluxio_tpu.utils.race import LockOrderAuditor, Watchdog, _LockProxy

_ENABLED = os.environ.get("ATPU_LOCK_AUDIT", "1") not in ("0", "false", "")
_WATCHDOG_S = float(os.environ.get("ATPU_LOCK_AUDIT_WATCHDOG_S", "240"))

#: (module path, class name, lock attribute, audited lock name) —
#: the coarse locks whose ordering defines the deadlock surface between
#: metadata, block map, store and metrics planes.
_INSTRUMENT: Tuple[Tuple[str, str, str, str], ...] = (
    ("alluxio_tpu.master.inode_tree", "InodeTree", "lock",
     "InodeTree.lock"),
    ("alluxio_tpu.master.inode_tree", "InodeTree", "registry_lock",
     "InodeTree.registry_lock"),
    # the journal's main lock doubles as the group-commit queue lock
    # (write_and_flush enqueues + applies under it; the flusher drains
    # under it) — auditing it proves the canonical order
    # inode locks -> journal commit lock holds across every test
    ("alluxio_tpu.journal.system", "LocalJournalSystem", "_lock",
     "LocalJournalSystem._lock"),
    ("alluxio_tpu.master.block_master", "BlockMaster", "_lock",
     "BlockMaster._lock"),
    ("alluxio_tpu.master.block_master", "BlockMaster", "_reserve_lock",
     "BlockMaster._reserve_lock"),
    ("alluxio_tpu.master.file_master", "FileSystemMaster", "_persist_mutex",
     "FileSystemMaster._persist_mutex"),
    ("alluxio_tpu.master.file_master", "FileSystemMaster",
     "_listing_cache_lock", "FileSystemMaster._listing_cache_lock"),
    ("alluxio_tpu.master.metrics_master", "MetricsStore", "_lock",
     "MetricsStore._lock"),
    ("alluxio_tpu.metrics.history", "MetricsHistory", "_lock",
     "MetricsHistory._lock"),
    ("alluxio_tpu.metrics.history", "MetricsHistory", "_pending_lock",
     "MetricsHistory._pending_lock"),
    ("alluxio_tpu.worker.tiered_store", "TieredBlockStore", "_alloc_lock",
     "TieredBlockStore._alloc_lock"),
    ("alluxio_tpu.worker.lock_manager", "BlockLockManager", "_meta_lock",
     "BlockLockManager._meta_lock"),
)


class _AuditorDelegate:
    """The auditor handle baked into every proxy: forwards to whichever
    per-test auditor is active, no-ops between tests.  Instances built
    in one test keep auditing correctly in the next — names, not object
    identities, define the order graph."""

    def __init__(self) -> None:
        self.current: Optional[LockOrderAuditor] = None

    def _before_acquire(self, name: str, blocking: bool = True) -> None:
        a = self.current
        if a is not None:
            a._before_acquire(name, blocking=blocking)

    def _acquired(self, name: str, *, record: bool = False) -> None:
        a = self.current
        if a is not None:
            a._acquired(name, record=record)

    def _abandoned(self, name: str) -> None:
        a = self.current
        if a is not None:
            a._abandoned(name)

    def _released(self, name: str) -> None:
        a = self.current
        if a is not None:
            a._released(name)


_DELEGATE = _AuditorDelegate()
_installed = False
_install_lock = threading.Lock()


def _install() -> None:
    """Patch each target class's ``__init__`` to wrap its lock attr in
    an audited proxy.  Installed once per process, active for the whole
    session; the delegate decides whether events are recorded."""
    global _installed
    with _install_lock:
        if _installed:
            return
        import importlib

        patched = {}
        for module_name, cls_name, attr, lock_name in _INSTRUMENT:
            mod = importlib.import_module(module_name)
            cls = getattr(mod, cls_name)
            patched.setdefault(cls, []).append((attr, lock_name))

        for cls, attrs in patched.items():
            orig_init = cls.__init__

            @functools.wraps(orig_init)
            def init(self, *a, _orig=orig_init, _attrs=tuple(attrs), **kw):
                _orig(self, *a, **kw)
                for attr, lock_name in _attrs:
                    inner = getattr(self, attr, None)
                    if inner is not None and \
                            not isinstance(inner, _LockProxy):
                        setattr(self, attr,
                                _LockProxy(inner, lock_name, _DELEGATE))

            cls.__init__ = init

        # Per-inode striped locks are created DYNAMICALLY by the
        # InodeLockManager pool, so attribute patching cannot reach
        # them; instead the manager's proxy-factory hook wraps every
        # fresh RWLock.  All of them audit under ONE name — the
        # root→leaf ordering *within* a path is structural (validated
        # by the concurrent-metadata property tests), while this name
        # puts the whole stripe set into the cross-plane order graph:
        # InodeTree.lock -> InodeTree.inode_lock ->
        # LocalJournalSystem._lock -> BlockMaster._lock.
        from alluxio_tpu.master.inode_tree import (
            InodeLockManager, InodeTree,
        )

        mgr_init = InodeLockManager.__init__

        @functools.wraps(mgr_init)
        def lock_mgr_init(self, *a, **kw):
            mgr_init(self, *a, **kw)
            self._proxy_factory = lambda lock: _LockProxy(
                lock, "InodeTree.inode_lock", _DELEGATE)

        InodeLockManager.__init__ = lock_mgr_init

        # WRITE_EDGE locks are a second dynamically-pooled stripe set,
        # keyed (parent_id, name).  They get their OWN audited name so
        # the graph proves the canonical order inode locks -> edge
        # locks (docs/metadata.md) — under one shared name an
        # inode-then-edge acquisition would be invisible self-ordering.
        tree_init = InodeTree.__init__

        @functools.wraps(tree_init)
        def inode_tree_init(self, *a, **kw):
            tree_init(self, *a, **kw)
            self.edge_lock_manager._proxy_factory = \
                lambda lock: _LockProxy(
                    lock, "InodeTree.edge_lock", _DELEGATE)

        InodeTree.__init__ = inode_tree_init
        _installed = True


def pytest_configure(config) -> None:
    config.addinivalue_line(
        "markers",
        "no_lockaudit: disable lock-order auditing for this test")
    if _ENABLED:
        _install()


@pytest.fixture(autouse=True)
def _lock_order_audit(request):
    if not _ENABLED or \
            request.node.get_closest_marker("no_lockaudit") is not None:
        yield
        return
    auditor = LockOrderAuditor()
    _DELEGATE.current = auditor
    wd = Watchdog(_WATCHDOG_S)
    wd.__enter__()
    try:
        yield
    finally:
        # manual exit: the watchdog dump is diagnostic-only — never turn
        # a slow-but-finishing test into a failure on a stolen-CPU box
        if wd._timer is not None:
            wd._timer.cancel()
        _DELEGATE.current = None
    if wd.fired:
        import warnings

        warnings.warn(
            f"lockaudit watchdog fired after {_WATCHDOG_S:.0f}s "
            f"(thread stacks were dumped to stderr)", stacklevel=1)
    # raising in teardown errors the test — an observed inversion on ANY
    # schedule proves a deadlocking schedule exists
    auditor.assert_clean()


def observed_edges() -> List[Tuple[str, str]]:
    """Test helper: edges of the active auditor (empty between tests)."""
    a = _DELEGATE.current
    return sorted(a.edges) if a is not None else []
