"""Worker data plane (reference: ``core/server/worker``)."""

from alluxio_tpu.worker.meta import (  # noqa: F401
    BlockMeta, BlockMetadataManager, StorageDir, StorageTier, TempBlockMeta,
)
from alluxio_tpu.worker.process import BlockWorker, build_store_from_conf  # noqa: F401
from alluxio_tpu.worker.tiered_store import TieredBlockStore  # noqa: F401
from alluxio_tpu.worker.ufs_fetch import (  # noqa: F401
    BlockFetch, FetchConf, UfsBlockFetcher,
)
from alluxio_tpu.worker.ufs_io import UfsBlockDescriptor  # noqa: F401
