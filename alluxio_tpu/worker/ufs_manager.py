"""Worker-side UFS manager.

Re-design of ``core/server/worker/src/main/java/alluxio/worker/underfs/
WorkerUfsManager.java``: the worker resolves mount-id -> UFS lazily by
asking the master for its mount table, then caches instances locally (the
reference pulls ``UfsInfo`` by mount id over the FileSystemMasterWorker
service).
"""

from __future__ import annotations

from alluxio_tpu.underfs.registry import UfsManager


class WorkerUfsManager:
    """UFS manager that learns mounts from the master on demand."""

    def __init__(self, fs_master_client) -> None:
        self._inner = UfsManager()
        self._fs = fs_master_client

    def get(self, mount_id: int):
        if not self._inner.has(mount_id):
            for mp in self._fs.get_mount_points():
                if not self._inner.has(mp.mount_id):
                    self._inner.add_mount(mp.mount_id, mp.ufs_uri,
                                          mp.properties)
        return self._inner.get(mount_id)

    def has(self, mount_id: int) -> bool:
        return self._inner.has(mount_id)

    def add_mount(self, *a, **k):
        return self._inner.add_mount(*a, **k)

    def remove_mount(self, mount_id: int) -> None:
        self._inner.remove_mount(mount_id)

    def close(self) -> None:
        self._inner.close()
