"""Worker storage metadata: tiers, dirs, block records.

Re-design of ``core/server/worker/.../block/meta/{StorageTier.java:48,
StorageDir.java:52,BlockMeta,TempBlockMeta}.java`` +
``BlockMetadataManager.java``. Tier ordering is by *ordinal* (0 fastest);
default aliases MEM (``/dev/shm`` — mmap-able by same-host clients for the
short-circuit zero-copy path) then SSD then HDD. The HBM tier lives
client-side (see ``client/cache/hbm_store.py``): device memory belongs to
the training process, so the worker's job is to stage bytes where the
client can map them without a copy.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class BlockMeta:
    block_id: int
    length: int
    dir: "StorageDir"

    @property
    def tier_alias(self) -> str:
        return self.dir.tier.alias

    @property
    def path(self) -> str:
        return self.dir.block_path(self.block_id)


@dataclass
class TempBlockMeta:
    block_id: int
    session_id: int
    dir: "StorageDir"
    bytes_reserved: int  # space accounted during write

    @property
    def path(self) -> str:
        return self.dir.temp_block_path(self.session_id, self.block_id)


class StorageDir:
    def __init__(self, tier: "StorageTier", index: int, path: str,
                 capacity_bytes: int, medium_type: str = "") -> None:
        self.tier = tier
        self.index = index
        self.path = path
        self.capacity_bytes = capacity_bytes
        self.medium_type = medium_type or tier.alias
        self._used = 0
        self._blocks: Dict[int, BlockMeta] = {}
        self._temp: Dict[int, TempBlockMeta] = {}
        self._lock = threading.RLock()
        os.makedirs(path, exist_ok=True)
        os.makedirs(self._tmp_root(), exist_ok=True)

    def _tmp_root(self) -> str:
        return os.path.join(self.path, ".tmp")

    def block_path(self, block_id: int) -> str:
        return os.path.join(self.path, str(block_id))

    def temp_block_path(self, session_id: int, block_id: int) -> str:
        return os.path.join(self._tmp_root(), f"{session_id}_{block_id}")

    # -- accounting ---------------------------------------------------------
    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    @property
    def available_bytes(self) -> int:
        with self._lock:
            return self.capacity_bytes - self._used

    def reserve(self, n: int) -> bool:
        with self._lock:
            if self._used + n > self.capacity_bytes:
                return False
            self._used += n
            return True

    def release(self, n: int) -> None:
        with self._lock:
            self._used = max(0, self._used - n)

    def force_reserve(self, n: int) -> None:
        """Account bytes that are already on disk even past capacity
        (short-circuit writes can overshoot; truth beats the quota)."""
        with self._lock:
            self._used += n

    # -- block records ------------------------------------------------------
    def add_block(self, meta: BlockMeta) -> None:
        with self._lock:
            self._blocks[meta.block_id] = meta

    def remove_block(self, block_id: int) -> Optional[BlockMeta]:
        with self._lock:
            return self._blocks.pop(block_id, None)

    def get_block(self, block_id: int) -> Optional[BlockMeta]:
        with self._lock:
            return self._blocks.get(block_id)

    def block_ids(self) -> List[int]:
        with self._lock:
            return list(self._blocks.keys())

    def add_temp(self, meta: TempBlockMeta) -> None:
        with self._lock:
            self._temp[meta.block_id] = meta

    def remove_temp(self, block_id: int) -> Optional[TempBlockMeta]:
        with self._lock:
            return self._temp.pop(block_id, None)

    def get_temp(self, block_id: int) -> Optional[TempBlockMeta]:
        with self._lock:
            return self._temp.get(block_id)

    def temp_blocks_of_session(self, session_id: int) -> List[TempBlockMeta]:
        with self._lock:
            return [t for t in self._temp.values()
                    if t.session_id == session_id]


class StorageTier:
    def __init__(self, alias: str, ordinal: int) -> None:
        self.alias = alias
        self.ordinal = ordinal
        self.dirs: List[StorageDir] = []

    def add_dir(self, path: str, capacity_bytes: int,
                medium_type: str = "") -> StorageDir:
        d = StorageDir(self, len(self.dirs), path, capacity_bytes, medium_type)
        self.dirs.append(d)
        return d

    @property
    def capacity_bytes(self) -> int:
        return sum(d.capacity_bytes for d in self.dirs)

    @property
    def used_bytes(self) -> int:
        return sum(d.used_bytes for d in self.dirs)

    @property
    def available_bytes(self) -> int:
        return sum(d.available_bytes for d in self.dirs)


class BlockMetadataManager:
    """All tiers + lookup across them (reference: BlockMetadataManager)."""

    def __init__(self) -> None:
        self.tiers: List[StorageTier] = []
        self._by_alias: Dict[str, StorageTier] = {}

    def add_tier(self, alias: str) -> StorageTier:
        tier = StorageTier(alias, len(self.tiers))
        self.tiers.append(tier)
        self._by_alias[alias] = tier
        return tier

    def get_tier(self, alias: str) -> StorageTier:
        return self._by_alias[alias]

    def has_tier(self, alias: str) -> bool:
        return alias in self._by_alias

    def tier_below(self, alias: str) -> Optional[StorageTier]:
        t = self._by_alias[alias]
        if t.ordinal + 1 < len(self.tiers):
            return self.tiers[t.ordinal + 1]
        return None

    def tier_above(self, alias: str) -> Optional[StorageTier]:
        t = self._by_alias[alias]
        if t.ordinal > 0:
            return self.tiers[t.ordinal - 1]
        return None

    def get_block(self, block_id: int) -> Optional[BlockMeta]:
        for tier in self.tiers:
            for d in tier.dirs:
                meta = d.get_block(block_id)
                if meta is not None:
                    return meta
        return None

    def get_temp(self, block_id: int) -> Optional[TempBlockMeta]:
        for tier in self.tiers:
            for d in tier.dirs:
                meta = d.get_temp(block_id)
                if meta is not None:
                    return meta
        return None

    def blocks_on_tiers(self) -> Dict[str, List[int]]:
        return {tier.alias: [bid for d in tier.dirs for bid in d.block_ids()]
                for tier in self.tiers}

    def capacity_on_tiers(self) -> Dict[str, int]:
        return {t.alias: t.capacity_bytes for t in self.tiers}

    def used_on_tiers(self) -> Dict[str, int]:
        return {t.alias: t.used_bytes for t in self.tiers}
