"""Per-block client read/write locks.

Re-design of ``core/server/worker/.../block/{BlockLockManager.java,
ClientRWLock.java}``: readers hold shared locks while a block is being
served (or mmap'd by a short-circuit client); remove/move/evict need the
exclusive lock. ``try_`` variants let eviction skip in-use blocks instead
of blocking the allocation path.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from alluxio_tpu.utils.locks import RWLock


class BlockLock:
    """A held lock lease; close() releases."""

    def __init__(self, manager: "BlockLockManager", block_id: int,
                 write: bool) -> None:
        self._manager = manager
        self.block_id = block_id
        self.write = write
        self._released = False

    def close(self) -> None:
        if not self._released:
            self._released = True
            self._manager._release(self.block_id, self.write)

    def __enter__(self) -> "BlockLock":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class BlockLockManager:
    def __init__(self) -> None:
        self._locks: Dict[int, RWLock] = {}
        self._refs: Dict[int, int] = {}
        self._meta_lock = threading.Lock()

    def _get(self, block_id: int) -> RWLock:
        with self._meta_lock:
            lock = self._locks.get(block_id)
            if lock is None:
                lock = RWLock()
                self._locks[block_id] = lock
            self._refs[block_id] = self._refs.get(block_id, 0) + 1
            return lock

    def _release(self, block_id: int, write: bool) -> None:
        with self._meta_lock:
            lock = self._locks.get(block_id)
        if lock is None:
            return
        if write:
            lock.release_write()
        else:
            lock.release_read()
        with self._meta_lock:
            self._refs[block_id] -= 1
            if self._refs[block_id] <= 0:
                self._refs.pop(block_id, None)
                self._locks.pop(block_id, None)

    def _drop_ref(self, block_id: int) -> None:
        with self._meta_lock:
            self._refs[block_id] -= 1
            if self._refs[block_id] <= 0:
                self._refs.pop(block_id, None)
                self._locks.pop(block_id, None)

    def lock_read(self, block_id: int, timeout: Optional[float] = None
                  ) -> Optional[BlockLock]:
        lock = self._get(block_id)
        if lock.acquire_read(timeout):
            return BlockLock(self, block_id, write=False)
        self._drop_ref(block_id)
        return None

    def lock_write(self, block_id: int, timeout: Optional[float] = None
                   ) -> Optional[BlockLock]:
        lock = self._get(block_id)
        if lock.acquire_write(timeout):
            return BlockLock(self, block_id, write=True)
        self._drop_ref(block_id)
        return None

    def try_lock_write(self, block_id: int) -> Optional[BlockLock]:
        """Non-blocking exclusive attempt (eviction uses this to skip
        blocks currently pinned by readers)."""
        return self.lock_write(block_id, timeout=0.0)

    def active_locks(self) -> int:
        with self._meta_lock:
            return len(self._locks)
