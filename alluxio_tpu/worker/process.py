"""Worker process assembly.

Re-design of ``core/server/worker/.../{AlluxioWorkerProcess.java,
block/DefaultBlockWorker.java:77,197-242}``: builds the tiered store from
config (tier templates), wires the master-sync heartbeats, the UFS
read-through path and the async cache manager, and exposes the block-level
API the data server handlers call. Transport-independent: the gRPC data
server (``worker/data_server.py``) and in-process tests drive the same
object.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional

from alluxio_tpu.conf import Configuration, Keys, Templates
from alluxio_tpu.heartbeat import HeartbeatContext, HeartbeatThread
from alluxio_tpu.underfs.registry import UfsManager
from alluxio_tpu.utils import ids as id_utils
from alluxio_tpu.utils.wire import TieredIdentity, WorkerNetAddress
from alluxio_tpu.worker.allocator import Allocator
from alluxio_tpu.worker.annotator import BlockAnnotator
from alluxio_tpu.worker.master_sync import (
    BlockMasterSync, PinListSync, StorageChecker,
)
from alluxio_tpu.worker.management import ManagementTaskCoordinator
from alluxio_tpu.worker.meta import BlockMetadataManager
from alluxio_tpu.worker.tiered_store import BlockReader, TieredBlockStore
from alluxio_tpu.worker.ufs_fetch import (
    BlockFetch, FetchConf, UfsBlockFetcher,
)
from alluxio_tpu.worker.ufs_io import AsyncCacheManager, UfsBlockDescriptor

LOG = logging.getLogger(__name__)


class LocalBlockLease:
    """Short-circuit lease: path + held shared lock; close() releases."""

    def __init__(self, path: str, length: int, lock) -> None:
        self.path = path
        self.length = length
        self._lock = lock

    def close(self) -> None:
        self._lock.close()

    def __enter__(self) -> "LocalBlockLease":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def build_store_from_conf(conf: Configuration) -> TieredBlockStore:
    """Tier layout from the template keys
    (reference: WORKER_TIERED_STORE_LEVELS + per-level templates)."""
    meta = BlockMetadataManager()
    levels = conf.get_int(Keys.WORKER_TIERED_STORE_LEVELS)
    data_folder = conf.get(Keys.WORKER_DATA_FOLDER)
    shm_dir = conf.get(Keys.WORKER_SHM_DIR)
    ram_size = conf.get_bytes(Keys.WORKER_RAMDISK_SIZE)
    for lvl in range(levels):
        alias = conf.get(Templates.WORKER_TIER_ALIAS.format(lvl)) or \
            {0: "MEM", 1: "SSD", 2: "HDD"}.get(lvl, f"TIER{lvl}")
        tier = meta.add_tier(alias)
        paths = conf.get_list(Templates.WORKER_TIER_DIRS_PATH.format(lvl))
        quotas = conf.get_list(Templates.WORKER_TIER_DIRS_QUOTA.format(lvl))
        if not paths:
            if alias == "MEM":
                paths = [os.path.join(shm_dir, "mem")]
                quotas = quotas or [str(ram_size)]
            else:
                paths = [os.path.join(data_folder, alias.lower())]
                quotas = quotas or [str(4 * ram_size)]
        for i, p in enumerate(paths):
            from alluxio_tpu.conf.property_key import parse_bytes

            quota = parse_bytes(quotas[i]) if i < len(quotas) else ram_size
            tier.add_dir(p, quota, medium_type=alias)
    allocator = Allocator.create(conf.get(Keys.WORKER_ALLOCATOR_CLASS), meta)
    ann_kind = conf.get(Keys.WORKER_ANNOTATOR_CLASS)
    if ann_kind == "LRFU":
        annotator = BlockAnnotator.create(
            "LRFU", step_factor=conf.get_float(Keys.WORKER_LRFU_STEP_FACTOR),
            attenuation_factor=conf.get_float(
                Keys.WORKER_LRFU_ATTENUATION_FACTOR))
    else:
        annotator = BlockAnnotator.create(ann_kind)
    return TieredBlockStore(meta, allocator, annotator)


class _MetricsReporter:
    """Ships this worker's metric snapshot — plus any completed trace
    spans drained from the local ring — to the master each tick for
    cluster aggregation and trace stitching (reference: worker side of
    metric_master.proto)."""

    def __init__(self, meta_client, source: str) -> None:
        self._client = meta_client
        self._source = source

    def heartbeat(self) -> None:
        from alluxio_tpu.metrics import metrics
        from alluxio_tpu.utils import faults
        from alluxio_tpu.utils.tracing import tracer

        if faults.armed() and \
                faults.injector().heartbeat_frozen(self._source):
            # injected fault: the node is alive but its telemetry is
            # not — exactly the wedge the heartbeat-staleness rule and
            # the quarantine remediation exist to catch
            return
        spans = tracer().drain(500) if tracer().enabled else []
        from alluxio_tpu.utils.profiler import profiler

        flame = profiler().drain() if profiler().running else None
        try:
            self._client.metrics_heartbeat(self._source,
                                           metrics().snapshot(),
                                           spans=spans, profile=flame)
        except Exception:  # noqa: BLE001 master transition: retry next tick
            # spans riding this tick are dropped — tracing is telemetry,
            # re-queueing could double-ship on a late-delivered RPC
            LOG.debug("metrics heartbeat failed", exc_info=True)

    def close(self) -> None:
        pass


class BlockWorker:
    """The worker: tiered store + protocols. Reference: DefaultBlockWorker."""

    def __init__(self, conf: Configuration, block_master_client,
                 fs_master_client=None,
                 ufs_manager: Optional[UfsManager] = None,
                 address: Optional[WorkerNetAddress] = None,
                 meta_master_client=None) -> None:
        self._meta_client = meta_master_client
        self._conf = conf
        from alluxio_tpu.utils import faults

        # arm the conf-gated fault hooks (atpu.debug.fault.*) — a
        # no-op with the defaults; chaos/self-healing tests set them
        faults.injector().configure(conf)
        self.store = build_store_from_conf(conf)
        self.ufs_manager = ufs_manager or UfsManager()
        host = conf.get(Keys.WORKER_HOSTNAME)
        self.address = address or WorkerNetAddress(
            host=host,
            rpc_port=conf.get_int(Keys.WORKER_RPC_PORT),
            shm_dir=conf.get(Keys.WORKER_SHM_DIR),
            tiered_identity=TieredIdentity.from_spec(
                conf.get(Keys.TIERED_IDENTITY), hostname=host))
        self._master_sync = BlockMasterSync(self.store, self.address,
                                            block_master_client)
        self._pin_sync = PinListSync(self.store, fs_master_client) \
            if fs_master_client is not None else None
        self._storage_checker = StorageChecker(self.store)
        self._mgmt = ManagementTaskCoordinator(
            self.store,
            align=conf.get_bool(Keys.WORKER_MANAGEMENT_TIER_ALIGN_ENABLED),
            promote=conf.get_bool(Keys.WORKER_MANAGEMENT_TIER_PROMOTE_ENABLED),
            quota_percent=conf.get_int(
                Keys.WORKER_MANAGEMENT_PROMOTE_QUOTA_PERCENT))
        self.ufs_fetcher = UfsBlockFetcher(
            self.store, FetchConf.from_conf(conf),
            host=self.address.tiered_identity.value("host")
            or self.address.host)
        from alluxio_tpu.worker.shm_store import ShmStore

        # same-host zero-copy plane: lease registry over the MEM tier's
        # /dev/shm segments (shm/, docs/small_reads.md)
        self.shm_store = ShmStore(
            self.store,
            lease_ttl_s=conf.get_duration_s(Keys.WORKER_SHM_LEASE_TTL),
            max_leases=conf.get_int(Keys.WORKER_SHM_MAX_LEASES),
            host=self.address.tiered_identity.value("host")
            or self.address.host)
        self.web_server = None
        self.web_port: Optional[int] = None
        qos_enabled = conf.get_bool(Keys.WORKER_QOS_ENABLED)
        self.async_cache = AsyncCacheManager(
            self.store, lambda mount_id: self.ufs_manager.get(mount_id),
            num_threads=conf.get_int(Keys.WORKER_ASYNC_CACHE_THREADS),
            queue_max=conf.get_int(Keys.WORKER_ASYNC_CACHE_QUEUE_MAX),
            fetcher=self.ufs_fetcher, prioritize=qos_enabled)
        if qos_enabled:
            from alluxio_tpu.metrics import metrics as _metrics

            # Worker.Qos* gauges ride the metrics heartbeat into the
            # master's Cluster.* aggregates and history series
            reg = _metrics()
            fetcher = self.ufs_fetcher
            reg.register_gauge(
                "Worker.QosFetchDeferred",
                lambda: fetcher.qos_stats()["deferred"])
            reg.register_gauge(
                "Worker.QosFetchQueued",
                lambda: fetcher.qos_stats()["queued"])
            reg.register_gauge(
                "Worker.QosFetchPromotedTotal",
                lambda: fetcher.qos_stats()["promoted"])
        self._threads: List[HeartbeatThread] = []
        self._started = False

    # -- lifecycle ----------------------------------------------------------
    @property
    def worker_id(self) -> Optional[int]:
        return self._master_sync.worker_id

    def start(self) -> None:
        """Register then start heartbeats
        (reference: ``DefaultBlockWorker.start:197-242``)."""
        from alluxio_tpu.utils.pause_monitor import ensure_process_monitor
        from alluxio_tpu.utils.tracing import (
            apply_trace_conf, set_tracing_enabled,
        )

        set_tracing_enabled(self._conf.get_bool(Keys.TRACE_ENABLED))
        apply_trace_conf(self._conf)
        from alluxio_tpu.utils.profiler import apply_profile_conf

        apply_profile_conf(self._conf)
        ensure_process_monitor()
        self._master_sync.register_with_master()
        if self._meta_client is not None:
            try:  # config consistency report (ServerConfigurationChecker)
                self._meta_client.register_node_conf(
                    f"worker-{self.address.host}:{self.address.rpc_port}",
                    {k: str(v) for k, v in self._conf.to_map().items()})
            except Exception:  # noqa: BLE001 - older master
                LOG.debug("config report failed", exc_info=True)
        hb_interval = self._conf.get_duration_s(
            Keys.WORKER_BLOCK_HEARTBEAT_INTERVAL)
        mgmt_interval = self._conf.get_duration_s(
            Keys.WORKER_MANAGEMENT_TASK_INTERVAL)
        self._threads = [
            HeartbeatThread(HeartbeatContext.WORKER_BLOCK_SYNC,
                            self._master_sync, hb_interval),
            HeartbeatThread(HeartbeatContext.WORKER_STORAGE_HEALTH,
                            self._storage_checker, 60.0),
            HeartbeatThread(HeartbeatContext.WORKER_MANAGEMENT_TASKS,
                            self._mgmt, mgmt_interval),
        ]
        if self._meta_client is not None:
            self._threads.append(HeartbeatThread(
                HeartbeatContext.WORKER_CLIENT_METRICS,
                _MetricsReporter(
                    self._meta_client,
                    f"worker-{self.address.host}:{self.address.rpc_port}"),
                self._conf.get_duration_s(
                    Keys.WORKER_METRICS_HEARTBEAT_INTERVAL)))
        if self._pin_sync is not None:
            self._threads.append(
                HeartbeatThread(HeartbeatContext.WORKER_PIN_LIST_SYNC,
                                self._pin_sync, hb_interval))
        from alluxio_tpu.metrics import metrics as _metrics
        from alluxio_tpu.metrics.sinks import SinkManager

        self.sink_manager = SinkManager(self._conf, _metrics())
        if self.sink_manager.sinks:
            self._threads.append(HeartbeatThread(
                HeartbeatContext.WORKER_METRICS_SINKS, self.sink_manager,
                self._conf.get_duration_s(Keys.METRICS_SINK_INTERVAL)))
        self.maybe_start_web()
        for t in self._threads:
            t.start()
        self._started = True

    def maybe_start_web(self) -> None:
        """Start the read-only web endpoint when enabled (safe to call
        without the heartbeat machinery: serves live store state)."""
        if self.web_server is None and \
                self._conf.get_bool(Keys.WORKER_WEB_ENABLED):
            from alluxio_tpu.worker.web import WorkerWebServer

            self.web_server = WorkerWebServer(
                self, port=self._conf.get_int(Keys.WORKER_WEB_PORT),
                bind_host=self._conf.get(Keys.WORKER_WEB_BIND_HOST))
            self.web_port = self.web_server.start()

    def stop(self) -> None:
        for t in self._threads:
            t.stop()
        if self.web_server is not None:
            self.web_server.stop()
            self.web_server = None
        self.async_cache.close()
        self.ufs_fetcher.close()

    # -- data-plane API (called by the data server / local clients) --------
    def create_block(self, session_id: int, block_id: int, *,
                     initial_bytes: int, tier_alias: str = "") -> str:
        """Returns the temp-block *path* — the short-circuit write lease
        (reference: ``CreateLocalBlock`` in block_worker.proto:127-152)."""
        temp = self.store.create_block(session_id, block_id,
                                       initial_bytes=initial_bytes,
                                       tier_alias=tier_alias)
        return temp.path

    def get_temp_writer(self, session_id: int, block_id: int):
        return self.store.get_temp_writer(session_id, block_id)

    def commit_block(self, session_id: int, block_id: int,
                     pinned: bool = False) -> None:
        """Commit locally then report to the master (reference:
        ``DefaultBlockWorker.commitBlock`` -> BlockMasterClient.commitBlock).

        The heartbeat "committed" delta is emitted only AFTER the master
        acknowledges: a delta arriving before the commit RPC makes the
        master free the block as an orphan (observed race)."""
        meta = self.store.commit_block(session_id, block_id, pinned,
                                       emit=False)
        client = self._master_sync._client
        try:
            if self._master_sync.worker_id is not None:
                used = self.store.meta.get_tier(meta.tier_alias).used_bytes
                client.commit_block(self._master_sync.worker_id, used,
                                    meta.tier_alias, block_id, meta.length)
        finally:
            # emit even when the RPC failed: the heartbeat delta then tells
            # the master about the block, which either records it (RPC
            # actually landed) or frees the orphan — both clean outcomes
            self.store._emit("committed", block_id)

    def abort_block(self, session_id: int, block_id: int) -> None:
        self.store.abort_block(session_id, block_id)

    def open_reader(self, block_id: int) -> BlockReader:
        """Local committed-block reader (holds the shared lock)."""
        return self.store.get_reader(block_id)

    def open_local_block(self, block_id: int) -> "LocalBlockLease":
        """Short-circuit read lease: the committed block file's path plus a
        shared lock held until the lease closes, so eviction cannot unlink
        the file mid-mmap (reference: ``OpenLocalBlock`` +
        ``ShortCircuitBlockReadHandler`` keep a block lock for the stream's
        lifetime)."""
        lock = self.store.pin_block(block_id)
        meta = self.store.get_block_meta(block_id)
        if meta is None:  # raced with eviction between pin and lookup
            lock.close()
            from alluxio_tpu.utils.exceptions import BlockDoesNotExistError

            raise BlockDoesNotExistError(f"block {block_id} not cached")
        return LocalBlockLease(meta.path, meta.length, lock)

    def open_ufs_fetch(self, desc: UfsBlockDescriptor, *,
                       cache: bool = True, priority: int = 0,
                       tenant: str = "") -> BlockFetch:
        """Start (or join) the striped cold fetch of a block; the
        returned handle streams chunks as stripes land — the data
        server serves from it while the tiered store fills in
        parallel.  ``priority``/``tenant`` feed the QoS scheduler
        (default ON_DEMAND, anonymous tenant)."""
        ufs = self.ufs_manager.get(desc.mount_id)
        return self.ufs_fetcher.fetch(ufs, desc, cache=cache,
                                      priority=priority, tenant=tenant)

    def read_ufs_block(self, desc: UfsBlockDescriptor, *,
                       cache: bool = True) -> bytes:
        """Cold read-through, whole block at once (reference:
        UnderFileSystemBlockReader). Rides the same striped/coalesced
        pipeline as :meth:`open_ufs_fetch`."""
        return self.open_ufs_fetch(desc, cache=cache).result()

    def persist_file(self, ufs_path: str, block_ids: List[int],
                     mount_id: int) -> str:
        """Write locally-cached blocks out as one UFS file; returns the UFS
        content fingerprint (reference: the worker-side persist executor,
        ``worker/file/`` + job-service ``PersistDefinition``)."""
        ufs = self.ufs_manager.get(mount_id)
        with ufs.create(ufs_path) as out:
            for bid in block_ids:
                with self.open_reader(bid) as r:
                    pos = 0
                    while pos < r.length:
                        chunk = r.read(pos, 4 << 20)
                        if not chunk:
                            raise IOError(
                                f"block {bid} truncated at {pos} "
                                f"(expected {r.length} bytes)")
                        out.write(chunk)
                        pos += len(chunk)
        return ufs.get_fingerprint(ufs_path).serialize()

    def cleanup_session(self, session_id: int) -> None:
        self.shm_store.close_session(session_id)
        self.store.cleanup_session(session_id)
