"""Worker->master sync protocols.

Re-designs of ``core/server/worker/.../block/{BlockMasterSync.java:51,
BlockHeartbeatReporter.java,PinListSync.java}`` and the storage health check
(``DefaultBlockWorker.StorageChecker:624``).

The master client is duck-typed: in-process tests pass the ``BlockMaster``
object wrapped in ``InProcessBlockMasterClient``; distributed deployments
pass the gRPC client (same surface) — the protocol code cannot tell.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Set

from alluxio_tpu.heartbeat import HeartbeatExecutor
from alluxio_tpu.master.block_master import WorkerCommand
from alluxio_tpu.utils import ids as id_utils
from alluxio_tpu.utils.wire import WorkerNetAddress
from alluxio_tpu.worker.tiered_store import TieredBlockStore

LOG = logging.getLogger(__name__)


class BlockHeartbeatReporter:
    """Accumulates block movements between heartbeats
    (reference: ``BlockHeartbeatReporter``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._added: Dict[str, List[int]] = {}
        self._removed: List[int] = []

    def on_event(self, store: TieredBlockStore, event: str, block_id: int) -> None:
        meta = store.get_block_meta(block_id)
        with self._lock:
            if event in ("committed", "moved") and meta is not None:
                for tier_blocks in self._added.values():
                    if block_id in tier_blocks:
                        tier_blocks.remove(block_id)
                self._added.setdefault(meta.tier_alias, []).append(block_id)
            elif event in ("removed", "evicted"):
                for tier_blocks in self._added.values():
                    if block_id in tier_blocks:
                        tier_blocks.remove(block_id)
                self._removed.append(block_id)

    def generate_report(self) -> Dict:
        with self._lock:
            report = {"added": {k: list(v) for k, v in self._added.items()
                                if v},
                      "removed": list(self._removed)}
            self._added.clear()
            self._removed.clear()
            return report

    def merge_back(self, report: Dict) -> None:
        """Heartbeat failed; keep the delta for the next attempt."""
        with self._lock:
            for tier, blocks in report["added"].items():
                self._added.setdefault(tier, []).extend(blocks)
            self._removed.extend(report["removed"])


class InProcessBlockMasterClient:
    """Direct-call adapter over a BlockMaster (thread-level 'RPC')."""

    def __init__(self, block_master) -> None:
        self._m = block_master

    def get_worker_id(self, address: WorkerNetAddress) -> int:
        return self._m.get_worker_id(address)

    def register(self, worker_id: int, capacity: Dict[str, int],
                 used: Dict[str, int], blocks: Dict[str, List[int]],
                 address: Optional[WorkerNetAddress] = None) -> None:
        self._m.worker_register(worker_id, capacity, used, blocks, address)

    def heartbeat(self, worker_id: int, used: Dict[str, int],
                  added: Dict[str, List[int]], removed: List[int],
                  metrics_snapshot: Optional[Dict[str, float]] = None) -> dict:
        return self._m.worker_heartbeat(worker_id, used, added, removed,
                                        metrics_snapshot)

    def commit_block(self, worker_id: int, used_on_tier: int, tier: str,
                     block_id: int, length: int) -> None:
        self._m.commit_block(worker_id, used_on_tier, tier, block_id, length)


class BlockMasterSync(HeartbeatExecutor):
    """Register + periodic heartbeat + command handling
    (reference: ``BlockMasterSync.java:96-189``)."""

    def __init__(self, store: TieredBlockStore, address: WorkerNetAddress,
                 master_client) -> None:
        self._store = store
        self._address = address
        self._client = master_client
        self._reporter = BlockHeartbeatReporter()
        store.add_listener(
            lambda ev, bid: self._reporter.on_event(store, ev, bid))
        self.worker_id: Optional[int] = None

    def register_with_master(self) -> int:
        self.worker_id = self._client.get_worker_id(self._address)
        # Discard the pending delta BEFORE snapshotting: an event that lands
        # after the clear is preserved and re-sent on the next heartbeat
        # (idempotent at the master), whereas clearing after the snapshot
        # would silently drop any commit/evict that raced the registration.
        self._reporter.generate_report()
        cap, used = self._store.store_meta()
        self._client.register(self.worker_id, cap, used,
                              self._store.block_report(), self._address)
        return self.worker_id

    def heartbeat(self) -> None:
        if self.worker_id is None:
            self.register_with_master()
            return
        report = self._reporter.generate_report()
        _, used = self._store.store_meta()
        try:
            resp = self._client.heartbeat(self.worker_id, used,
                                          report["added"], report["removed"])
        except Exception:  # noqa: BLE001 - keep delta, retry next tick
            self._reporter.merge_back(report)
            raise
        self._handle_command(resp)

    def _handle_command(self, resp: dict) -> None:
        cmd, data = resp.get("command"), resp.get("data", [])
        if cmd == WorkerCommand.REGISTER:
            # master lost us (failover / timeout): full re-register
            self.register_with_master()
        elif cmd in (WorkerCommand.FREE, WorkerCommand.DELETE):
            for bid in data:
                try:
                    self._store.remove_block(bid, timeout=0.5)
                except Exception:  # noqa: BLE001
                    LOG.debug("free of block %s deferred (busy)", bid)


class PinListSync(HeartbeatExecutor):
    """Pulls the master's pinned-file set and maps it onto local block ids
    (reference: ``PinListSync.java``)."""

    def __init__(self, store: TieredBlockStore, fs_master_client) -> None:
        self._store = store
        self._client = fs_master_client

    def heartbeat(self) -> None:
        pinned_files: Set[int] = set(self._client.get_pinned_file_ids())
        pinned_blocks = {
            bid for tier_blocks in self._store.block_report().values()
            for bid in tier_blocks
            if id_utils.file_id_for_block(bid) in pinned_files}
        # replaces only the master-driven set; commit-time pins
        # (commit_block(pinned=True)) live in store.pinned_blocks and are
        # not clobbered by a sync computed from an older block report
        self._store.master_pinned_blocks = pinned_blocks


class StorageChecker(HeartbeatExecutor):
    """Detects failed storage dirs (unwritable paths) and drops their blocks
    so the next heartbeat/registration reflects reality
    (reference: ``DefaultBlockWorker.StorageChecker:624``)."""

    def __init__(self, store: TieredBlockStore,
                 on_dir_lost=None) -> None:
        self._store = store
        self._on_dir_lost = on_dir_lost

    def heartbeat(self) -> None:
        for tier in self._store.meta.tiers:
            for d in list(tier.dirs):
                if not os.path.isdir(d.path) or not os.access(d.path, os.W_OK):
                    LOG.error("storage dir %s failed; dropping %d blocks",
                              d.path, len(d.block_ids()))
                    for bid in d.block_ids():
                        try:
                            self._store.remove_block(bid, timeout=0.1)
                        except Exception:  # noqa: BLE001
                            # busy/gone: still drop the record AND tell the
                            # master, or it keeps routing clients here
                            LOG.debug("remove_block(%s) on failed dir "
                                      "errored; dropping record", bid,
                                      exc_info=True)
                            meta = d.remove_block(bid)
                            if meta is not None:
                                d.release(meta.length)
                            self._store._emit("removed", bid)
                    tier.dirs.remove(d)
                    if self._on_dir_lost is not None:
                        self._on_dir_lost(d)
