"""TieredBlockStore: the worker's cache of block files across storage tiers.

Re-design of ``core/server/worker/.../block/TieredBlockStore.java:85`` (lock
hierarchy documented ``:58-83``): temp-block create/commit/abort lifecycle,
eviction-on-allocation in annotator order with cascade demotion to the next
tier, move/free, and lock-guarded reads.

Storage layout: one file per block, ``<dir>/<block_id>``; temp blocks at
``<dir>/.tmp/<session>_<block_id>``. The MEM tier sits on ``/dev/shm`` so a
same-host client can ``mmap`` the committed file and hand the pages to XLA
without a copy (the short-circuit read path; reference:
``OpenLocalBlock`` leases in ``block_worker.proto:18-21``).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

from alluxio_tpu.metrics import metrics
from alluxio_tpu.worker.allocator import ANY_TIER, Allocator
from alluxio_tpu.worker.annotator import BlockAnnotator
from alluxio_tpu.worker.lock_manager import BlockLock, BlockLockManager
from alluxio_tpu.worker.meta import (
    BlockMeta, BlockMetadataManager, StorageDir, TempBlockMeta,
)
from alluxio_tpu.utils.exceptions import (
    AlreadyExistsError, BlockDoesNotExistError, InvalidArgumentError,
    WorkerOutOfSpaceError, best_effort,
)

LOG = logging.getLogger(__name__)


class BlockWriter:
    """Appender for a temp block file."""

    def __init__(self, temp: TempBlockMeta, store: "TieredBlockStore") -> None:
        self._temp = temp
        self._store = store
        self._f = open(temp.path, "ab")
        self.written = os.path.getsize(temp.path)

    def append(self, data: bytes) -> int:
        needed = self.written + len(data) - self._temp.bytes_reserved
        if needed > 0:
            self._store.request_space(self._temp.session_id,
                                      self._temp.block_id, needed)
        self._f.write(data)
        self.written += len(data)
        return len(data)

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class BlockReader:
    """Positioned reader over a committed block file, holding a read lock."""

    def __init__(self, meta: BlockMeta, lock: BlockLock) -> None:
        self._meta = meta
        self._lock = lock
        self._fd = os.open(meta.path, os.O_RDONLY)
        self.length = meta.length
        self.path = meta.path
        self.tier_alias = meta.tier_alias

    def read(self, offset: int, length: int) -> bytes:
        return os.pread(self._fd, length, offset)

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass
        self._lock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class CacheFill:
    """Incremental read-through fill: a temp block the UFS fetch
    pipeline appends to as stripes land (in frontier order), committed
    when the block completes. Best-effort like every cache fill: any
    failure aborts the temp block and reports False — the fetch keeps
    serving waiters from its own buffer."""

    def __init__(self, store: "TieredBlockStore", session_id: int,
                 block_id: int, writer: BlockWriter) -> None:
        self._store = store
        self._session = session_id
        self._block_id = block_id
        self._writer: Optional[BlockWriter] = writer

    def append(self, data: bytes) -> bool:
        if self._writer is None:
            return False
        try:
            self._writer.append(data)
            return True
        except Exception:  # noqa: BLE001 - cache fill is best-effort
            LOG.debug("cache-fill append for block %s failed",
                      self._block_id, exc_info=True)
            self.abort()
            return False

    def commit(self) -> bool:
        if self._writer is None:
            return False
        try:
            self._writer.close()
            self._writer = None
            self._store.commit_block(self._session, self._block_id)
            return True
        except Exception:  # noqa: BLE001 - cache fill is best-effort
            LOG.debug("cache-fill commit for block %s failed",
                      self._block_id, exc_info=True)
            self.abort()
            return False

    def abort(self) -> None:
        w, self._writer = self._writer, None
        if w is not None:
            best_effort("cache-fill writer close", w.close)
        best_effort("cache-fill abort", self._store.abort_block,
                    self._session, self._block_id)


class TieredBlockStore:
    def __init__(self, meta: BlockMetadataManager, allocator: Allocator,
                 annotator: BlockAnnotator,
                 eviction_retries: int = 3) -> None:
        self.meta = meta
        self._allocator = allocator
        self.annotator = annotator
        self._locks = BlockLockManager()
        self._eviction_retries = eviction_retries
        #: commit-time pins (commit_block(pinned=True))
        self.pinned_blocks: Set[int] = set()
        #: master-driven pins, wholesale-replaced by PinListSync each tick
        self.master_pinned_blocks: Set[int] = set()
        #: prefetch-agent pins: block_id -> expiry (monotonic). Soon-
        #: needed blocks the clairvoyant scheduler placed ahead of the
        #: consumer; eviction must not undo a placement before its
        #: consume (prefetch/agent.py). TTL-bounded, NOT session-bound:
        #: a SIGKILLed client can never unpin, and a permanent pin
        #: would make the block unevictable forever — expiry is the
        #: worker-side reclamation path.
        self.prefetch_pinned_blocks: Dict[int, float] = {}
        #: SHM-lease pins: block_id -> expiry (monotonic). A same-host
        #: client holding an shm lease (shm/) has the MEM-tier file
        #: mmapped; eviction must not demote/unlink it mid-read. Same
        #: crash-safety shape as prefetch pins — TTL-bounded, NOT
        #: session-bound: a SIGKILLed client's pins self-expire one
        #: lease TTL later, no death detection needed.
        self.shm_leased_blocks: Dict[int, float] = {}
        #: serialized allocation/eviction decisions (metadata lock; IO and
        #: reads proceed outside it — mirroring the reference's hierarchy)
        self._alloc_lock = threading.RLock()
        self._listeners: List[Callable[[str, int], None]] = []
        self._m = metrics()

    # -- observability ------------------------------------------------------
    def add_listener(self, fn: Callable[[str, int], None]) -> None:
        """fn(event, block_id); events: committed/removed/moved/evicted."""
        self._listeners.append(fn)

    def _emit(self, event: str, block_id: int) -> None:
        for fn in self._listeners:
            best_effort("block-event listener", fn, event, block_id)

    # -- write path ---------------------------------------------------------
    def create_block(self, session_id: int, block_id: int, *,
                     initial_bytes: int, tier_alias: str = ANY_TIER
                     ) -> TempBlockMeta:
        """Allocate a temp block, evicting on demand
        (reference: ``createBlock`` + ``freeSpace``, TieredBlockStore.java:80-82)."""
        with self._alloc_lock:
            if self.meta.get_block(block_id) is not None or \
                    self.meta.get_temp(block_id) is not None:
                raise AlreadyExistsError(f"block {block_id} already exists")
            d = self._allocate_with_eviction(initial_bytes, tier_alias)
            temp = TempBlockMeta(block_id=block_id, session_id=session_id,
                                 dir=d, bytes_reserved=initial_bytes)
            d.reserve(initial_bytes)
            d.add_temp(temp)
        # touch the file outside the metadata lock
        open(temp.path, "wb").close()
        return temp

    def get_temp_writer(self, session_id: int, block_id: int) -> BlockWriter:
        temp = self.meta.get_temp(block_id)
        if temp is None or temp.session_id != session_id:
            raise BlockDoesNotExistError(
                f"no temp block {block_id} for session {session_id}")
        return BlockWriter(temp, self)

    def request_space(self, session_id: int, block_id: int,
                      additional: int) -> None:
        with self._alloc_lock:
            temp = self.meta.get_temp(block_id)
            if temp is None or temp.session_id != session_id:
                raise BlockDoesNotExistError(f"no temp block {block_id}")
            if not temp.dir.reserve(additional):
                freed = self._free_space_in_dir(temp.dir, additional)
                if not temp.dir.reserve(additional):
                    raise WorkerOutOfSpaceError(
                        f"cannot reserve {additional}B in "
                        f"{temp.dir.tier.alias}:{temp.dir.index} "
                        f"(freed {freed}B)")
            temp.bytes_reserved += additional

    def commit_block(self, session_id: int, block_id: int,
                     pinned: bool = False, emit: bool = True) -> BlockMeta:
        """Temp -> committed: rename into place, fix accounting, annotate.

        ``emit=False``: suppress the "committed" listener event; the caller
        emits it after the master acknowledges the commit. Otherwise the
        heartbeat delta can reach the master BEFORE the synchronous
        commit RPC, and the master frees the "orphan" (reference split:
        onCommitBlockToLocal vs onCommitBlockToMaster)."""
        with self._alloc_lock:
            temp = self.meta.get_temp(block_id)
            if temp is None:
                raise BlockDoesNotExistError(f"no temp block {block_id}")
            if temp.session_id != session_id:
                raise InvalidArgumentError(
                    f"temp block {block_id} belongs to another session")
            length = os.path.getsize(temp.path)
            final = BlockMeta(block_id=block_id, length=length, dir=temp.dir)
            os.replace(temp.path, final.path)
            temp.dir.remove_temp(block_id)
            # reconcile reservation with the actual on-disk size: release
            # over-reservation; for short-circuit writes that overshot the
            # reservation, force-account the shortfall (the bytes are already
            # on disk) and restore headroom by freeing
            delta = temp.bytes_reserved - length
            if delta > 0:
                temp.dir.release(delta)
            elif delta < 0:
                if not temp.dir.reserve(-delta):
                    temp.dir.force_reserve(-delta)
                    overshoot = temp.dir.used_bytes - temp.dir.capacity_bytes
                    if overshoot > 0:
                        self._free_space_in_dir(temp.dir, overshoot)
            temp.dir.add_block(final)
            if pinned:
                self.pinned_blocks.add(block_id)
        self.annotator.on_commit(block_id)
        self._m.counter("Worker.BlocksCommitted").inc()
        if emit:
            self._emit("committed", block_id)
        return final

    def abort_block(self, session_id: int, block_id: int) -> None:
        with self._alloc_lock:
            temp = self.meta.get_temp(block_id)
            if temp is None:
                raise BlockDoesNotExistError(f"no temp block {block_id}")
            if temp.session_id != session_id:
                raise InvalidArgumentError("wrong session")
            temp.dir.remove_temp(block_id)
            temp.dir.release(temp.bytes_reserved)
        if os.path.exists(temp.path):
            os.remove(temp.path)

    def cleanup_session(self, session_id: int) -> None:
        """Abort all of a dead session's temp blocks
        (reference: ``SessionCleaner``)."""
        for tier in self.meta.tiers:
            for d in tier.dirs:
                for temp in d.temp_blocks_of_session(session_id):
                    best_effort("session temp-block abort",
                                self.abort_block, session_id,
                                temp.block_id)

    # -- read path ----------------------------------------------------------
    def get_reader(self, block_id: int) -> BlockReader:
        from alluxio_tpu.utils.tracing import current_span

        sp = current_span()
        if sp is None:
            lock = self._locks.lock_read(block_id)
        else:
            import time as _time

            t0 = _time.perf_counter()
            lock = self._locks.lock_read(block_id)
            sp.phase("lock_wait", (_time.perf_counter() - t0) * 1000.0)
        try:
            meta = self.meta.get_block(block_id)
            if meta is None:
                raise BlockDoesNotExistError(f"block {block_id} not cached")
            reader = BlockReader(meta, lock)
        except BaseException:
            lock.close()  # never leak the read lock (unremovable block)
            raise
        self.annotator.on_access(block_id)
        self._m.counter("Worker.BlocksAccessed").inc()
        # per-tier access split: the input doctor's worker-side view of
        # which tier actually serves reads (MEM on /dev/shm ~= host DRAM)
        self._m.counter(f"Worker.BlocksAccessed.{meta.tier_alias}").inc()
        return reader

    def pin_block(self, block_id: int) -> Optional[BlockLock]:
        """Shared-lock lease without opening the file — backs the
        short-circuit read lease so eviction cannot unlink a file a client
        is mmapping (reference: OpenLocalBlock holds a block lock for the
        stream's lifetime)."""
        lock = self._locks.lock_read(block_id)
        if self.meta.get_block(block_id) is None:
            lock.close()
            raise BlockDoesNotExistError(f"block {block_id} not cached")
        self.annotator.on_access(block_id)
        return lock

    def pin_prefetch(self, block_id: int, ttl_s: float = 600.0) -> bool:
        """Shield a committed block from eviction until the prefetch
        consumer reads it. Unlike :meth:`pin_block` this holds no lock
        object a remote caller would have to keep alive — it is an
        expiring entry the evictor respects, dropped by
        :meth:`unpin_prefetch`, block removal, or TTL expiry (the
        backstop for clients that die without unpinning)."""
        import time

        with self._alloc_lock:
            if self.meta.get_block(block_id) is None:
                return False
            self.prefetch_pinned_blocks[block_id] = \
                time.monotonic() + ttl_s
        self.annotator.on_access(block_id)
        return True

    def unpin_prefetch(self, block_id: int) -> None:
        with self._alloc_lock:
            self.prefetch_pinned_blocks.pop(block_id, None)

    def pin_shm(self, block_id: int, ttl_s: float) -> bool:
        """Shield a committed block from eviction while a same-host
        client has its segment mmapped (shm lease). Renewal extends the
        expiry; expiry never moves backwards, so a stale renewal racing
        a fresh grant cannot shorten the pin. False when the block is
        gone (the lease grant then fails)."""
        import time

        with self._alloc_lock:
            if self.meta.get_block(block_id) is None:
                return False
            expiry = time.monotonic() + ttl_s
            prev = self.shm_leased_blocks.get(block_id, 0.0)
            self.shm_leased_blocks[block_id] = max(prev, expiry)
        self.annotator.on_access(block_id)
        return True

    def unpin_shm(self, block_id: int) -> None:
        with self._alloc_lock:
            self.shm_leased_blocks.pop(block_id, None)

    def get_block_meta(self, block_id: int) -> Optional[BlockMeta]:
        return self.meta.get_block(block_id)

    def has_block(self, block_id: int) -> bool:
        return self.meta.get_block(block_id) is not None

    def access_block(self, block_id: int) -> None:
        self.annotator.on_access(block_id)

    def open_cache_fill(self, block_id: int, length: int,
                        tier_alias: str = "") -> Optional[CacheFill]:
        """Start an incremental read-through fill for a cold block the
        fetch pipeline is streaming (reserves the full length up front
        so per-stripe appends never allocate). None when the block
        already exists, is being filled, or space cannot be found —
        the fetch then serves without caching."""
        from alluxio_tpu.utils import ids as id_utils

        session = id_utils.create_session_id()
        try:
            self.create_block(session, block_id,
                              initial_bytes=max(1, length),
                              tier_alias=tier_alias)
            return CacheFill(self, session, block_id,
                             self.get_temp_writer(session, block_id))
        except AlreadyExistsError:
            return None
        except Exception:  # noqa: BLE001 - cache fill is best-effort
            LOG.debug("cache fill for block %s failed to start",
                      block_id, exc_info=True)
            best_effort("cache-fill abort", self.abort_block,
                        session, block_id)
            return None

    # -- removal / movement -------------------------------------------------
    def remove_block(self, block_id: int, timeout: Optional[float] = 5.0) -> None:
        lock = self._locks.lock_write(block_id, timeout)
        if lock is None:
            raise InvalidArgumentError(f"block {block_id} is busy")
        try:
            with self._alloc_lock:
                meta = self.meta.get_block(block_id)
                if meta is None:
                    raise BlockDoesNotExistError(f"block {block_id} not cached")
                meta.dir.remove_block(block_id)
                meta.dir.release(meta.length)
                self.pinned_blocks.discard(block_id)
                self.master_pinned_blocks.discard(block_id)
                self.prefetch_pinned_blocks.pop(block_id, None)
                self.shm_leased_blocks.pop(block_id, None)
            if os.path.exists(meta.path):
                os.remove(meta.path)
        finally:
            lock.close()
        self.annotator.on_remove(block_id)
        self._emit("removed", block_id)

    def move_block(self, block_id: int, dst_tier_alias: str) -> BlockMeta:
        """Move a committed block to another tier (promote/demote)."""
        lock = self._locks.lock_write(block_id, 5.0)
        if lock is None:
            raise InvalidArgumentError(f"block {block_id} is busy")
        try:
            with self._alloc_lock:
                meta = self.meta.get_block(block_id)
                if meta is None:
                    raise BlockDoesNotExistError(f"block {block_id} not cached")
                if meta.tier_alias == dst_tier_alias:
                    return meta
                dst = self._allocate_with_eviction(meta.length, dst_tier_alias)
                new_meta = BlockMeta(block_id=block_id, length=meta.length,
                                     dir=dst)
                dst.reserve(meta.length)
                os.replace(meta.path, new_meta.path)
                meta.dir.remove_block(block_id)
                meta.dir.release(meta.length)
                dst.add_block(new_meta)
            self._emit("moved", block_id)
            return new_meta
        finally:
            lock.close()

    # -- eviction -----------------------------------------------------------
    def _allocate_with_eviction(self, size: int, tier_alias: str) -> StorageDir:
        d = self._allocator.allocate(size, tier_alias)
        for _ in range(self._eviction_retries):
            if d is not None:
                return d
            freed = self._free_space_on_tier(size, tier_alias)
            d = self._allocator.allocate(size, tier_alias)
            if freed == 0 and d is None:
                break
        if d is None:
            raise WorkerOutOfSpaceError(
                f"cannot allocate {size}B on tier {tier_alias or 'ANY'}")
        return d

    def _free_space_on_tier(self, size: int, tier_alias: str) -> int:
        tiers = self.meta.tiers if tier_alias == ANY_TIER else \
            [self.meta.get_tier(tier_alias)]
        freed = 0
        for tier in tiers:
            for d in tier.dirs:
                freed += self._free_space_in_dir(d, size)
                if freed >= size:
                    return freed
        return freed

    def _free_space_in_dir(self, d: StorageDir, need: int) -> int:
        """Evict coldest blocks from one dir; demote to the tier below when
        it has room, else drop (re-fetchable cache by design)."""
        import time

        victims = self.annotator.sorted_blocks(d.block_ids())
        freed = 0
        below = self.meta.tier_below(d.tier.alias)
        now = time.monotonic()
        for bid in victims:
            if freed >= need:
                break
            if bid in self.pinned_blocks or \
                    bid in self.master_pinned_blocks:
                continue
            expiry = self.prefetch_pinned_blocks.get(bid)
            if expiry is not None:
                if expiry > now:
                    continue
                del self.prefetch_pinned_blocks[bid]  # expired: reclaim
            shm_expiry = self.shm_leased_blocks.get(bid)
            if shm_expiry is not None:
                if shm_expiry > now:
                    continue
                del self.shm_leased_blocks[bid]  # expired: reclaim
            lock = self._locks.try_lock_write(bid)
            if lock is None:
                continue  # in use by a reader; skip (reference retries)
            try:
                meta = d.get_block(bid)
                if meta is None:
                    continue
                demoted = False
                if below is not None:
                    for dst in below.dirs:
                        if dst.available_bytes >= meta.length and \
                                dst.reserve(meta.length):
                            new_meta = BlockMeta(block_id=bid,
                                                 length=meta.length, dir=dst)
                            os.replace(meta.path, new_meta.path)
                            dst.add_block(new_meta)
                            demoted = True
                            break
                if not demoted and os.path.exists(meta.path):
                    os.remove(meta.path)
                d.remove_block(bid)
                d.release(meta.length)
                freed += meta.length
                if not demoted:
                    self.annotator.on_remove(bid)
                    self._emit("evicted", bid)
                    self._m.counter("Worker.BlocksEvicted").inc()
                else:
                    self._emit("moved", bid)
            finally:
                lock.close()
        return freed

    def free_space(self, tier_alias: str, bytes_to_free: int) -> int:
        """Explicit free (Free command from master / watermark restore)."""
        with self._alloc_lock:
            return self._free_space_on_tier(bytes_to_free, tier_alias)

    # -- reporting ----------------------------------------------------------
    def block_report(self) -> Dict[str, List[int]]:
        return self.meta.blocks_on_tiers()

    def store_meta(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        return self.meta.capacity_on_tiers(), self.meta.used_on_tiers()
