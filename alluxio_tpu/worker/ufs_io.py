"""Worker-side UFS block IO: cold reads with concurrent caching.

Re-design of ``core/server/worker/.../block/{UnderFileSystemBlockStore.java,
UnderFileSystemBlockReader.java:50}`` + the async cache manager
(``worker/block/AsyncCacheRequestManager.java:52,88``): when a client reads
a block that is not cached, the worker streams it from the UFS at the block
offset and *concurrently* writes it into the local top tier, so the next
reader is warm. ``AsyncCacheManager`` executes client-issued cache requests
off the read path (passive caching).
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from alluxio_tpu.underfs.base import UnderFileSystem
from alluxio_tpu.utils import ids as id_utils
from alluxio_tpu.utils.exceptions import AlreadyExistsError, best_effort
from alluxio_tpu.worker.tiered_store import TieredBlockStore

LOG = logging.getLogger(__name__)

_CHUNK = 4 << 20


@dataclass
class UfsBlockDescriptor:
    """Where a block lives in its UFS file."""

    block_id: int
    ufs_path: str
    offset: int
    length: int
    mount_id: int = 0


class UfsBlockReader:
    """Single-range read-through: serve from UFS while caching into the
    local store. This is the *unstriped* path — one blocking connection,
    first byte after the last — kept as the striped pipeline's fallback
    and as the bench baseline; the hot cold-read path is
    ``ufs_fetch.UfsBlockFetcher``."""

    def __init__(self, store: TieredBlockStore) -> None:
        self._store = store

    def read_block(self, ufs: UnderFileSystem, desc: UfsBlockDescriptor, *,
                   cache: bool = True, tier_alias: str = "") -> bytes:
        """Fetch the whole block (the TPU read path wants whole pages into
        a staging buffer, not tiny chunks)."""
        from alluxio_tpu.metrics import metrics
        from alluxio_tpu.utils.tracing import tracer

        with tracer().span("atpu.worker.ufs_read",
                           block_id=desc.block_id, bytes=desc.length):
            data = ufs.read_range(desc.ufs_path, desc.offset, desc.length)
        m = metrics()
        m.counter("Worker.UfsBlocksRead").inc()
        m.counter("Worker.UfsBytesRead").inc(len(data))
        if cache:
            self.cache_block(desc.block_id, data, tier_alias)
        return data

    def cache_block(self, block_id: int, data: bytes,
                    tier_alias: str = "") -> bool:
        session = id_utils.create_session_id()
        try:
            self._store.create_block(session, block_id,
                                     initial_bytes=len(data),
                                     tier_alias=tier_alias)
        except AlreadyExistsError:
            return False
        except Exception:  # noqa: BLE001 - cache fill is best-effort
            LOG.debug("cache fill for block %s failed", block_id, exc_info=True)
            return False
        try:
            with self._store.get_temp_writer(session, block_id) as w:
                w.append(data)
            self._store.commit_block(session, block_id)
            return True
        except Exception:  # noqa: BLE001
            LOG.debug("cache commit for block %s failed", block_id,
                      exc_info=True)
            best_effort("cache-fill abort", self._store.abort_block,
                        session, block_id)
            return False


class AsyncCacheManager:
    """Executes passive-cache requests off the read path
    (reference: ``AsyncCacheRequestManager.java:88``). A client that read a
    block remotely (or straight from UFS) asks its local worker to cache it
    in the background.

    The queue is bounded (``atpu.worker.async.cache.queue.max``): a burst
    of cache requests beyond it is *rejected* (counted in
    ``Worker.AsyncCacheRejected``) instead of growing the backlog without
    limit — passive caching is advisory, the client already has the bytes.
    When a ``UfsBlockFetcher`` is wired in, cache fills ride the same
    coalescing registry as foreground reads, so a background fill never
    duplicates an in-flight foreground fetch of the same block.

    With worker QoS on (``prioritize=True``) the queue drains in
    priority order — client-issued ASYNC_FILL requests before the
    prefetch agent's speculative PREFETCH loads — and each request's
    class and tenant ride into the coalescing fetch, so the per-mount
    stripe executors see the true originator.  Off, the queue is exact
    FIFO (today's behavior)."""

    def __init__(self, store: TieredBlockStore,
                 ufs_resolver: Callable[[int], UnderFileSystem],
                 num_threads: int = 1, queue_max: int = 512,
                 fetcher=None, prioritize: bool = False) -> None:
        from alluxio_tpu.qos import PriorityTaskQueue

        self._store = store
        self._reader = UfsBlockReader(store)
        self._ufs_resolver = ufs_resolver
        self._fetcher = fetcher  # Optional[ufs_fetch.UfsBlockFetcher]
        self._queue = PriorityTaskQueue(max(1, queue_max),
                                        prioritize=prioritize)
        self._prioritize = prioritize
        self._inflight: Dict[int, bool] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._threads = [threading.Thread(target=self._run, daemon=True,
                                          name=f"async-cache-{i}")
                         for i in range(max(1, num_threads))]
        for t in self._threads:
            t.start()

    def submit(self, desc: UfsBlockDescriptor, *,
               priority: Optional[int] = None, tenant: str = "") -> bool:
        from alluxio_tpu.metrics import metrics
        from alluxio_tpu.qos import ASYNC_FILL, PRIORITY_NAMES

        if priority is None:
            priority = ASYNC_FILL
        with self._lock:
            if self._closed or desc.block_id in self._inflight or \
                    self._store.has_block(desc.block_id):
                return False
            if self._fetcher is not None and \
                    self._fetcher.caching_in_flight(desc.block_id):
                # a foreground read-through is already CACHING this
                # block (an in-flight cache=False fetch is not enough
                # to stand down — joining it upgrades it instead)
                return False
            self._inflight[desc.block_id] = True
        try:
            self._queue.put_nowait((desc, priority, tenant), priority)
        except queue.Full:
            with self._lock:
                self._inflight.pop(desc.block_id, None)
            metrics().counter("Worker.AsyncCacheRejected").inc()
            return False
        if self._prioritize:
            metrics().counter(
                "Worker.QosAsyncCache."
                + PRIORITY_NAMES.get(priority, str(priority))).inc()
        return True

    def _run(self) -> None:
        while True:
            try:
                desc, priority, tenant = self._queue.get(timeout=0.2)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if self._closed:
                # shutdown drops the backlog: passive caching is
                # advisory and must not delay worker stop
                self._queue.task_done()
                return
            try:
                if self._store.has_block(desc.block_id):
                    continue  # cached while queued
                ufs = self._ufs_resolver(desc.mount_id)
                if self._fetcher is not None:
                    # coalesces with any concurrent fetch of this block;
                    # joining a cache=False fetch upgrades it, and if
                    # even that was too late, cache from the bytes.
                    # The request's class/tenant ride into the stripe
                    # executor so background fills queue as background
                    data = self._fetcher.fetch(ufs, desc, cache=True,
                                               priority=priority,
                                               tenant=tenant).result()
                    if not self._store.has_block(desc.block_id):
                        self._reader.cache_block(desc.block_id, data)
                else:
                    self._reader.read_block(ufs, desc, cache=True)
            except Exception:  # noqa: BLE001
                LOG.debug("async cache of block %s failed", desc.block_id,
                          exc_info=True)
            finally:
                with self._lock:
                    self._inflight.pop(desc.block_id, None)
                self._queue.task_done()

    def wait_idle(self, timeout_s: float = 10.0) -> bool:
        """Block until the queue drains or the deadline passes; returns
        True if idle."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._queue.all_tasks_done:
                if self._queue.unfinished_tasks == 0:
                    return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        # flag-based shutdown: workers poll the flag between short
        # blocking gets, so no poison pills are needed — pills on a
        # BOUNDED queue either deadlock (queue full) or corrupt the
        # unfinished-task accounting wait_idle() relies on
        with self._lock:
            self._closed = True
