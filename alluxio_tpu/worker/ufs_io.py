"""Worker-side UFS block IO: cold reads with concurrent caching.

Re-design of ``core/server/worker/.../block/{UnderFileSystemBlockStore.java,
UnderFileSystemBlockReader.java:50}`` + the async cache manager
(``worker/block/AsyncCacheRequestManager.java:52,88``): when a client reads
a block that is not cached, the worker streams it from the UFS at the block
offset and *concurrently* writes it into the local top tier, so the next
reader is warm. ``AsyncCacheManager`` executes client-issued cache requests
off the read path (passive caching).
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from alluxio_tpu.underfs.base import UnderFileSystem
from alluxio_tpu.utils import ids as id_utils
from alluxio_tpu.utils.exceptions import AlreadyExistsError
from alluxio_tpu.worker.tiered_store import TieredBlockStore

LOG = logging.getLogger(__name__)

_CHUNK = 4 << 20


@dataclass
class UfsBlockDescriptor:
    """Where a block lives in its UFS file."""

    block_id: int
    ufs_path: str
    offset: int
    length: int
    mount_id: int = 0


class UfsBlockReader:
    """Read-through: serve from UFS while caching into the local store."""

    def __init__(self, store: TieredBlockStore) -> None:
        self._store = store

    def read_block(self, ufs: UnderFileSystem, desc: UfsBlockDescriptor, *,
                   cache: bool = True, tier_alias: str = "") -> bytes:
        """Fetch the whole block (the TPU read path wants whole pages into
        a staging buffer, not tiny chunks)."""
        from alluxio_tpu.metrics import metrics
        from alluxio_tpu.utils.tracing import tracer

        with tracer().span("atpu.worker.ufs_read",
                           block_id=desc.block_id, bytes=desc.length):
            data = ufs.read_range(desc.ufs_path, desc.offset, desc.length)
        m = metrics()
        m.counter("Worker.UfsBlocksRead").inc()
        m.counter("Worker.UfsBytesRead").inc(len(data))
        if cache:
            self.cache_block(desc.block_id, data, tier_alias)
        return data

    def cache_block(self, block_id: int, data: bytes,
                    tier_alias: str = "") -> bool:
        session = id_utils.create_session_id()
        try:
            self._store.create_block(session, block_id,
                                     initial_bytes=len(data),
                                     tier_alias=tier_alias)
        except AlreadyExistsError:
            return False
        except Exception:  # noqa: BLE001 - cache fill is best-effort
            LOG.debug("cache fill for block %s failed", block_id, exc_info=True)
            return False
        try:
            with self._store.get_temp_writer(session, block_id) as w:
                w.append(data)
            self._store.commit_block(session, block_id)
            return True
        except Exception:  # noqa: BLE001
            try:
                self._store.abort_block(session, block_id)
            except Exception:  # noqa: BLE001
                pass
            return False


class AsyncCacheManager:
    """Executes passive-cache requests off the read path
    (reference: ``AsyncCacheRequestManager.java:88``). A client that read a
    block remotely (or straight from UFS) asks its local worker to cache it
    in the background."""

    def __init__(self, store: TieredBlockStore,
                 ufs_resolver: Callable[[int], UnderFileSystem],
                 num_threads: int = 1) -> None:
        self._store = store
        self._reader = UfsBlockReader(store)
        self._ufs_resolver = ufs_resolver
        self._queue: "queue.Queue[Optional[UfsBlockDescriptor]]" = queue.Queue()
        self._inflight: Dict[int, bool] = {}
        self._lock = threading.Lock()
        self._threads = [threading.Thread(target=self._run, daemon=True,
                                          name=f"async-cache-{i}")
                         for i in range(num_threads)]
        for t in self._threads:
            t.start()

    def submit(self, desc: UfsBlockDescriptor) -> bool:
        with self._lock:
            if desc.block_id in self._inflight or \
                    self._store.has_block(desc.block_id):
                return False
            self._inflight[desc.block_id] = True
        self._queue.put(desc)
        return True

    def _run(self) -> None:
        while True:
            desc = self._queue.get()
            if desc is None:
                return
            try:
                ufs = self._ufs_resolver(desc.mount_id)
                self._reader.read_block(ufs, desc, cache=True)
            except Exception:  # noqa: BLE001
                LOG.debug("async cache of block %s failed", desc.block_id,
                          exc_info=True)
            finally:
                with self._lock:
                    self._inflight.pop(desc.block_id, None)
                self._queue.task_done()

    def wait_idle(self, timeout_s: float = 10.0) -> bool:
        """Block until the queue drains or the deadline passes; returns
        True if idle."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._queue.all_tasks_done:
                if self._queue.unfinished_tasks == 0:
                    return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        for _ in self._threads:
            self._queue.put(None)
