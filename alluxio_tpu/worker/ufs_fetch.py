"""Pipelined UFS cold reads: striped parallel fetch, streaming
read-through, and in-flight coalescing.

Replaces the naive cold path (one blocking whole-block ``read_range``
in ``ufs_io.UfsBlockReader.read_block``) with a fetch pipeline:

- **striped parallel fetch** — a block is split into fixed-size stripes
  fetched concurrently over a per-mount bounded executor, so cold-read
  bandwidth is limited by the link, not by one UFS connection (the
  Hoard / hierarchical-HPC-I/O result: object stores serve many modest
  streams far faster than one);
- **streaming read-through** — waiters consume bytes as stripes land in
  ascending offset order, so time-to-first-byte is O(stripe) instead of
  O(block), and the tiered-store temp writer fills in parallel with the
  stream (``TieredBlockStore.open_cache_fill``);
- **in-flight coalescing** — a per-block registry shares one UFS fetch
  among N concurrent cold readers (every host hitting step-0 of an
  epoch together), with late readers attaching to the stripe pipeline
  mid-flight; the async cache manager and the prefetch agent's loads
  dedupe against foreground fetches through the same registry.

A UFS that rejects ranged reads (short reads, errors on sub-block
ranges) demotes the fetch to a single full-range read — and when no
stripe succeeded but the full read did (the rejection signature), the
mount is remembered for ``UNSTRIPED_MOUNT_TTL_S`` so later fetches skip
the doomed striping attempt without demoting the mount forever.

Observability: ``Worker.UfsFetch*`` counters + ``Worker.UfsFetchTtfb``
timer, and an ``atpu.worker.ufs_fetch`` span per fetch that joins the
caller's trace context (so the input doctor can attribute cold-read
stalls to this pipeline).

QoS (``atpu.worker.qos.enabled``): every fetch carries a priority class
(ON_DEMAND > ASYNC_FILL > PREFETCH) and a tenant (principal).  The
per-mount executors drain in priority order — a queued prefetch fetch
is overtaken by an arriving on-demand read (in-flight stripes are never
interrupted), and a queued fetch is PROMOTED the moment an on-demand
reader coalesces onto it — with per-tenant caps on concurrent stripe
tasks so one flooding principal cannot monopolize the mount's
connection budget (``atpu.worker.ufs.fetch.tenant.limit``).  Disabled,
the executors are plain FIFO pools: byte-identical to a build without
QoS.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

from alluxio_tpu.metrics import metrics
from alluxio_tpu.qos import ON_DEMAND, PRIORITY_NAMES, PriorityExecutor
from alluxio_tpu.underfs.base import UnderFileSystem
from alluxio_tpu.utils import tracing as _tracing
from alluxio_tpu.utils.striping import plan_stripes as _plan_stripes
from alluxio_tpu.worker.tiered_store import CacheFill, TieredBlockStore
from alluxio_tpu.worker.ufs_io import UfsBlockDescriptor

LOG = logging.getLogger(__name__)

#: how long a mount that failed a ranged read stays demoted to
#: single-range fetches before striping is retried
UNSTRIPED_MOUNT_TTL_S = 600.0


@dataclass(frozen=True)
class FetchConf:
    """Tuning for the striped fetch pipeline (see
    ``atpu.worker.ufs.fetch.*`` in ``conf/property_key.py``)."""

    #: bytes per stripe; also the time-to-first-byte unit
    stripe_size: int = 4 << 20
    #: stripes in flight per block
    concurrency: int = 4
    #: concurrent UFS reads per mount, across all blocks
    per_mount_limit: int = 16
    #: priority-class scheduling + tenant caps (atpu.worker.qos.enabled)
    qos_enabled: bool = False
    #: concurrent stripe tasks one tenant may occupy per mount (with
    #: QoS on; 0 = unlimited)
    tenant_limit: int = 8

    @classmethod
    def from_conf(cls, conf) -> "FetchConf":
        from alluxio_tpu.conf import Keys

        return cls(
            stripe_size=max(1, conf.get_bytes(
                Keys.WORKER_UFS_FETCH_STRIPE_SIZE)),
            concurrency=max(1, conf.get_int(
                Keys.WORKER_UFS_FETCH_CONCURRENCY)),
            per_mount_limit=max(1, conf.get_int(
                Keys.WORKER_UFS_FETCH_PER_MOUNT_LIMIT)),
            qos_enabled=conf.get_bool(Keys.WORKER_QOS_ENABLED),
            tenant_limit=max(0, conf.get_int(
                Keys.WORKER_UFS_FETCH_TENANT_LIMIT)),
        )


def plan_stripes(length: int, stripe_size: int) -> List[Tuple[int, int]]:
    """(block-relative offset, length) per stripe; never empty — a
    zero-length block still needs one completion event to close the
    pipeline (the shared planner returns [] there)."""
    if length <= 0:
        return [(0, 0)]
    return _plan_stripes(length, stripe_size)


class FetchError(IOError):
    """A cold fetch failed after exhausting the single-range fallback."""


class BlockFetch:
    """One in-flight cold-block fetch shared by any number of waiters.

    Stripe workers call :meth:`_complete_stripe` / :meth:`_stripe_failed`;
    waiters stream with :meth:`iter_range` or block with :meth:`result`.
    All state transitions happen under ``_cond`` and notify all waiters.
    """

    def __init__(self, desc: UfsBlockDescriptor, conf: FetchConf, *,
                 store: Optional[TieredBlockStore] = None,
                 on_done=None) -> None:
        self.desc = desc
        self.conf = conf
        self._store = store
        #: QoS class of the most demanding waiter (coalescing joins by
        #: an on-demand reader lower it and promote the queued tasks)
        self.priority = ON_DEMAND
        self.stripes = plan_stripes(desc.length, conf.stripe_size)
        self.fallback = False
        #: any stripe read succeeded / the fallback read succeeded —
        #: together they distinguish "mount rejects ranged reads"
        #: (fallback ok, zero stripes ok) from a transient error
        self.any_stripe_ok = False
        self.fallback_ok = False
        #: bytes actually served: desc.length unless the UFS object
        #: turned out shorter (legacy single-range semantics: serve and
        #: cache what exists instead of failing every waiter)
        self.served_length = max(0, desc.length)
        #: readers sharing this fetch (1 = the starter); registry-managed
        self.waiters = 1
        self.created_at = time.perf_counter()
        self.first_byte_at: Optional[float] = None
        self._buf = bytearray(max(0, desc.length))
        self._landed = [False] * len(self.stripes)
        self._frontier = 0  # contiguous landed stripes from stripe 0
        self._next = 0      # next stripe index to hand a worker
        self._striping_aborted = False
        self._done = False
        self._error: Optional[BaseException] = None
        self._cond = threading.Condition()
        self._result: Optional[bytes] = None  # shared result() snapshot
        #: newly-contiguous stripe ranges awaiting a cache-fill append,
        #: built in frontier order under ``_cond``, drained in that
        #: order under ``_fill_lock`` OUTSIDE ``_cond`` so disk writes
        #: never stall stripe completions or streaming waiters
        self._fill_pending: List[Tuple[int, int]] = []
        self._fill_lock = threading.Lock()
        #: attached by the fetcher (before stripe workers start) when
        #: this fetch should read-through cache
        self._cache_fill: Optional[CacheFill] = None
        #: a caching reader joined too late to attach a fill; finalize
        #: then fills from the completed buffer instead
        self._cache_wanted = False
        self._cache_tier_alias = ""
        self._on_done = on_done
        self._span = self._open_span()
        #: phase accumulators (only written when the fetch is traced):
        #: UFS read time summed across stripe workers, cache-fill write
        #: time, and when the first stripe task actually started — the
        #: created->first-claim gap is the executor queue wait
        self._ufs_ms = 0.0
        self._fill_ms = 0.0
        self._first_claim_at: Optional[float] = None

    # -- tracing ------------------------------------------------------------
    def _open_span(self):
        """Manually-managed span: the fetch starts on the caller's thread
        (inheriting its trace context) but finishes on whichever stripe
        worker lands last, so the context-manager form cannot be used."""
        t = _tracing.tracer()
        if not t.enabled:
            return None
        ctx = _tracing.current_trace_context()
        span = _tracing.Span(
            "atpu.worker.ufs_fetch", _tracing.new_span_id(),
            ctx.span_id if ctx else None,
            ctx.trace_id if ctx else _tracing.new_trace_id(),
            sampled=ctx.sampled if ctx else t._sample())
        span.tags = {"block_id": str(self.desc.block_id),
                     "bytes": str(self.desc.length),
                     "stripes": str(len(self.stripes))}
        return span

    def _close_span(self) -> None:
        if self._span is None:
            return
        if self._first_claim_at is not None:
            self._span.phase(
                "queue_wait",
                (self._first_claim_at - self.created_at) * 1000.0)
        if self._ufs_ms > 0.0:
            self._span.phase("ufs_fetch", self._ufs_ms)
        if self._fill_ms > 0.0:
            self._span.phase("cache_fill", self._fill_ms)
        self._span.duration_ms = \
            (time.perf_counter() - self.created_at) * 1000.0
        self._span.tags["fallback"] = str(self.fallback)
        self._span.tags["waiters"] = str(self.waiters)
        if self._error is not None:
            self._span.error = \
                f"{type(self._error).__name__}: {self._error}"
        if self._span.sampled:
            _tracing.tracer().record(self._span)

    # -- stripe-worker side -------------------------------------------------
    def _claim_stripe(self) -> Optional[int]:
        with self._cond:
            if self._span is not None and self._first_claim_at is None:
                self._first_claim_at = time.perf_counter()
            if self._striping_aborted or self._error is not None:
                return None
            if self._next >= len(self.stripes):
                return None
            i = self._next
            self._next += 1
            return i

    def _note_ufs_ms(self, elapsed_ms: float) -> None:
        """Accumulate one stripe's UFS read time (workers run
        concurrently, so the sum can exceed the span's wall — the
        critical-path analyzer scales phases into self-time)."""
        with self._cond:
            self._ufs_ms += elapsed_ms

    def _complete_stripe(self, i: int, data: bytes) -> None:
        off, ln = self.stripes[i]
        m = metrics()
        with self._cond:
            if self._landed[i]:
                # raced with a full-range fallback fill: the buffer is
                # already published to waiters — a straggler write here
                # (object replaced mid-fetch -> different bytes) would
                # tear it, so landed stripes are never rewritten
                return
            self._buf[off:off + ln] = data
            self.any_stripe_ok = True
            self._landed[i] = True
            if self.first_byte_at is None and i == 0:
                self.first_byte_at = time.perf_counter()
                m.timer("Worker.UfsFetchTtfb").update(
                    self.first_byte_at - self.created_at)
            finished = self._advance_frontier_locked()
            self._cond.notify_all()
        self._drain_fill()
        if finished:
            self._finalize_success()

    def _advance_frontier_locked(self) -> bool:
        """Advance the contiguous frontier, queueing newly-contiguous
        stripes for the cache fill. Runs under ``_cond``, so the queue
        is strictly in frontier order; the actual (disk-touching)
        appends happen in :meth:`_drain_fill` outside the lock."""
        n = len(self.stripes)
        while self._frontier < n and self._landed[self._frontier]:
            off, ln = self.stripes[self._frontier]
            if self._cache_fill is not None and ln > 0:
                self._fill_pending.append((off, ln))
            self._frontier += 1
        return self._frontier == n

    def _drain_fill(self, blocking: bool = False) -> None:
        """Append queued frontier ranges to the cache fill. Holding
        ``_fill_lock`` across the whole drain keeps appends in frontier
        order; a stripe worker that finds another thread draining skips
        instead of queueing behind its disk writes (the drainer — or at
        the latest the blocking drain in finalize — picks the ranges
        up). Buffer reads are safe outside ``_cond`` because landed
        stripes are never rewritten."""
        if blocking:
            self._fill_lock.acquire()
        elif not self._fill_lock.acquire(blocking=False):
            return
        try:
            while True:
                with self._cond:
                    fill = self._cache_fill
                    if fill is None or not self._fill_pending:
                        return
                    off, ln = self._fill_pending.pop(0)
                t_fill = time.perf_counter() if self._span is not None \
                    else 0.0
                ok = fill.append(self._buf[off:off + ln])
                if self._span is not None:
                    # under _fill_lock: drains are serialized
                    self._fill_ms += \
                        (time.perf_counter() - t_fill) * 1000.0
                if not ok:
                    with self._cond:  # fill failed: serve-only
                        self._cache_fill = None
                        self._fill_pending.clear()
                    return
        finally:
            self._fill_lock.release()

    def _stripe_failed(self, ufs: UnderFileSystem,
                       exc: BaseException) -> None:
        """First stripe failure demotes the fetch to one full-range read
        (the UFS may reject ranged reads outright); a second failure
        fails the fetch for every waiter."""
        with self._cond:
            if self._done or self._error is not None:
                return
            if self._striping_aborted:  # fallback already running/failed
                return
            self._striping_aborted = True
        LOG.debug("stripe fetch of block %s failed; falling back to "
                  "single-range read", self.desc.block_id, exc_info=True)
        self.fallback = True
        metrics().counter("Worker.UfsFetchFallbacks").inc()
        try:
            t_ufs = time.perf_counter() if self._span is not None else 0.0
            data = ufs.read_range(self.desc.ufs_path, self.desc.offset,
                                  self.desc.length)
            if self._span is not None:
                self._note_ufs_ms((time.perf_counter() - t_ufs) * 1000.0)
        except BaseException as e2:  # noqa: BLE001
            self._fail(e2)
            return
        self.fallback_ok = True
        m = metrics()
        m.counter("Worker.UfsFetchBytes").inc(len(data))
        n = min(len(data), self.desc.length)
        truncated = n < self.desc.length
        late_fill = None
        with self._cond:
            if truncated:
                # the UFS object is shorter than the block metadata
                # says (shrunk/replaced): mirror the legacy path —
                # serve and cache the bytes that exist. The stripe-wise
                # incremental fill would pad zeros, so it is replaced
                # by a buffered fill of the served slice at finalize —
                # but only when someone actually asked for caching
                self.served_length = n
                late_fill, self._cache_fill = self._cache_fill, None
                self._fill_pending.clear()
                self._cache_wanted = self._cache_wanted or \
                    late_fill is not None
            # fill ONLY un-landed stripes: landed ones are published to
            # waiters/cache fill and must never be rewritten (a replaced
            # object mid-fetch would tear mixed-version bytes into them)
            for i, (off, ln) in enumerate(self.stripes):
                if self._landed[i]:
                    continue
                upper = min(off + ln, n)
                if off < upper:
                    self._buf[off:upper] = data[off:upper]
                self._landed[i] = True
            if self.first_byte_at is None:
                self.first_byte_at = time.perf_counter()
                m.timer("Worker.UfsFetchTtfb").update(
                    self.first_byte_at - self.created_at)
            if truncated:
                self._frontier = len(self.stripes)
                finished = True
            else:
                finished = self._advance_frontier_locked()
            self._cond.notify_all()
        if late_fill is not None:
            late_fill.abort()
        self._drain_fill()
        if finished:
            self._finalize_success()

    def _finalize_success(self) -> None:
        # blocking: every queued append must land before the commit
        self._drain_fill(blocking=True)
        with self._cond:
            fill, wanted = self._cache_fill, self._cache_wanted
        if fill is not None:
            t_fill = time.perf_counter() if self._span is not None else 0.0
            fill.commit()
            if self._span is not None:
                self._fill_ms += (time.perf_counter() - t_fill) * 1000.0
        elif wanted and self._store is not None:
            # a caching reader attached after the frontier moved (or
            # the fetch truncated): the block is resident now, fill in
            # one buffered pass of the served slice
            late = self._store.open_cache_fill(self.desc.block_id,
                                               self.served_length,
                                               self._cache_tier_alias)
            if late is not None and \
                    late.append(self._buf[:self.served_length]):
                late.commit()
        # legacy cold-read counters (logical block/bytes served from
        # UFS) so pre-striping dashboards keep reading correctly;
        # Worker.UfsFetchBytes above counts raw UFS traffic instead
        m = metrics()
        m.counter("Worker.UfsBlocksRead").inc()
        m.counter("Worker.UfsBytesRead").inc(self.served_length)
        with self._cond:
            self._done = True
            self._cond.notify_all()
        self._close_span()
        if self._on_done is not None:
            self._on_done(self)

    def _fail(self, exc: BaseException) -> None:
        metrics().counter("Worker.UfsFetchFailures").inc()
        with self._cond:
            fill, self._cache_fill = self._cache_fill, None
            self._fill_pending.clear()
        if fill is not None:
            fill.abort()  # before waking waiters: they check has_block
        with self._cond:
            self._error = exc
            self._cond.notify_all()
        self._close_span()
        if self._on_done is not None:
            self._on_done(self)

    def try_attach_cache_fill(self, store: TieredBlockStore,
                              tier_alias: str = "") -> bool:
        """Attach a read-through cache fill — at start, or mid-flight
        when a caching reader joins a fetch that began with
        ``cache=False``. Appends are frontier-ordered, so attaching is
        only sound while nothing has passed the frontier; after that
        ``_cache_wanted`` makes finalize cache the completed buffer in
        one pass instead."""
        with self._cond:
            if self._cache_fill is not None:
                return True
            if self._done or self._error is not None:
                return False
            if self._frontier:
                self._cache_wanted = True  # finalize fills from buffer
                self._cache_tier_alias = tier_alias
                return False
            fill = store.open_cache_fill(self.desc.block_id,
                                         self.desc.length, tier_alias)
            if fill is None:
                return False
            self._cache_fill = fill
            return True

    # -- waiter side --------------------------------------------------------
    @property
    def done(self) -> bool:
        with self._cond:
            return self._done

    @property
    def error(self) -> Optional[BaseException]:
        with self._cond:
            return self._error

    def wait_done(self, timeout_s: Optional[float] = None) -> bool:
        """Wait for the whole pipeline — including the cache-fill
        commit, which lands just after the final stripe — to finish.
        Streaming waiters can drain every byte slightly before this.
        Returns False on timeout or when the fetch failed (check
        :attr:`error` to distinguish)."""
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        with self._cond:
            while not self._done and self._error is None:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return self._done

    def _stripe_index(self, pos: int) -> int:
        return min(pos // max(1, self.conf.stripe_size),
                   len(self.stripes) - 1)

    def _wait_stripe(self, i: int) -> None:
        with self._cond:
            while not self._landed[i] and self._error is None:
                self._cond.wait()
            if self._error is not None and not self._landed[i]:
                raise FetchError(
                    f"cold fetch of block {self.desc.block_id} failed: "
                    f"{self._error}") from self._error

    def iter_range(self, offset: int = 0, length: int = -1,
                   chunk_size: int = 1 << 20) -> Iterator[bytes]:
        """Yield ``[offset, offset+length)`` in ascending order, each
        chunk as soon as the stripe containing it has landed — this is
        what makes the read-through *streaming*: a waiter gets its first
        chunk after one stripe, not after the whole block."""
        end = self.desc.length if length < 0 else \
            min(self.desc.length, offset + length)
        pos = max(0, offset)
        chunk_size = max(1, chunk_size)
        # one copy per chunk (a bare bytearray slice would be a second);
        # holding the view only pins the bytearray's size, never writes
        view = memoryview(self._buf)
        while pos < end:
            si = self._stripe_index(pos)
            self._wait_stripe(si)
            # a truncated fetch (shrunk UFS object) shortens the stream
            # exactly like the legacy single-range path did
            end = min(end, self.served_length)
            s_off, s_len = self.stripes[si]
            upper = min(end, s_off + s_len)
            while pos < upper:
                n = min(chunk_size, upper - pos)
                yield bytes(view[pos:pos + n])
                pos += n

    def result(self) -> bytes:
        """Block until the whole block is resident; raises on failure.
        All waiters share one immutable snapshot — N coalesced readers
        of a big block must not mean N full-block copies."""
        with self._cond:
            while not self._done and self._error is None:
                self._cond.wait()
            if self._error is not None:
                raise FetchError(
                    f"cold fetch of block {self.desc.block_id} failed: "
                    f"{self._error}") from self._error
            if self._result is None:
                self._result = bytes(
                    memoryview(self._buf)[:self.served_length])
            return self._result


class UfsBlockFetcher:
    """Per-block fetch registry + per-mount bounded stripe executors.

    ``fetch()`` is the single cold-read entry point for foreground
    reads, the async cache manager and the prefetch agent's loads: the
    first caller starts the stripe pipeline, every later caller for the
    same block attaches to it mid-flight (``Worker.UfsFetchCoalesced``).
    """

    def __init__(self, store: TieredBlockStore, conf: FetchConf, *,
                 host: str = "") -> None:
        self._store = store
        self.conf = conf
        #: locality host the fault-injection scope matches against
        self._fault_host = host
        self._lock = threading.Lock()
        self._inflight: Dict[int, BlockFetch] = {}
        self._executors: Dict[int, PriorityExecutor] = {}
        #: mount_id -> retry-after (monotonic): a mount whose UFS failed
        #: a ranged read goes straight to single-range until the TTL
        #: lapses — a permanent demotion would let one transient stripe
        #: error collapse the mount to one connection forever
        self._unstriped_mounts: Dict[int, float] = {}
        self._closed = False
        self._m = metrics()

    # -- registry -----------------------------------------------------------
    def in_flight(self, block_id: int) -> bool:
        with self._lock:
            return block_id in self._inflight

    def caching_in_flight(self, block_id: int) -> bool:
        """True when an in-flight fetch of this block is already
        read-through caching it (a cache=False fetch is NOT enough for
        a passive-cache request to stand down)."""
        with self._lock:
            fetch = self._inflight.get(block_id)
        return fetch is not None and fetch._cache_fill is not None

    def _executor(self, mount_id: int) -> PriorityExecutor:
        with self._lock:
            if self._closed:
                # close() already drained the map; recreating here
                # would leak an executor no shutdown will ever see
                raise FetchError("fetcher is closed")
            ex = self._executors.get(mount_id)
            if ex is None:
                # with QoS off this drains FIFO with no tenant caps —
                # semantically the ThreadPoolExecutor it replaced
                ex = PriorityExecutor(
                    self.conf.per_mount_limit,
                    thread_name_prefix=f"ufs-fetch-m{mount_id}",
                    prioritize=self.conf.qos_enabled,
                    tenant_cap=self.conf.tenant_limit
                    if self.conf.qos_enabled else 0)
                self._executors[mount_id] = ex
            return ex

    #: qos_stats memo TTL: three gauges read these on every metrics
    #: scrape — one executor sweep serves all three, not three
    QOS_STATS_TTL_S = 0.5

    def qos_stats(self) -> Dict[str, float]:
        """Aggregated executor QoS counters (gauges in BlockWorker);
        briefly memoized so one scrape's three gauges share a sweep."""
        now = time.monotonic()
        cached = getattr(self, "_qos_stats_cache", None)
        if cached is not None and now - cached[0] < self.QOS_STATS_TTL_S:
            return cached[1]
        with self._lock:
            exs = list(self._executors.values())
        stats = {
            "deferred": float(sum(e.deferred for e in exs)),
            "promoted": float(sum(e.promoted for e in exs)),
            "queued": float(sum(e.queued() for e in exs)),
        }
        self._qos_stats_cache = (now, stats)
        return stats

    def _mark_unstriped(self, mount_id: int) -> None:
        with self._lock:
            self._unstriped_mounts[mount_id] = \
                time.monotonic() + UNSTRIPED_MOUNT_TTL_S

    def _effective_conf_locked(self, desc: UfsBlockDescriptor) -> FetchConf:
        expiry = self._unstriped_mounts.get(desc.mount_id)
        if expiry is not None and expiry <= time.monotonic():
            del self._unstriped_mounts[desc.mount_id]
            expiry = None
        if expiry is None:
            return self.conf
        # known-unstriped mount: one worker, one whole-block stripe
        return FetchConf(stripe_size=max(1, desc.length), concurrency=1,
                         per_mount_limit=self.conf.per_mount_limit,
                         qos_enabled=self.conf.qos_enabled,
                         tenant_limit=self.conf.tenant_limit)

    def _on_done(self, fetch: BlockFetch) -> None:
        # demote the mount only on the precise range-rejection
        # signature — every stripe failed but the full-range read
        # worked AT FULL LENGTH. A transient error mid-fetch, a total
        # outage, or a shrunk object (stripes past EOF short-read, the
        # truncated fallback is legal) must not collapse the mount to
        # one connection.
        if fetch.fallback_ok and not fetch.any_stripe_ok and \
                fetch.served_length >= fetch.desc.length:
            self._mark_unstriped(fetch.desc.mount_id)
        with self._lock:
            self._inflight.pop(fetch.desc.block_id, None)

    # -- entry point --------------------------------------------------------
    def fetch(self, ufs: UnderFileSystem, desc: UfsBlockDescriptor, *,
              cache: bool = True, tier_alias: str = "",
              priority: int = ON_DEMAND, tenant: str = "") -> BlockFetch:
        """Start (or join) the fetch of one cold block.

        ``priority`` is the caller's QoS class (the async cache manager
        passes ASYNC_FILL, the prefetch agent's loads PREFETCH); with
        QoS disabled it is ignored.  Joining a queued lower-priority
        fetch PROMOTES it: the moment an on-demand reader waits on a
        prefetch-initiated fetch, its queued stripe tasks jump the
        background work ahead of them."""
        with self._lock:
            if self._closed:
                raise FetchError("fetcher is closed")
            existing = self._inflight.get(desc.block_id)
            if existing is None:
                conf = self._effective_conf_locked(desc)
            else:
                existing.waiters += 1
        if existing is None:
            # construct outside the registry lock: zero-filling the
            # block-sized buffer is tens of ms for huge blocks and must
            # not stall coalescing joins / fetch starts of other blocks
            fetch = BlockFetch(desc, conf, store=self._store,
                               on_done=self._on_done)
            fetch.priority = priority
            with self._lock:
                if self._closed:
                    raise FetchError("fetcher is closed")
                existing = self._inflight.get(desc.block_id)
                if existing is None:
                    self._inflight[desc.block_id] = fetch
                else:  # raced with another starter: join theirs
                    existing.waiters += 1
        if existing is not None:
            self._m.counter("Worker.UfsFetchCoalesced").inc()
            promote_ex = None
            if self.conf.qos_enabled:
                # decide under the registry lock: two simultaneous
                # joiners must not both read the stale priority and
                # skip (or double-run) the promotion
                with self._lock:
                    if priority < existing.priority:
                        existing.priority = priority
                        promote_ex = self._executors.get(desc.mount_id)
            if promote_ex is not None:
                # an on-demand reader joined background work: its
                # queued stripe tasks stop yielding to other queues
                moved = promote_ex.promote(desc.block_id, priority)
                if moved:
                    self._m.counter("Worker.QosFetchPromoted").inc(moved)
            if cache:
                # a caching reader joining a cache=False fetch upgrades
                # it while that is still sound (nothing past the
                # frontier); otherwise the caller caches from the bytes
                existing.try_attach_cache_fill(self._store, tier_alias)
            return existing
        if cache:
            # likewise outside the lock: opening the fill can trigger
            # allocation/eviction IO; no stripe runs before the workers
            # below are submitted, so it cannot race the frontier
            fetch.try_attach_cache_fill(self._store, tier_alias)
        self._m.counter("Worker.UfsFetchStarted").inc()
        if self.conf.qos_enabled:
            self._m.counter(
                "Worker.QosFetch."
                + PRIORITY_NAMES.get(priority, str(priority))).inc()
        try:
            ex = self._executor(desc.mount_id)
            workers = min(conf.concurrency, len(fetch.stripes))
            for _ in range(max(1, workers)):
                ex.submit(self._stripe_loop, ufs, fetch,
                          priority=priority, tenant=tenant,
                          group=desc.block_id)
        except BaseException as e:  # closed/shutdown race: no workers
            fetch._fail(e)          # will ever land stripes — fail the
            raise                   # fetch so no waiter hangs on it
        return fetch

    def _stripe_loop(self, ufs: UnderFileSystem, fetch: BlockFetch) -> None:
        """One pipeline worker: pull stripe indices until exhausted.
        Each loop occupies one per-mount executor slot, so concurrent
        UFS connections per mount never exceed ``per_mount_limit``."""
        while True:
            i = fetch._claim_stripe()
            if i is None:
                return
            off, ln = fetch.stripes[i]
            # one retry per stripe before demoting the whole fetch: the
            # full-range fallback re-downloads everything over a single
            # connection, far too expensive an answer to one transient
            # 503/reset on an otherwise healthy striped fetch
            for attempt in (0, 1):
                try:
                    if ln > 0:
                        from alluxio_tpu.utils import faults

                        if faults.armed() and faults.injector() \
                                .take_ufs_error(self._fault_host):
                            raise faults.InjectedFaultError(
                                f"injected UFS fault for stripe {i} of "
                                f"block {fetch.desc.block_id}")
                        t_ufs = time.perf_counter() \
                            if fetch._span is not None else 0.0
                        data = ufs.read_range(fetch.desc.ufs_path,
                                              fetch.desc.offset + off, ln)
                        if fetch._span is not None:
                            fetch._note_ufs_ms(
                                (time.perf_counter() - t_ufs) * 1000.0)
                        if len(data) != ln:
                            raise FetchError(
                                f"short stripe read: {len(data)}B of "
                                f"{ln}B at +{off} of block "
                                f"{fetch.desc.block_id}")
                    else:
                        data = b""
                    self._m.counter("Worker.UfsFetchStripes").inc()
                    self._m.counter("Worker.UfsFetchBytes").inc(ln)
                    fetch._complete_stripe(i, data)
                    break
                except BaseException as e:  # noqa: BLE001
                    if attempt:
                        fetch._stripe_failed(ufs, e)
                        return
                    self._m.counter("Worker.UfsFetchStripeRetries").inc()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            executors = list(self._executors.values())
            self._executors.clear()
        for ex in executors:
            ex.shutdown(wait=False)
