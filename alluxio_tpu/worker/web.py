"""Read-only HTTP/JSON state endpoint for the worker.

Re-design of ``core/server/worker/src/main/java/alluxio/worker/
AlluxioWorkerRestServiceHandler.java`` (the worker web UI's backing
REST API) as a stdlib HTTP server, the worker-side twin of
``master/web.py``.

Routes:
  GET /api/v1/worker/info      id, address, tier topology, uptime
  GET /api/v1/worker/capacity  per-tier and per-dir capacity/used
  GET /api/v1/worker/blocks    block counts per tier (+ recent ids)
  GET /api/v1/worker/metrics   flat metrics snapshot (JSON)
  GET /metrics                 Prometheus text exposition
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

LOG = logging.getLogger(__name__)

_BLOCK_LIST_CAP = 1000  # /blocks id sample cap: bounded response size

def _dashboard_html() -> bytes:
    """Status page (webui-worker stand-in; shared chrome in
    ``utils/statuspage.py``)."""
    from alluxio_tpu.utils.statuspage import render

    return render(
        "alluxio-tpu worker", "/api/v1/worker",
        sections=[("Worker", "info"), ("Tiers", "tiers"),
                  ("Blocks", "blocks")],
        raw_routes=["/api/v1/worker/info", "/capacity", "/blocks",
                    "/metrics"],
        js_body="""
    const info = await j('/info');
    const t = document.getElementById('info');
    for (const k of ['worker_id','host','rpc_port','tiered_identity',
                     'uptime_ms'])
      row(t, [k, String(info[k])]);
    const cap = await j('/capacity');
    const tt = document.getElementById('tiers');
    row(tt, ['tier','capacity','used','dirs'], true);
    for (const x of cap.tiers)
      row(tt, [x.alias, gb(x.capacity), gb(x.used), x.dirs.length]);
    const bl = await j('/blocks');
    const bt = document.getElementById('blocks');
    row(bt, ['tier','count'], true);
    for (const [tier, d] of Object.entries(bl.blocks))
      row(bt, [tier, d.count]);
""")


class WorkerWebServer:
    def __init__(self, worker, port: int = 0,
                 bind_host: str = "0.0.0.0") -> None:
        wp = worker
        start_ms = int(time.time() * 1000)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                LOG.debug("worker web: " + fmt, *args)

            def do_GET(self):  # noqa: N802 (stdlib API)
                try:
                    route = self.path.split("?", 1)[0].rstrip("/")
                    if route == "":
                        self._send(200, _dashboard_html(),
                                   "text/html; charset=utf-8")
                        return
                    if route == "/metrics":
                        from alluxio_tpu.metrics import metrics

                        body = metrics().to_prometheus().encode()
                        self._send(200, body,
                                   "text/plain; version=0.0.4")
                        return
                    payload = self._route(route)
                    if payload is None:
                        self._send(404, json.dumps(
                            {"error": f"no route {route}"}).encode(),
                            "application/json")
                        return
                    self._send(200, json.dumps(
                        payload, sort_keys=True, default=str).encode(),
                        "application/json")
                except Exception as e:  # noqa: BLE001 - surface as 500
                    LOG.warning("worker web handler failed",
                                exc_info=True)
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json")

            def _send(self, code: int, body: bytes,
                      ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self, route: str):
                meta = wp.store.meta
                if route == "/api/v1/worker/info":
                    return {
                        "worker_id": wp.worker_id,
                        "host": wp.address.host,
                        "rpc_port": wp.address.rpc_port,
                        "tiered_identity": str(
                            getattr(wp.address, "tiered_identity", "")),
                        "tiers": [t.alias for t in meta.tiers],
                        "start_time_ms": start_ms,
                        "uptime_ms": max(0, int(time.time() * 1000)
                                         - start_ms),
                    }
                if route == "/api/v1/worker/capacity":
                    return {"tiers": [{
                        "alias": t.alias,
                        "ordinal": t.ordinal,
                        "capacity": t.capacity_bytes,
                        "used": t.used_bytes,
                        "dirs": [{
                            "path": d.path,
                            "capacity": d.capacity_bytes,
                            "used": d.used_bytes,
                        } for d in t.dirs],
                    } for t in meta.tiers]}
                if route == "/api/v1/worker/blocks":
                    # block_ids() snapshots under the per-dir lock, so
                    # iteration here is safe against concurrent
                    # eviction/commit without holding the store-wide
                    # allocation lock (an admin poll must not stall the
                    # write path); cross-dir counts may be ~1 op skewed
                    out = {}
                    for t in meta.tiers:
                        count, sample = 0, []
                        for d in t.dirs:
                            ids = d.block_ids()
                            count += len(ids)
                            sample.extend(
                                ids[:_BLOCK_LIST_CAP - len(sample)])
                        out[t.alias] = {"count": count,
                                        "sample": sample}
                    return {"blocks": out}
                if route == "/api/v1/worker/metrics":
                    from alluxio_tpu.metrics import metrics

                    return {"metrics": metrics().snapshot()}
                return None

        self._server = ThreadingHTTPServer((bind_host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="worker-web",
            daemon=True)
        self._thread.start()
        LOG.info("worker web endpoint on port %d", self.port)
        return self.port

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
