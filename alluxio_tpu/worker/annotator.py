"""Eviction-order annotators: LRU and LRFU.

Re-design of ``core/server/worker/.../block/annotator/{BlockAnnotator,
LRUAnnotator.java:27,LRFUAnnotator.java:29,DefaultBlockIterator,
SortedBlockSet}.java``: each cached block carries an online-maintained sort
value; eviction iterates blocks in ascending value (coldest first), tier
management iterates descending (hottest first) for promotion.

LRFU follows the reference's CRF recurrence: on access
``crf = 1 + crf * attenuation^(-step * (clock - last_clock))`` with a
logical clock ticked per access.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple


class BlockAnnotator:
    """Tracks per-block sort values; thread-safe."""

    def __init__(self) -> None:
        self._values: Dict[int, float] = {}
        self._lock = threading.Lock()

    def on_access(self, block_id: int) -> None:
        raise NotImplementedError

    def on_commit(self, block_id: int) -> None:
        self.on_access(block_id)

    def on_remove(self, block_id: int) -> None:
        with self._lock:
            self._values.pop(block_id, None)

    def sorted_blocks(self, block_ids: List[int],
                      reverse: bool = False) -> List[int]:
        """Blocks in eviction order (coldest first); unknown ids coldest."""
        with self._lock:
            vals = {bid: self._values.get(bid, float("-inf"))
                    for bid in block_ids}
        return sorted(block_ids, key=lambda b: vals[b], reverse=reverse)

    def value(self, block_id: int) -> Optional[float]:
        with self._lock:
            return self._values.get(block_id)

    @staticmethod
    def create(kind: str, **kwargs) -> "BlockAnnotator":
        k = kind.upper()
        if k == "LRU":
            return LRUAnnotator()
        if k == "LRFU":
            return LRFUAnnotator(**kwargs)
        raise ValueError(f"unknown annotator {kind}")


class LRUAnnotator(BlockAnnotator):
    """Sort value = logical access clock (reference: ``LRUAnnotator.java:27``)."""

    def __init__(self) -> None:
        super().__init__()
        self._clock = 0

    def on_access(self, block_id: int) -> None:
        with self._lock:
            self._clock += 1
            self._values[block_id] = float(self._clock)


class LRFUAnnotator(BlockAnnotator):
    """CRF-decayed frequency+recency (reference: ``LRFUAnnotator.java:29``)."""

    def __init__(self, step_factor: float = 0.25,
                 attenuation_factor: float = 2.0) -> None:
        super().__init__()
        self._step = step_factor
        self._att = attenuation_factor
        self._clock = 0
        self._last_clock: Dict[int, int] = {}

    def on_access(self, block_id: int) -> None:
        with self._lock:
            self._clock += 1
            last_crf = self._values.get(block_id, 0.0)
            last_clock = self._last_clock.get(block_id, self._clock)
            decay = self._att ** (-self._step * (self._clock - last_clock))
            self._values[block_id] = 1.0 + last_crf * decay
            self._last_clock[block_id] = self._clock

    def on_remove(self, block_id: int) -> None:
        super().on_remove(block_id)
        with self._lock:
            self._last_clock.pop(block_id, None)
