"""Block placement allocators.

Re-design of ``core/server/worker/.../block/allocator/{Allocator.java,
MaxFreeAllocator.java:28,RoundRobinAllocator.java,GreedyAllocator.java}``:
choose a StorageDir for a new block of a given size, optionally constrained
to a tier ("location"). Returns None when nothing fits — the store then
frees space and retries (eviction-on-demand).
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterable, Optional

from alluxio_tpu.worker.meta import BlockMetadataManager, StorageDir, StorageTier

ANY_TIER = ""


class Allocator:
    def __init__(self, meta: BlockMetadataManager) -> None:
        self._meta = meta

    def _candidate_tiers(self, tier_alias: str) -> Iterable[StorageTier]:
        if tier_alias == ANY_TIER:
            return self._meta.tiers
        return [self._meta.get_tier(tier_alias)]

    def allocate(self, size: int, tier_alias: str = ANY_TIER) -> Optional[StorageDir]:
        raise NotImplementedError

    @staticmethod
    def create(kind: str, meta: BlockMetadataManager) -> "Allocator":
        k = kind.upper()
        if k == "MAX_FREE":
            return MaxFreeAllocator(meta)
        if k == "ROUND_ROBIN":
            return RoundRobinAllocator(meta)
        if k == "GREEDY":
            return GreedyAllocator(meta)
        raise ValueError(f"unknown allocator {kind}")


class MaxFreeAllocator(Allocator):
    """Dir with the most free space, top tier first
    (reference default, ``MaxFreeAllocator.java:28``)."""

    def allocate(self, size: int, tier_alias: str = ANY_TIER) -> Optional[StorageDir]:
        for tier in self._candidate_tiers(tier_alias):
            best = None
            for d in tier.dirs:
                if d.available_bytes >= size and (
                        best is None or d.available_bytes > best.available_bytes):
                    best = d
            if best is not None:
                return best
        return None


class GreedyAllocator(Allocator):
    """First dir that fits, scanning tiers top-down."""

    def allocate(self, size: int, tier_alias: str = ANY_TIER) -> Optional[StorageDir]:
        for tier in self._candidate_tiers(tier_alias):
            for d in tier.dirs:
                if d.available_bytes >= size:
                    return d
        return None


class RoundRobinAllocator(Allocator):
    """Rotate across dirs within each tier to spread IO."""

    def __init__(self, meta: BlockMetadataManager) -> None:
        super().__init__(meta)
        self._next_idx: Dict[str, int] = {}

    def allocate(self, size: int, tier_alias: str = ANY_TIER) -> Optional[StorageDir]:
        for tier in self._candidate_tiers(tier_alias):
            n = len(tier.dirs)
            if n == 0:
                continue
            start = self._next_idx.get(tier.alias, 0)
            for off in range(n):
                d = tier.dirs[(start + off) % n]
                if d.available_bytes >= size:
                    self._next_idx[tier.alias] = (start + off + 1) % n
                    return d
        return None
