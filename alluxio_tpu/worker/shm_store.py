"""Worker side of the same-host zero-copy plane: the SHM lease store.

Grants, renews, releases and reclaims leases on MEM-tier block files
(named shared-memory segments under ``atpu.worker.shm.dir``) so a
co-located client can mmap them and read with zero copies. See
``alluxio_tpu/shm/`` for the protocol contract and
docs/small_reads.md for the design.

Pin integration: a granted lease calls
:meth:`TieredBlockStore.pin_shm`, which shields the block from eviction
until the lease's TTL expires — renewal extends the pin, release drops
it once the block's *last* lease goes away. The pin is the worker-side
truth: even if this registry and the store disagree transiently (e.g. a
release racing a renewal), the TTL backstop reclaims within one lease
lifetime, and Linux mmap semantics keep an already-mapped client safe
across an unlink regardless.

Lock order: the registry lock is NEVER held across a store call —
``pin_shm``/``unpin_shm`` take the store's alloc lock, so registry
mutations collect their side effects and apply them after release.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Set

from alluxio_tpu.metrics import metrics
from alluxio_tpu.shm import ShmLeaseDeniedError, ShmSegmentUnavailableError
from alluxio_tpu.worker.tiered_store import TieredBlockStore


class _Lease:
    __slots__ = ("lease_id", "session_id", "block_id", "expires_at")

    def __init__(self, lease_id: int, session_id: int, block_id: int,
                 expires_at: float) -> None:
        self.lease_id = lease_id
        self.session_id = session_id
        self.block_id = block_id
        self.expires_at = expires_at


class ShmStore:
    """Registry of live SHM segment leases for one worker."""

    def __init__(self, store: TieredBlockStore, *, lease_ttl_s: float = 30.0,
                 max_leases: int = 1024, host: str = "") -> None:
        self._store = store
        self.lease_ttl_s = max(1.0, float(lease_ttl_s))
        self.max_leases = max(1, int(max_leases))
        self._host = host
        self._lock = threading.Lock()
        self._leases: Dict[int, _Lease] = {}
        self._by_block: Dict[int, Set[int]] = {}
        self._by_session: Dict[int, Set[int]] = {}
        self._ids = itertools.count(1)
        self._m = metrics()
        # the MEM tier (top tier) is the only mappable one: its files
        # sit on /dev/shm, lower tiers are ordinary disk paths
        self._top_alias = store.meta.tiers[0].alias if store.meta.tiers \
            else "MEM"

    # ------------------------------------------------------------- grant
    def open(self, session_id: int, block_id: int) -> dict:
        """Grant a lease: ``{lease_id, path, length, ttl_s}``.

        Raises :class:`ShmLeaseDeniedError` (table full / injected
        fault) or :class:`ShmSegmentUnavailableError` (no mappable
        top-tier segment) — both of which the client treats as
        "serve this read remotely", never as a read failure."""
        from alluxio_tpu.utils import faults

        if faults.armed() and \
                faults.injector().take_shm_lease_deny(self._host):
            self._m.counter("Worker.ShmLeasesDenied").inc()
            raise ShmLeaseDeniedError(
                f"shm lease for block {block_id} denied (injected fault)")
        meta = self._store.get_block_meta(block_id)
        if meta is None or meta.tier_alias != self._top_alias:
            raise ShmSegmentUnavailableError(
                f"block {block_id} has no mappable {self._top_alias} "
                f"segment (tier: "
                f"{meta.tier_alias if meta else 'not cached'})")
        now = time.monotonic()
        unpins: List[int] = []
        try:
            with self._lock:
                self._reap_locked(now, unpins)
                if len(self._leases) >= self.max_leases:
                    self._m.counter("Worker.ShmLeasesDenied").inc()
                    raise ShmLeaseDeniedError(
                        f"shm lease table full ({self.max_leases} leases)")
                lease = _Lease(next(self._ids), session_id, block_id,
                               now + self.lease_ttl_s)
                self._leases[lease.lease_id] = lease
                self._by_block.setdefault(block_id, set()).add(
                    lease.lease_id)
                self._by_session.setdefault(session_id, set()).add(
                    lease.lease_id)
        finally:
            self._unpin_all(unpins)
        # pin AFTER registry insert: a pin without a lease self-expires,
        # a lease without a pin could let eviction unlink a fresh map
        if not self._store.pin_shm(block_id, self.lease_ttl_s):
            # raced with eviction between meta lookup and pin
            self._drop(lease.lease_id)
            raise ShmSegmentUnavailableError(
                f"block {block_id} evicted during lease grant")
        self._m.counter("Worker.ShmLeasesGranted").inc()
        return {"lease_id": lease.lease_id, "path": meta.path,
                "length": meta.length, "ttl_s": self.lease_ttl_s}

    # ------------------------------------------------------- renew/release
    def renew(self, session_id: int, lease_id: int) -> dict:
        """Extend a lease one TTL. ``{ok: False}`` for an unknown or
        expired lease (worker restart, reclaimed) — the client's cue to
        drop its mapping and re-open or fall back."""
        now = time.monotonic()
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or lease.session_id != session_id or \
                    lease.expires_at <= now:
                return {"ok": False, "ttl_s": 0.0}
            lease.expires_at = now + self.lease_ttl_s
            block_id = lease.block_id
        if not self._store.pin_shm(block_id, self.lease_ttl_s):
            self._drop(lease_id)
            return {"ok": False, "ttl_s": 0.0}
        self._m.counter("Worker.ShmLeasesRenewed").inc()
        return {"ok": True, "ttl_s": self.lease_ttl_s}

    def release(self, session_id: int, lease_id: int) -> bool:
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or lease.session_id != session_id:
                return False
        self._drop(lease_id)
        return True

    def close_session(self, session_id: int) -> None:
        """Release every lease of a disconnecting session (the graceful
        path; TTL expiry covers sessions that never say goodbye)."""
        with self._lock:
            victims = list(self._by_session.get(session_id, ()))
        for lid in victims:
            self._drop(lid)

    # --------------------------------------------------------- reclamation
    def reap_expired(self) -> int:
        """Drop expired leases and their pins; returns the count. Called
        opportunistically on every grant and by tests — the evictor's
        own TTL check on the pin map makes a dedicated reaper thread
        unnecessary."""
        unpins: List[int] = []
        with self._lock:
            n = self._reap_locked(time.monotonic(), unpins)
        self._unpin_all(unpins)
        return n

    def _reap_locked(self, now: float, unpins: List[int]) -> int:
        expired = [lid for lid, lease in self._leases.items()
                   if lease.expires_at <= now]
        for lid in expired:
            self._remove_locked(lid, unpins)
        if expired:
            self._m.counter("Worker.ShmLeasesReclaimed").inc(len(expired))
        return len(expired)

    def _drop(self, lease_id: int) -> None:
        unpins: List[int] = []
        with self._lock:
            self._remove_locked(lease_id, unpins)
        self._unpin_all(unpins)

    def _remove_locked(self, lease_id: int, unpins: List[int]) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        sset = self._by_session.get(lease.session_id)
        if sset is not None:
            sset.discard(lease_id)
            if not sset:
                del self._by_session[lease.session_id]
        bset = self._by_block.get(lease.block_id)
        if bset is not None:
            bset.discard(lease_id)
            if not bset:
                del self._by_block[lease.block_id]
                # last lease gone: lift the eviction shield now instead
                # of waiting out the TTL (applied after the lock drops)
                unpins.append(lease.block_id)

    def _unpin_all(self, block_ids: List[int]) -> None:
        for bid in block_ids:
            self._store.unpin_shm(bid)

    # ------------------------------------------------------------- report
    def stats(self) -> dict:
        with self._lock:
            return {"live_leases": len(self._leases),
                    "leased_blocks": len(self._by_block),
                    "sessions": len(self._by_session),
                    "max_leases": self.max_leases,
                    "lease_ttl_s": self.lease_ttl_s}

    def lease_of(self, lease_id: int) -> Optional[_Lease]:
        with self._lock:
            return self._leases.get(lease_id)
