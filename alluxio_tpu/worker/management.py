"""Background tier management: align, promote, watermark restore.

Re-design of ``core/server/worker/.../block/management/
{ManagementTaskCoordinator.java:37,BlockTransferExecutor}.java`` and
``management/tier/{AlignTask.java:53,PromoteTask.java:51,SwapRestoreTask.java}``:

- **Align**: tier ordering should match access order — if a block on a
  lower tier is hotter than the coldest block on the tier above, swap them
  (demote the cold one, promote the hot one).
- **Promote**: warm data moves up while the upper tier is under its
  promote quota.
- **Watermark restore**: when a tier exceeds its high watermark, free down
  to the low watermark (the reference's swap-restore/reserved-space job).

Load-awareness: tasks back off while the store is serving (the reference's
``DefaultStoreLoadTracker``); here a simple read-counter delta check.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from alluxio_tpu.heartbeat import HeartbeatExecutor
from alluxio_tpu.metrics import metrics
from alluxio_tpu.worker.tiered_store import TieredBlockStore

LOG = logging.getLogger(__name__)


class StoreLoadTracker:
    """Backs off management work while clients are actively reading."""

    def __init__(self, store: TieredBlockStore) -> None:
        self._store = store
        self._last_access_count = 0

    def is_idle(self) -> bool:
        current = metrics().counter("Worker.BlocksAccessed").count
        idle = current == self._last_access_count
        self._last_access_count = current
        return idle


class AlignTask:
    """Reference: ``management/tier/AlignTask.java:53``."""

    def __init__(self, store: TieredBlockStore, swaps_per_run: int = 16) -> None:
        self._store = store
        self._swaps = swaps_per_run

    def run(self) -> int:
        moved = 0
        meta = self._store.meta
        ann = self._store.annotator
        for upper in meta.tiers[:-1]:
            lower = meta.tiers[upper.ordinal + 1]
            upper_blocks = [b for d in upper.dirs for b in d.block_ids()]
            lower_blocks = [b for d in lower.dirs for b in d.block_ids()]
            if not upper_blocks or not lower_blocks:
                continue
            cold_up = ann.sorted_blocks(upper_blocks)          # coldest first
            hot_down = ann.sorted_blocks(lower_blocks, reverse=True)
            for cold, hot in zip(cold_up, hot_down):
                if moved >= self._swaps:
                    return moved
                cv, hv = ann.value(cold), ann.value(hot)
                if cv is None or hv is None or hv <= cv:
                    break  # ordering aligned
                try:
                    self._store.move_block(cold, lower.alias)
                    self._store.move_block(hot, upper.alias)
                    moved += 2
                except Exception:  # noqa: BLE001 - busy blocks retry next tick
                    LOG.debug("tier-align move skipped", exc_info=True)
                    continue
        return moved


class PromoteTask:
    """Reference: ``management/tier/PromoteTask.java:51``."""

    def __init__(self, store: TieredBlockStore, quota_percent: int = 90,
                 moves_per_run: int = 16) -> None:
        self._store = store
        self._quota = quota_percent
        self._moves = moves_per_run

    def run(self) -> int:
        moved = 0
        meta = self._store.meta
        ann = self._store.annotator
        for upper in meta.tiers[:-1]:
            lower = meta.tiers[upper.ordinal + 1]
            lower_blocks = [b for d in lower.dirs for b in d.block_ids()]
            for hot in ann.sorted_blocks(lower_blocks, reverse=True):
                if moved >= self._moves:
                    return moved
                used_pct = (100 * upper.used_bytes // upper.capacity_bytes
                            if upper.capacity_bytes else 100)
                if used_pct >= self._quota:
                    break
                try:
                    self._store.move_block(hot, upper.alias)
                    moved += 1
                except Exception:  # noqa: BLE001 - busy/full: retry next tick
                    LOG.debug("tier-promote move skipped", exc_info=True)
                    break
        return moved


class WatermarkRestoreTask:
    """Free tiers above their high watermark down to the low watermark."""

    def __init__(self, store: TieredBlockStore, high: float = 0.95,
                 low: float = 0.7) -> None:
        self._store = store
        self._high = high
        self._low = low

    def run(self) -> int:
        freed = 0
        for tier in self._store.meta.tiers:
            cap = tier.capacity_bytes
            if cap and tier.used_bytes > self._high * cap:
                target = int(tier.used_bytes - self._low * cap)
                freed += self._store.free_space(tier.alias, target)
        return freed


class ManagementTaskCoordinator(HeartbeatExecutor):
    """One heartbeat driving the task set, load-aware
    (reference: ``ManagementTaskCoordinator.java:37``)."""

    def __init__(self, store: TieredBlockStore, *, align: bool = True,
                 promote: bool = True, quota_percent: int = 90,
                 high_watermark: float = 0.95, low_watermark: float = 0.7):
        self._tracker = StoreLoadTracker(store)
        self._tasks: List = [WatermarkRestoreTask(store, high_watermark,
                                                  low_watermark)]
        if align:
            self._tasks.append(AlignTask(store))
        if promote:
            self._tasks.append(PromoteTask(store, quota_percent))

    def heartbeat(self) -> None:
        if not self._tracker.is_idle():
            return  # back off under load
        for task in self._tasks:
            try:
                task.run()
            except Exception:  # noqa: BLE001
                LOG.exception("management task %s failed", type(task).__name__)
