"""Security: authentication, authorization (POSIX + ACL), audit.

Re-design of ``core/common/src/main/java/alluxio/security`` (41 files):
the reference runs a SASL handshake over a dedicated gRPC stream
(``ChannelAuthenticator``/``DefaultAuthenticationServer``); the TPU build
carries the identity in per-RPC gRPC metadata validated server-side by a
pluggable provider — same trust model for SIMPLE/CUSTOM (the wire asserts
a username; CUSTOM validates an opaque credential), much less machinery.
"""

from alluxio_tpu.security.user import (
    User, authenticated_user, get_client_user, set_authenticated_user,
)

__all__ = ["User", "authenticated_user", "get_client_user",
           "set_authenticated_user"]
