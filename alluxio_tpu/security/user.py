"""User identity + per-request authentication context.

Re-design of ``security/user/User.java`` + ``AuthenticatedClientUser``
(thread-local in the reference -> contextvar here, which also survives
async handlers) and the group-mapping service
(``security/group/GroupMappingService``: OS groups by default).
"""

from __future__ import annotations

import contextvars
import getpass
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass(frozen=True)
class User:
    name: str
    groups: tuple = field(default_factory=tuple)
    #: the user that actually connected, when this one is impersonated
    connection_user: Optional[str] = None


def get_os_user() -> str:
    try:
        return getpass.getuser()
    except Exception:  # noqa: BLE001 - no passwd entry in some containers
        import os

        return os.environ.get("USER", f"uid-{os.getuid()}")


_GROUP_CACHE: dict = {}
_GROUP_CACHE_TTL_S = 60.0


def get_os_groups(user: str) -> List[str]:
    """OS group mapping, cached with a TTL — grp.getgrall() enumerates the
    whole group database (an NSS/LDAP round trip on some hosts) and this
    runs on the master's per-RPC authentication path (reference: the
    GroupMappingService cache)."""
    import time

    hit = _GROUP_CACHE.get(user)
    if hit is not None and time.monotonic() - hit[1] < _GROUP_CACHE_TTL_S:
        return list(hit[0])
    groups = _get_os_groups_uncached(user)
    _GROUP_CACHE[user] = (groups, time.monotonic())
    return list(groups)


def _get_os_groups_uncached(user: str) -> List[str]:
    try:
        import grp
        import pwd

        pw = pwd.getpwnam(user)
        groups = [g.gr_name for g in grp.getgrall() if user in g.gr_mem]
        primary = grp.getgrgid(pw.pw_gid).gr_name
        if primary not in groups:
            groups.insert(0, primary)
        return groups
    except (KeyError, ImportError):
        return []


_CURRENT_USER: contextvars.ContextVar[Optional[User]] = \
    contextvars.ContextVar("atpu_authenticated_user", default=None)


def authenticated_user() -> Optional[User]:
    """The user bound to the current RPC (server side)."""
    return _CURRENT_USER.get()


def set_authenticated_user(user: Optional[User]) -> contextvars.Token:
    return _CURRENT_USER.set(user)


def reset_authenticated_user(token: contextvars.Token) -> None:
    _CURRENT_USER.reset(token)


def get_client_user(conf=None) -> str:
    """The identity a client asserts (reference: LoginUser resolution:
    configured username, else the OS user)."""
    if conf is not None:
        from alluxio_tpu.conf import Keys

        configured = conf.get(Keys.SECURITY_LOGIN_USERNAME)
        if configured:
            return str(configured)
    return get_os_user()
