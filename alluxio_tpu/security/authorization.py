"""Authorization: POSIX mode bits + access control lists.

Re-design of ``core/common/.../security/authorization/{Mode,AclEntry,
AccessControlList,DefaultAccessControlList}.java`` and the master-side
permission checker (``core/server/master/.../file/PermissionChecker.java``):
mode-bit checks walk the ancestor chain (EXECUTE on every directory),
ACLs extend them with named user/group entries and a mask, directories
can carry default ACLs inherited at create time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence

from alluxio_tpu.utils.exceptions import PermissionDeniedError

READ = 4
WRITE = 2
EXECUTE = 1

#: xattr keys carrying ACLs (single source of truth; the file master and
#: the checker both use these)
ACL_XATTR = "system.acl"
DEFAULT_ACL_XATTR = "system.default.acl"


def acl_entries_of(inode) -> "Optional[List[str]]":
    raw = inode.xattr.get(ACL_XATTR, "")
    return raw.split(",") if raw else None


def bits_to_string(bits: int) -> str:
    return (("r" if bits & READ else "-") + ("w" if bits & WRITE else "-")
            + ("x" if bits & EXECUTE else "-"))


@dataclass(frozen=True)
class AclEntry:
    """``user:alice:rwx`` / ``group:team:r-x`` / ``mask::rw-`` /
    ``other::r--`` (reference: AclEntry.toCliString)."""

    type: str          # user | group | mask | other | owner_user | owner_group
    subject: str       # empty for mask/other/owner entries
    bits: int
    is_default: bool = False

    def to_cli_string(self) -> str:
        prefix = "default:" if self.is_default else ""
        t = {"owner_user": "user", "owner_group": "group"}.get(
            self.type, self.type)
        return f"{prefix}{t}:{self.subject}:{bits_to_string(self.bits)}"

    @staticmethod
    def parse(text: str) -> "AclEntry":
        s = text.strip()
        is_default = s.startswith("default:")
        if is_default:
            s = s[len("default:"):]
        parts = s.split(":")
        if len(parts) != 3:
            raise ValueError(f"bad ACL entry: {text!r}")
        t, subject, perm = parts
        bits = 0
        for ch in perm:
            bits |= {"r": READ, "w": WRITE, "x": EXECUTE, "-": 0}[ch]
        if t == "user" and not subject:
            t = "owner_user"
        if t == "group" and not subject:
            t = "owner_group"
        return AclEntry(type=t, subject=subject, bits=bits,
                        is_default=is_default)


@dataclass
class AccessControlList:
    """Extended ACL over the owner/group/other base
    (reference: AccessControlList.java)."""

    named_users: dict = field(default_factory=dict)    # name -> bits
    named_groups: dict = field(default_factory=dict)   # name -> bits
    mask: Optional[int] = None

    def is_empty(self) -> bool:
        return not self.named_users and not self.named_groups \
            and self.mask is None

    def effective(self, bits: int) -> int:
        return bits & self.mask if self.mask is not None else bits

    def to_entries(self, is_default: bool = False) -> List[str]:
        out = []
        for name, bits in sorted(self.named_users.items()):
            out.append(AclEntry("user", name, bits,
                                is_default).to_cli_string())
        for name, bits in sorted(self.named_groups.items()):
            out.append(AclEntry("group", name, bits,
                                is_default).to_cli_string())
        if self.mask is not None:
            out.append(AclEntry("mask", "", self.mask,
                                is_default).to_cli_string())
        return out

    @staticmethod
    def from_entries(entries: Iterable[str]) -> "AccessControlList":
        acl = AccessControlList()
        for raw in entries:
            e = AclEntry.parse(raw)
            if e.type == "user":
                acl.named_users[e.subject] = e.bits
            elif e.type == "group":
                acl.named_groups[e.subject] = e.bits
            elif e.type == "mask":
                acl.mask = e.bits
        return acl


def check_bits(*, bits_wanted: int, user: str, groups: Sequence[str],
               owner: str, group: str, mode: int,
               acl_entries: Optional[List[str]] = None) -> bool:
    """POSIX + ACL evaluation order (reference:
    AccessControlList.checkPermission): owner, named users, owning/named
    groups, other. Per POSIX.1e, each matching group entry is evaluated
    INDIVIDUALLY (mask-limited): access is granted iff at least one entry
    alone carries every requested bit — entries are never OR-merged."""
    if user == owner:
        return (mode >> 6) & bits_wanted == bits_wanted
    acl = AccessControlList.from_entries(acl_entries or [])
    if user in acl.named_users:
        return acl.effective(acl.named_users[user]) & bits_wanted \
            == bits_wanted
    matched_group = False
    if group and group in groups:
        matched_group = True
        # the owning-group bits are mask-limited when an extended ACL exists
        if acl.effective((mode >> 3) & 7) & bits_wanted == bits_wanted:
            return True
    for g in groups:
        if g in acl.named_groups:
            matched_group = True
            if acl.effective(acl.named_groups[g]) & bits_wanted \
                    == bits_wanted:
                return True
    if matched_group:
        return False
    return mode & bits_wanted == bits_wanted


class PermissionChecker:
    """Master-side checks (reference: DefaultPermissionChecker):
    - traverse: EXECUTE on every ancestor directory
    - read/write on the target (or WRITE on the parent for create/delete)
    - owner-or-superuser for chmod/chgrp; superuser-only for chown."""

    def __init__(self, *, enabled: bool = True,
                 supergroup: str = "supergroup",
                 superuser: str = "") -> None:
        self.enabled = enabled
        self._supergroup = supergroup
        self._superuser = superuser or ""

    def is_superuser(self, user) -> bool:
        if user is None:
            return True  # in-process caller (no RPC context) is trusted
        return user.name == self._superuser or \
            self._supergroup in user.groups

    def check_traverse(self, user, chain) -> None:
        """chain: iterable of ancestor inodes (root..parent)."""
        if not self.enabled or user is None or self.is_superuser(user):
            return
        for inode in chain:
            if not inode.is_directory:
                continue
            if not check_bits(bits_wanted=EXECUTE, user=user.name,
                              groups=user.groups, owner=inode.owner,
                              group=inode.group, mode=inode.mode,
                              acl_entries=acl_entries_of(inode)):
                raise PermissionDeniedError(
                    f"user {user.name} lacks execute on "
                    f"ancestor {inode.name or '/'}")

    def check(self, user, inode, bits_wanted: int, *,
              path: str = "") -> None:
        if not self.enabled or user is None or self.is_superuser(user):
            return
        if not check_bits(bits_wanted=bits_wanted, user=user.name,
                          groups=user.groups, owner=inode.owner,
                          group=inode.group, mode=inode.mode,
                          acl_entries=acl_entries_of(inode)):
            raise PermissionDeniedError(
                f"user {user.name} lacks "
                f"{bits_to_string(bits_wanted).replace('-', '')} on "
                f"{path or inode.name}")

    def check_owner(self, user, inode, *, path: str = "") -> None:
        if not self.enabled or user is None or self.is_superuser(user):
            return
        if user.name != inode.owner:
            raise PermissionDeniedError(
                f"user {user.name} is not the owner of "
                f"{path or inode.name}")

    def check_superuser(self, user) -> None:
        if not self.enabled or user is None:
            return
        if not self.is_superuser(user):
            raise PermissionDeniedError(
                f"user {user.name} is not a superuser")
