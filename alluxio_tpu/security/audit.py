"""Async audit logging.

Re-design of ``core/server/common/.../master/audit/
AsyncUserAccessAuditLogWriter.java:31`` + ``master/file/
FileSystemMasterAuditContext.java:27``: RPC handlers record an audit
context (user, command, src/dst, allowed, succeeded); entries drain to a
logger on a background thread so the RPC path never blocks on IO.
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass
from typing import Optional

AUDIT_LOG = logging.getLogger("alluxio_tpu.audit")


@dataclass
class AuditContext:
    command: str
    src_path: str = ""
    dst_path: str = ""
    user: str = ""
    ip: str = ""
    allowed: bool = True
    succeeded: bool = True

    def format(self) -> str:
        return (f"succeeded={str(self.succeeded).lower()} "
                f"allowed={str(self.allowed).lower()} "
                f"ugi={self.user} ip={self.ip} cmd={self.command} "
                f"src={self.src_path} dst={self.dst_path}")


class AsyncAuditLogWriter:
    """Bounded-queue writer; drops (and counts) entries when saturated
    rather than stalling RPCs (reference behavior)."""

    def __init__(self, capacity: int = 10_000) -> None:
        self._queue: "queue.Queue[Optional[AuditContext]]" = \
            queue.Queue(maxsize=capacity)
        self._thread: Optional[threading.Thread] = None
        self.dropped = 0
        self._stopped = threading.Event()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._drain,
                                        name="audit-writer", daemon=True)
        self._thread.start()

    def append(self, ctx: AuditContext) -> None:
        try:
            self._queue.put_nowait(ctx)
        except queue.Full:
            self.dropped += 1

    def _drain(self) -> None:
        while not self._stopped.is_set():
            try:
                ctx = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if ctx is None:
                break
            AUDIT_LOG.info("%s", ctx.format())

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
            self._thread.join(timeout=2)
