"""Authentication: metadata-borne identity + pluggable validation.

Re-design of ``security/authentication/{ChannelAuthenticator,
DefaultAuthenticationServer,AuthenticationProvider}.java`` +
``grpc/sasl_server.proto``: instead of a SASL side-stream, the client
attaches ``atpu-user`` (+ optional ``atpu-impersonate``, ``atpu-token``)
metadata to every RPC; the server validates per auth type and resolves
impersonation against the master's allow-list
(reference: ``ImpersonationAuthenticator``).
"""

from __future__ import annotations

import importlib
from typing import Callable, List, Optional, Tuple

from alluxio_tpu.conf import Configuration, Keys, Templates
from alluxio_tpu.security.user import User, get_client_user, get_os_groups
from alluxio_tpu.utils.exceptions import (
    PermissionDeniedError, UnauthenticatedError,
)

USER_KEY = "atpu-user"
IMPERSONATE_KEY = "atpu-impersonate"
TOKEN_KEY = "atpu-token"

#: CUSTOM provider signature: (user, token) -> None, raise to reject
AuthenticationProvider = Callable[[str, str], None]


def load_custom_provider(spec: str) -> AuthenticationProvider:
    """``module.path:attr`` -> provider callable."""
    mod_name, _, attr = spec.partition(":")
    provider = getattr(importlib.import_module(mod_name), attr)
    return provider() if isinstance(provider, type) else provider


def client_metadata(conf: Optional[Configuration] = None
                    ) -> List[Tuple[str, str]]:
    """Metadata a client attaches to every call."""
    md = [(USER_KEY, get_client_user(conf))]
    if conf is not None:
        target = conf.get(Keys.SECURITY_LOGIN_IMPERSONATION_USERNAME)
        if target:
            md.append((IMPERSONATE_KEY, str(target)))
        token = conf.get(Keys.SECURITY_LOGIN_TOKEN)
        if token:
            md.append((TOKEN_KEY, str(token)))
    return md


def worker_authenticator(conf: Configuration):
    """The worker data plane's authenticator — installed only when
    worker QoS is on (per-tenant quotas need a principal on every RPC);
    None otherwise, keeping the QoS-off server byte-identical to a
    build without it.  One helper so every worker boot path
    (standalone launch, minicluster) stays in lockstep."""
    from alluxio_tpu.conf import Keys

    if not conf.get_bool(Keys.WORKER_QOS_ENABLED):
        return None
    return Authenticator(conf)


class Authenticator:
    """Server-side per-RPC authentication + impersonation resolution."""

    def __init__(self, conf: Optional[Configuration] = None) -> None:
        self._conf = conf or Configuration()
        self.auth_type = str(self._conf.get(Keys.SECURITY_AUTH_TYPE))
        self._provider: Optional[AuthenticationProvider] = None
        if self.auth_type == "CUSTOM":
            spec = self._conf.get(Keys.SECURITY_AUTH_CUSTOM_PROVIDER)
            if not spec:
                raise ValueError(
                    "CUSTOM auth needs atpu.security.authentication."
                    "custom.provider")
            self._provider = load_custom_provider(str(spec))

    def authenticate(self, metadata: dict) -> Optional[User]:
        """Metadata dict -> authenticated User (None when NOSASL)."""
        if self.auth_type == "NOSASL":
            return None
        name = metadata.get(USER_KEY, "")
        if not name:
            raise UnauthenticatedError(
                "no user in request metadata (SIMPLE/CUSTOM auth)")
        if self._provider is not None:
            try:
                self._provider(name, metadata.get(TOKEN_KEY, ""))
            except Exception as e:  # noqa: BLE001 - provider rejects
                raise UnauthenticatedError(
                    f"authentication failed for {name}: {e}") from None
        target = metadata.get(IMPERSONATE_KEY, "")
        if target and target != name:
            self._check_impersonation(name, target)
            return User(name=target,
                        groups=tuple(get_os_groups(target)),
                        connection_user=name)
        return User(name=name, groups=tuple(get_os_groups(name)))

    def _check_impersonation(self, connection_user: str,
                             target: str) -> None:
        """Reference: master-side impersonation allow-list
        (``alluxio.master.security.impersonation.<user>.users/groups``)."""
        allowed_users = self._conf.get_list(
            Templates.MASTER_IMPERSONATION_USERS.format(connection_user))
        allowed_groups = self._conf.get_list(
            Templates.MASTER_IMPERSONATION_GROUPS.format(connection_user))
        if "*" in allowed_users or target in allowed_users:
            return
        if allowed_groups:
            target_groups = set(get_os_groups(target))
            if "*" in allowed_groups or \
                    target_groups.intersection(allowed_groups):
                return
        raise PermissionDeniedError(
            f"user {connection_user!r} is not configured to impersonate "
            f"{target!r}")
