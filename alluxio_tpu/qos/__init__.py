"""Multi-tenant QoS primitives: priority classes, token buckets and
tenant-scoped concurrency budgets.

"Millions of users" makes contention — not raw throughput — the
cluster's failure mode: one abusive tenant flooding master RPCs or cold
UFS reads starves every well-behaved reader, and background work
(prefetch, async cache fills) competes head-to-head with on-demand
reads on the same bounded executors.  Shared-cache studies (Hoard,
arxiv 1812.00669; the hierarchical HPC storage study, arxiv 2301.01494)
both find cross-job interference on shared tiers dominating tail
latency.  This package holds the mechanisms every enforcement point
shares:

- :class:`TokenBucket` / :class:`TokenBucketSet` — per-principal rate
  limiting with a retry-after hint, used by the master's RPC admission
  controller (``qos/admission.py``);
- :data:`ON_DEMAND` / :data:`ASYNC_FILL` / :data:`PREFETCH` — the
  priority classes every worker-side request carries;
- :class:`PriorityExecutor` — a bounded thread pool that drains in
  priority order with per-tenant concurrency caps; queued (not
  in-flight) background work is overtaken by arriving on-demand work,
  and a queued fetch joined by an on-demand reader is promoted;
- :class:`PriorityTaskQueue` — priority-ordered drop-in for the async
  cache manager's bounded FIFO;
- :class:`StripeBudget` — per-tenant cap on concurrent client-side DCN
  stripe streams (``client/remote_read.py``).

Everything here is clock-injectable for deterministic tests, and every
class degrades to today's FIFO/unlimited behavior when its feature is
disabled — QoS off is byte-identical to a build without it.  See
``docs/qos.md``.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

LOG = logging.getLogger(__name__)

__all__ = [
    "ON_DEMAND", "ASYNC_FILL", "PREFETCH", "PRIORITY_NAMES",
    "priority_from_name", "TokenBucket", "TokenBucketSet",
    "PriorityExecutor", "PriorityTaskQueue", "StripeBudget",
]

#: Priority classes, lowest number drains first.  ON_DEMAND is a reader
#: blocked RIGHT NOW; ASYNC_FILL is a client-issued passive cache fill
#: (the client already has the bytes); PREFETCH is speculative work for
#: a predicted future access.
ON_DEMAND = 0
ASYNC_FILL = 1
PREFETCH = 2

PRIORITY_NAMES = {ON_DEMAND: "ON_DEMAND", ASYNC_FILL: "ASYNC_FILL",
                  PREFETCH: "PREFETCH"}
_NAME_TO_PRIORITY = {v: k for k, v in PRIORITY_NAMES.items()}


def priority_from_name(name: str, default: int = ASYNC_FILL) -> int:
    """Wire string -> class; unknown strings fall back to ``default``
    (an old client naming a class this build dropped must not crash the
    worker)."""
    return _NAME_TO_PRIORITY.get(str(name or "").upper(), default)


class TokenBucket:
    """Classic token bucket with a *retry-after* answer.

    ``try_acquire`` never blocks: over-limit callers are the ones being
    shed, and making them queue inside the limiter would recreate the
    unbounded backlog admission control exists to prevent.  The returned
    hint is how long until one token accrues — what the master puts in
    the typed ``ResourceExhausted`` so clients back off instead of
    hammering.
    """

    __slots__ = ("rate", "burst", "_tokens", "_last", "_clock", "_lock")

    def __init__(self, rate: float, burst: float, *,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.rate = max(1e-9, float(rate))
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst  # start full: a fresh principal is
        self._last = clock()       # not mid-flood by definition
        self._clock = clock
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> Tuple[bool, float]:
        """``(admitted, retry_after_s)``; the hint is 0.0 on admit."""
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, (n - self._tokens) / self.rate

    def available(self) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            return self._tokens


class TokenBucketSet:
    """Keyed token buckets with bounded membership.

    The key space is attacker-controlled (any client can mint
    principals), so the map is capped: beyond ``max_keys`` the
    least-recently-USED bucket is evicted — O(1) via insertion-ordered
    dict, because a principal flood must not make every admission
    check O(cap).  An evicted flooding principal that comes back gets
    a fresh (full) bucket — one burst of grace, still bounded memory,
    which is the right trade against an unbounded dict.
    """

    def __init__(self, rate: float, burst: float, *, max_keys: int = 4096,
                 clock: Callable[[], float] = time.monotonic) -> None:
        from collections import OrderedDict

        self.rate = float(rate)
        self.burst = float(burst)
        self._max = max(1, int(max_keys))
        self._clock = clock
        self._lock = threading.Lock()
        #: key -> bucket, ordered least- to most-recently used
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self.evictions = 0

    def bucket(self, key: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(key)
            if b is None:
                if len(self._buckets) >= self._max:
                    self._buckets.popitem(last=False)  # LRU out
                    self.evictions += 1
                b = self._buckets[key] = TokenBucket(
                    self.rate, self.burst, clock=self._clock)
            else:
                self._buckets.move_to_end(key)
            return b

    def try_acquire(self, key: str, n: float = 1.0) -> Tuple[bool, float]:
        return self.bucket(key).try_acquire(n)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buckets)


class _Task:
    __slots__ = ("priority", "seq", "fn", "args", "tenant", "group",
                 "stale")

    def __init__(self, priority: int, seq: int, fn, args, tenant: str,
                 group) -> None:
        self.priority = priority
        self.seq = seq
        self.fn = fn
        self.args = args
        self.tenant = tenant
        self.group = group
        self.stale = False  # superseded by a promoted copy

    def order(self) -> Tuple[int, int]:
        return (self.priority, self.seq)


class PriorityExecutor:
    """Bounded thread pool draining a priority queue with per-tenant
    concurrency caps — the enforcement point the worker's per-mount UFS
    stripe executors ride.

    Semantics:

    - tasks of a lower priority number run first; within a class,
      submission order (so ``prioritize=False`` — QoS disabled — is
      exactly the FIFO ThreadPoolExecutor it replaces);
    - an arriving ON_DEMAND task overtakes QUEUED background work;
      in-flight tasks are never interrupted (preempt-queued-only);
    - :meth:`promote` re-prioritizes queued tasks of a group — the
      coalescing path upgrades a queued PREFETCH fetch the moment an
      on-demand reader joins it;
    - a task whose tenant already runs ``tenant_cap`` tasks is passed
      over (parked) until one of that tenant's tasks finishes, so one
      flooding principal cannot occupy every executor slot however
      early it queued.  Parked work is counted in ``deferred``.

    ``submit`` after :meth:`shutdown` raises ``RuntimeError`` like the
    stdlib executor it replaces.
    """

    def __init__(self, max_workers: int, *, thread_name_prefix: str = "qos",
                 prioritize: bool = True, tenant_cap: int = 0) -> None:
        self._max_workers = max(1, int(max_workers))
        self._prefix = thread_name_prefix
        self._prioritize = bool(prioritize)
        self.tenant_cap = max(0, int(tenant_cap))
        self._heap: List[Tuple[Tuple[int, int], _Task]] = []
        self._parked: Dict[str, List[_Task]] = {}
        self._running: Dict[str, int] = {}
        self._threads: List[threading.Thread] = []
        self._cond = threading.Condition()
        self._seq = itertools.count()
        self._closed = False
        self._idle = 0
        #: live (non-stale, non-parked) heap entries — maintained so
        #: submit's spawn decision is O(1) instead of sweeping a
        #: flood-deep heap under the lock on every submission
        self._ready = 0
        self.deferred = 0   # tenant-cap park events
        self.promoted = 0   # queued tasks re-prioritized

    # ------------------------------------------------------------ submit
    def submit(self, fn, *args, priority: int = ON_DEMAND,
               tenant: str = "", group=None) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("cannot submit after shutdown")
            if not self._prioritize:
                priority, tenant = 0, ""
            t = _Task(priority, next(self._seq), fn, args, tenant, group)
            heapq.heappush(self._heap, (t.order(), t))
            self._ready += 1
            if len(self._threads) < self._max_workers and \
                    self._ready > self._idle:
                th = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"{self._prefix}-{len(self._threads)}")
                self._threads.append(th)
                th.start()
            self._cond.notify()

    def promote(self, group, priority: int) -> int:
        """Raise every queued (and parked) task of ``group`` at a lower
        priority to ``priority``; returns how many moved.  In-flight
        tasks are untouched — promotion reorders the queue, it does not
        preempt."""
        if not self._prioritize:
            return 0
        moved = 0
        with self._cond:
            for _, t in list(self._heap):
                if not t.stale and t.group == group and \
                        t.priority > priority:
                    # stale + clone keeps _ready balanced: -1 (stale
                    # discard pre-counted here) +1 (clone)
                    t.stale = True
                    clone = _Task(priority, next(self._seq), t.fn,
                                  t.args, t.tenant, t.group)
                    heapq.heappush(self._heap, (clone.order(), clone))
                    moved += 1
            for tasks in self._parked.values():
                for t in tasks:
                    if t.group == group and t.priority > priority:
                        # in-place: the unpark path picks the best-
                        # priority parked task, so this takes effect
                        # at the tenant's next free slot
                        t.priority = priority
                        moved += 1
            if moved:
                self.promoted += moved
                self._cond.notify_all()
        return moved

    # ------------------------------------------------------------- drain
    def _tenant_at_cap_locked(self, tenant: str) -> bool:
        return bool(self.tenant_cap) and tenant != "" and \
            self._running.get(tenant, 0) >= self.tenant_cap

    def _pop_locked(self) -> Optional[_Task]:
        """Highest-priority runnable task; tenants at cap are parked
        (re-queued by priority when one of their tasks ends)."""
        while self._heap:
            _, t = heapq.heappop(self._heap)
            if t.stale:
                continue  # _ready already dropped when it was staled
            self._ready -= 1
            if self._tenant_at_cap_locked(t.tenant):
                self._parked.setdefault(t.tenant, []).append(t)
                self.deferred += 1
                continue
            return t
        return None

    def _run(self) -> None:
        while True:
            with self._cond:
                self._idle += 1
                try:
                    while True:
                        task = self._pop_locked()
                        if task is not None:
                            break
                        # like ThreadPoolExecutor.shutdown(wait=False):
                        # no NEW submits, but already-queued (and
                        # parked) work still runs — dropping it would
                        # strand fetch waiters forever
                        if self._closed and not self._heap and \
                                not self._parked:
                            return
                        self._cond.wait()
                finally:
                    self._idle -= 1
                self._running[task.tenant] = \
                    self._running.get(task.tenant, 0) + 1
            try:
                task.fn(*task.args)
            except BaseException:  # noqa: BLE001 - stripe loops own errors
                LOG.debug("priority-executor task raised", exc_info=True)
            finally:
                with self._cond:
                    n = self._running.get(task.tenant, 0) - 1
                    if n > 0:
                        self._running[task.tenant] = n
                    else:
                        self._running.pop(task.tenant, None)
                    parked = self._parked.get(task.tenant)
                    if parked and not self._tenant_at_cap_locked(
                            task.tenant):
                        # best (priority, seq) first, NOT FIFO: a
                        # parked task promoted by a coalescing
                        # on-demand join must use the tenant's next
                        # slot ahead of its older background work
                        t2 = min(parked, key=_Task.order)
                        parked.remove(t2)
                        if not parked:
                            del self._parked[task.tenant]
                        heapq.heappush(self._heap, (t2.order(), t2))
                        self._ready += 1
                    self._cond.notify()

    def queued(self) -> int:
        with self._cond:
            return self._ready + \
                sum(len(v) for v in self._parked.values())

    def running_by_tenant(self) -> Dict[str, int]:
        with self._cond:
            return dict(self._running)

    def shutdown(self, wait: bool = False) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if wait:
            for th in self._threads:
                th.join(timeout=5)


class PriorityTaskQueue:
    """Bounded priority queue with ``queue.Queue`` task-accounting
    compatibility (``task_done`` / ``unfinished_tasks`` /
    ``all_tasks_done``), so :class:`~alluxio_tpu.worker.ufs_io.
    AsyncCacheManager` can swap it in without changing its
    ``wait_idle`` logic.  ``prioritize=False`` degrades to exact FIFO
    (today's behavior)."""

    def __init__(self, maxsize: int, *, prioritize: bool = True) -> None:
        self._max = max(1, int(maxsize))
        self._prioritize = bool(prioritize)
        self._heap: List[Tuple[int, int, object]] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self.all_tasks_done = threading.Condition(self._lock)
        self.unfinished_tasks = 0
        self._seq = itertools.count()

    def put_nowait(self, item, priority: int = 0) -> None:
        import queue as _q

        with self._lock:
            if len(self._heap) >= self._max:
                raise _q.Full
            if not self._prioritize:
                priority = 0
            heapq.heappush(self._heap,
                           (priority, next(self._seq), item))
            self.unfinished_tasks += 1
            self._not_empty.notify()

    def get(self, timeout: Optional[float] = None):
        import queue as _q

        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._not_empty:
            while not self._heap:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise _q.Empty
                self._not_empty.wait(remaining)
            return heapq.heappop(self._heap)[2]

    def task_done(self) -> None:
        with self.all_tasks_done:
            n = self.unfinished_tasks - 1
            if n < 0:
                raise ValueError("task_done() called too many times")
            self.unfinished_tasks = n
            if n == 0:
                self.all_tasks_done.notify_all()

    def qsize(self) -> int:
        with self._lock:
            return len(self._heap)


class StripeBudget:
    """Per-tenant cap on concurrent remote-read stripe streams.

    The client-side counterpart of the worker's tenant caps: a shared
    multi-tenant client process (FUSE mount, REST proxy) must not let
    one tenant's striped reads monopolize the DCN fan-out.  ``cap`` is
    read per call so a remediation/conf overlay can retune it live;
    ``cap <= 0`` means unlimited and costs one comparison.

    ``acquire(force=True)`` always succeeds (and is still counted):
    the frontier stripe of a read must never deadlock behind the
    budget — the cap shapes readahead and hedges, not liveness.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._held: Dict[str, int] = {}
        #: denied acquires (any kind); the metrics split
        #: deferred-stripes from suppressed-hedges at the call sites
        self.deferred = 0

    def acquire(self, tenant: str, cap: int, *, force: bool = False) -> bool:
        with self._lock:
            held = self._held.get(tenant, 0)
            if not force and cap > 0 and held >= cap:
                self.deferred += 1
                return False
            self._held[tenant] = held + 1
            return True

    def release(self, tenant: str) -> None:
        with self._lock:
            n = self._held.get(tenant, 0) - 1
            if n > 0:
                self._held[tenant] = n
            else:
                self._held.pop(tenant, None)

    def held(self, tenant: str) -> int:
        with self._lock:
            return self._held.get(tenant, 0)
