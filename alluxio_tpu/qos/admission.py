"""Master RPC admission control: per-principal token buckets wrapped
around the server dispatch.

Overload at the master must be a *bounded, observable* state: over-limit
calls are SHED with a typed ``ResourceExhaustedError`` carrying a
retry-after hint (which ``utils/retry.py`` honors client-side) instead
of queuing in the RPC executor until everything times out.  Principals
come from the existing ``security/`` plumbing — the authenticated user
when the server runs an authenticator, else the ``atpu-user`` metadata
every client attaches.

Conf: ``atpu.master.rpc.admission.*`` (default off; enabling it changes
only what happens to traffic *beyond* a principal's rate).  Worker- and
cluster-critical methods (heartbeats, registration, block commits) are
exempt by default: shedding those would destabilize the cluster faster
than any tenant flood.

Shed calls are audited (``security/audit.py``: principal + command +
``allowed=False``) and counted in ``Master.RpcAdmission*`` metrics; the
controller also samples its counters into the metrics history (source
``master``) so ``fsadmin report history Master.RpcAdmissionShed`` shows
the flood's shape after the fact.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from alluxio_tpu.qos import TokenBucketSet
from alluxio_tpu.utils.exceptions import ResourceExhaustedError

#: retry-after hints are clamped here: a bucket drained far below zero
#: would otherwise tell a client to go away for minutes, turning one
#: burst into a self-inflicted outage
MAX_RETRY_AFTER_S = 5.0

#: principal used when no identity is attached (NOSASL servers, raw
#: in-process calls): anonymous callers share one bucket — they are
#: indistinguishable, so they must also be un-separable rate-wise
ANONYMOUS = "(anonymous)"

#: cluster-critical methods never shed — the compiled-in floor behind
#: the ``atpu.master.rpc.admission.exempt`` conf default.  The fault-
#: injected reject drill honors this set too (when no controller is
#: configured): a rate-1.0 chaos drill must not shed worker
#: registration/heartbeats and destabilize the very cluster it
#: observes.
DEFAULT_EXEMPT = frozenset((
    "register", "heartbeat", "commit_block", "get_worker_id",
    "metrics_heartbeat", "file_system_heartbeat", "worker_heartbeat",
    "register_worker"))


class AdmissionConf:
    """Parsed ``atpu.master.rpc.admission.*`` (one read at boot)."""

    def __init__(self, *, enabled: bool = False, rate: float = 200.0,
                 burst: float = 400.0, max_principals: int = 4096,
                 exempt: tuple = ()) -> None:
        self.enabled = bool(enabled)
        self.rate = max(1e-3, float(rate))
        self.burst = max(1.0, float(burst))
        self.max_principals = max(1, int(max_principals))
        self.exempt = frozenset(exempt)

    @classmethod
    def from_conf(cls, conf) -> "AdmissionConf":
        from alluxio_tpu.conf import Keys

        exempt = tuple(
            m.strip() for m in str(conf.get(
                Keys.MASTER_RPC_ADMISSION_EXEMPT) or "").split(",")
            if m.strip())
        return cls(
            enabled=conf.get_bool(Keys.MASTER_RPC_ADMISSION_ENABLED),
            rate=conf.get_float(Keys.MASTER_RPC_ADMISSION_RATE),
            burst=conf.get_float(Keys.MASTER_RPC_ADMISSION_BURST),
            max_principals=conf.get_int(
                Keys.MASTER_RPC_ADMISSION_MAX_PRINCIPALS),
            exempt=exempt)


class _PrincipalStats:
    __slots__ = ("admitted", "shed", "last_shed_at")

    def __init__(self) -> None:
        self.admitted = 0
        self.shed = 0
        self.last_shed_at = 0.0


class AdmissionController:
    """Per-principal token-bucket gate on the master's RPC dispatch.

    ``check()`` runs on every non-exempt RPC: O(1), one lock hop in the
    bucket plus one in the stats map.  Shedding never allocates beyond
    the bounded principal maps — the whole point is that a flood cannot
    grow server state.
    """

    def __init__(self, conf: AdmissionConf, *, audit_writer=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.conf = conf
        self._audit = audit_writer
        self._clock = clock
        from collections import OrderedDict

        self._buckets = TokenBucketSet(conf.rate, conf.burst,
                                       max_keys=conf.max_principals,
                                       clock=clock)
        self._stats: "OrderedDict[str, _PrincipalStats]" = OrderedDict()
        self._stats_lock = threading.Lock()
        from alluxio_tpu.metrics import metrics

        m = metrics()
        self._c_admitted = m.counter("Master.RpcAdmissionAdmitted")
        self._c_shed = m.counter("Master.RpcAdmissionShed")
        m.register_gauge("Master.RpcAdmissionPrincipals",
                         lambda: float(len(self._buckets)))
        #: instance totals: the registry counters above are process-
        #: global (an in-process minicluster shares them across
        #: masters), so reports/history sample THESE
        self.admitted_total = 0
        self.shed_total = 0

    # ------------------------------------------------------------- gate
    def _stat(self, principal: str) -> _PrincipalStats:
        s = self._stats.get(principal)
        if s is None:
            if len(self._stats) >= self.conf.max_principals:
                # LRU-evict (insertion-ordered dict, O(1)); the stats
                # and bucket maps drift independently but both stay
                # bounded, which is what matters under a flood
                self._stats.pop(next(iter(self._stats)))
            s = self._stats[principal] = _PrincipalStats()
        else:
            self._stats.move_to_end(principal)
        return s

    def check(self, principal: Optional[str], method: str) -> None:
        """Admit or raise ``ResourceExhaustedError`` (with
        ``retry_after_s``) for one RPC."""
        if method in self.conf.exempt:
            return
        who = principal or ANONYMOUS
        ok, retry_after = self._buckets.try_acquire(who)
        if ok:
            self._c_admitted.inc()
            with self._stats_lock:
                self.admitted_total += 1
                self._stat(who).admitted += 1
            return
        retry_after = min(MAX_RETRY_AFTER_S, retry_after)
        self._c_shed.inc()
        now = self._clock()
        with self._stats_lock:
            self.shed_total += 1
            s = self._stat(who)
            s.shed += 1
            s.last_shed_at = now
        if self._audit is not None:
            from alluxio_tpu.security.audit import AuditContext

            self._audit.append(AuditContext(
                command=method, user=who, allowed=False,
                succeeded=False))
        err = ResourceExhaustedError(
            f"rpc admission: principal {who!r} is over its master RPC "
            f"rate ({self.conf.rate:g}/s, burst {self.conf.burst:g}); "
            f"retry after {retry_after:.3f}s")
        err.retry_after_s = retry_after
        raise err

    # ----------------------------------------------------------- report
    def report(self) -> dict:
        """Wire view for ``get_qos`` / ``fsadmin report qos``."""
        with self._stats_lock:
            rows = [{"principal": p, "admitted": s.admitted,
                     "shed": s.shed, "last_shed_at": s.last_shed_at}
                    for p, s in self._stats.items()]
        rows.sort(key=lambda r: (-r["shed"], -r["admitted"]))
        return {
            "enabled": self.conf.enabled,
            "rate_per_s": self.conf.rate,
            "burst": self.conf.burst,
            "max_principals": self.conf.max_principals,
            "exempt": sorted(self.conf.exempt),
            "principals": rows[:64],
            "admitted_total": self.admitted_total,
            "shed_total": self.shed_total,
            "bucket_evictions": self._buckets.evictions,
        }

    def shed_counts(self) -> Dict[str, int]:
        """principal -> shed count; the tenant-overload health rule
        diffs successive snapshots of this."""
        with self._stats_lock:
            return {p: s.shed for p, s in self._stats.items() if s.shed}

    def sample_history(self, history, now: Optional[float] = None) -> None:
        """Push the admission counters into the metrics history as
        ``master``-source series (same pattern as the remediation
        engine's ``Master.Remediation*`` samples)."""
        if history is None:
            return
        history.ingest("master", {
            "Master.RpcAdmissionAdmitted": float(self.admitted_total),
            "Master.RpcAdmissionShed": float(self.shed_total),
            "Master.RpcAdmissionPrincipals": float(len(self._buckets)),
        }, **({} if now is None else {"now": now}))
