"""Typed configuration system (reference: ``core/common/.../conf``)."""

from alluxio_tpu.conf.property_key import (  # noqa: F401
    ConsistencyLevel, Keys, KeyType, PropertyKey, REGISTRY, Scope, Template,
    Templates, parse_bytes, parse_duration_s,
)
from alluxio_tpu.conf.configuration import (  # noqa: F401
    Configuration, Source, global_configuration, reset_global_configuration,
)
