"""Layered, typed runtime configuration.

Re-design of the reference's ``conf/InstancedConfiguration.java:43`` +
``conf/AlluxioProperties.java`` + ``conf/Source.java``: values are resolved
through a priority stack of sources (RUNTIME > PATH_DEFAULT > CLUSTER_DEFAULT
> SYSTEM_PROPERTY/env > SITE_PROPERTY file > DEFAULT), every lookup is parsed
through the key's declared type, and a content hash supports the reference's
live-reconfiguration handshake (``client/file/ConfigHashSync.java:36``).
"""

from __future__ import annotations

import enum
import hashlib
import os
import threading
from typing import Any, Dict, Iterator, Optional, Tuple

from alluxio_tpu.conf.property_key import (
    REGISTRY, Keys, PropertyKey, Scope, Template,
)


class Source(enum.IntEnum):
    """Priority-ordered provenance of a config value (higher wins).
    Order mirrors the reference's ``Source.Type``: cluster defaults served
    by the master sit just above built-in defaults, so any locally-set
    site/env/runtime value beats them."""

    DEFAULT = 0
    CLUSTER_DEFAULT = 1
    SITE_PROPERTY = 2
    ENVIRONMENT = 3
    PATH_DEFAULT = 4
    RUNTIME = 5
    MOUNT_OPTION = 6


_ENV_PREFIX = "ATPU_"


def _env_to_key(env_name: str) -> str:
    # ATPU_MASTER_RPC_PORT -> atpu.master.rpc.port
    return env_name.lower().replace("_", ".")


class Configuration:
    """An instanced, layered configuration."""

    def __init__(self, initial: Optional[Dict[str, Any]] = None,
                 load_env: bool = True) -> None:
        self._lock = threading.RLock()
        # name -> (raw value, source); highest-priority source wins at get()
        self._values: Dict[str, Tuple[Any, Source]] = {}
        if load_env:
            for env_name, v in os.environ.items():
                if env_name.startswith(_ENV_PREFIX):
                    name = _env_to_key(env_name)
                    if REGISTRY.is_valid(name):
                        self._put(name, v, Source.ENVIRONMENT)
        if initial:
            for k, v in initial.items():
                self.set(k, v)

    # -- mutation -----------------------------------------------------------
    def _put(self, name: str, value: Any, source: Source) -> None:
        with self._lock:
            cur = self._values.get(name)
            if cur is None or source >= cur[1]:
                self._values[name] = (value, source)

    def set(self, key: "PropertyKey | str", value: Any,
            source: Source = Source.RUNTIME) -> None:
        # canonicalize aliases so set()/get() agree on the storage name
        self._put(self._resolve_key(key).name, value, source)

    def unset(self, key: "PropertyKey | str") -> None:
        name = self._resolve_key(key).name
        with self._lock:
            self._values.pop(name, None)

    def merge(self, props: Dict[str, Any], source: Source) -> None:
        for k, v in props.items():
            if REGISTRY.is_valid(k):
                self._put(k, v, source)

    def load_site_properties(self, path: str) -> None:
        """Load a java-properties-style ``key=value`` file."""
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                k, _, v = line.partition("=")
                k, v = k.strip(), v.strip()
                if REGISTRY.is_valid(k):
                    self._put(k, v, Source.SITE_PROPERTY)

    # -- resolution ---------------------------------------------------------
    def _resolve_key(self, key: "PropertyKey | str") -> PropertyKey:
        if isinstance(key, PropertyKey):
            return key
        pk = REGISTRY.get(str(key))
        if pk is None:
            tmpl = Template.match(str(key))
            if tmpl is not None:
                # registers the concrete key with its templated default
                import re
                m = re.fullmatch(tmpl.regex, str(key))
                return tmpl.format(*m.groups())
            raise KeyError(f"unknown property key: {key}")
        return pk

    def is_set(self, key: "PropertyKey | str") -> bool:
        pk = self._resolve_key(key)
        with self._lock:
            return pk.name in self._values or pk.default is not None

    def get(self, key: "PropertyKey | str") -> Any:
        pk = self._resolve_key(key)
        with self._lock:
            entry = self._values.get(pk.name)
        raw = entry[0] if entry is not None else pk.default
        return pk.parse(raw)

    def get_or(self, key: "PropertyKey | str", fallback: Any) -> Any:
        v = self.get(key)
        return fallback if v is None else v

    def source(self, key: "PropertyKey | str") -> Source:
        pk = self._resolve_key(key)
        with self._lock:
            entry = self._values.get(pk.name)
        return entry[1] if entry is not None else Source.DEFAULT

    # convenience typed getters
    def get_int(self, key) -> int:
        return int(self.get(key))

    def get_float(self, key) -> float:
        return float(self.get(key))

    def get_bool(self, key) -> bool:
        return bool(self.get(key))

    def get_bytes(self, key) -> int:
        return int(self.get(key))

    def get_duration_s(self, key) -> float:
        return float(self.get(key))

    def get_ms(self, key) -> int:
        return int(self.get(key) * 1000)

    def get_list(self, key) -> list:
        v = self.get(key)
        return list(v) if v else []

    # -- introspection / distribution --------------------------------------
    def items(self) -> Iterator[Tuple[str, Any, Source]]:
        with self._lock:
            snapshot = dict(self._values)
        for name, (value, source) in sorted(snapshot.items()):
            yield name, value, source

    def to_map(self, min_source: Source = Source.DEFAULT) -> Dict[str, Any]:
        """Raw values at or above a source level — used for cluster-default
        distribution from master to clients/workers
        (reference: ``meta_master.proto:196-211``)."""
        return {name: value for name, value, source in self.items()
                if source >= min_source}

    def hash(self) -> str:
        """Content hash for the live-reconfiguration handshake
        (reference: ``ConfigHashSync.java:36``)."""
        h = hashlib.md5()
        for name, value, _ in self.items():
            h.update(f"{name}={value};".encode())
        return h.hexdigest()

    def copy(self) -> "Configuration":
        c = Configuration(load_env=False)
        with self._lock:
            c._values = dict(self._values)
        return c

    def validate(self) -> None:
        """Parse every set value through its key's type; raise on error."""
        for name, value, _ in self.items():
            pk = REGISTRY.get(name)
            if pk is not None:
                pk.parse(value)


# Global process-wide configuration (reference: ServerConfiguration singleton,
# core/server/common/.../conf/ServerConfiguration.java). Tests construct their
# own Configuration instances instead.
_GLOBAL: Optional[Configuration] = None
_GLOBAL_LOCK = threading.Lock()


def global_configuration() -> Configuration:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = Configuration()
            site = os.environ.get("ATPU_SITE_PROPERTIES",
                                  "/etc/alluxio_tpu/site.properties")
            if os.path.exists(site):
                _GLOBAL.load_site_properties(site)
        return _GLOBAL


def reset_global_configuration() -> None:
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = None
