"""Typed configuration property keys.

TPU-native re-design of the reference's typed key registry
(``core/common/src/main/java/alluxio/conf/PropertyKey.java:1`` — 6254 LoC of
builder-generated keys with defaults, aliases, scopes and parameterized
templates).  Here a key is a small frozen dataclass registered in a global
catalog; parameterized families (e.g. per-tier worker settings, mirroring
``PropertyKey.Template``, ``PropertyKey.java:5668``) are `Template` factories
that mint concrete keys on demand.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


class Scope(enum.Flag):
    """Which process types consume a key (reference: ``conf/Scope.java``)."""

    MASTER = enum.auto()
    WORKER = enum.auto()
    CLIENT = enum.auto()
    JOB_MASTER = enum.auto()
    JOB_WORKER = enum.auto()
    SERVER = MASTER | WORKER | JOB_MASTER | JOB_WORKER
    ALL = SERVER | CLIENT
    NONE = 0


class ConsistencyLevel(enum.Enum):
    """Cross-cluster consistency requirement for a key's value.

    Mirrors the reference's config-consistency checking
    (``meta/checkconf/ServerConfigurationChecker.java``).
    """

    IGNORE = "IGNORE"
    WARN = "WARN"
    ENFORCE = "ENFORCE"


_DURATION_RE = re.compile(r"^\s*(-?\d+(?:\.\d+)?)\s*(ms|s|sec|m|min|h|hr|d|day)?\s*$")
_BYTES_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)\s*(b|kb|mb|gb|tb|pb|k|m|g|t|p|ki|mi|gi|ti|pi)?\s*$",
    re.I)

_DURATION_UNITS = {
    None: 0.001,  # bare numbers are milliseconds, matching the reference
    "ms": 0.001,
    "s": 1.0,
    "sec": 1.0,
    "m": 60.0,
    "min": 60.0,
    "h": 3600.0,
    "hr": 3600.0,
    "d": 86400.0,
    "day": 86400.0,
}

_BYTE_UNITS = {
    None: 1,
    "b": 1,
    # "ki/mi/gi" are the Kubernetes quantity spellings — accepted so
    # chart values flow into ATPU_* env vars verbatim
    "k": 1 << 10, "kb": 1 << 10, "ki": 1 << 10,
    "m": 1 << 20, "mb": 1 << 20, "mi": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "gi": 1 << 30,
    "t": 1 << 40, "tb": 1 << 40, "ti": 1 << 40,
    "p": 1 << 50, "pb": 1 << 50, "pi": 1 << 50,
}


def parse_duration_s(value: Any) -> float:
    """Parse ``"5s"``, ``"100ms"``, ``"1h"`` (or a bare ms count) to seconds."""
    if isinstance(value, (int, float)):
        return float(value) / 1000.0
    m = _DURATION_RE.match(str(value))
    if not m:
        raise ValueError(f"cannot parse duration: {value!r}")
    return float(m.group(1)) * _DURATION_UNITS[m.group(2)]


def parse_bytes(value: Any) -> int:
    """Parse ``"64MB"``, ``"1g"`` (or a bare byte count) to bytes."""
    if isinstance(value, int):
        return value
    if isinstance(value, float):
        return int(value)
    m = _BYTES_RE.match(str(value))
    if not m:
        raise ValueError(f"cannot parse byte size: {value!r}")
    unit = m.group(2).lower() if m.group(2) else None
    return int(float(m.group(1)) * _BYTE_UNITS[unit])


def parse_bool(value: Any) -> bool:
    if isinstance(value, bool):
        return value
    s = str(value).strip().lower()
    if s in ("true", "1", "yes", "on"):
        return True
    if s in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"cannot parse bool: {value!r}")


class KeyType(enum.Enum):
    STRING = "string"
    INT = "int"
    FLOAT = "float"
    BOOL = "bool"
    BYTES = "bytes"        # human sizes: "64MB"
    DURATION = "duration"  # human durations: "5s" -> seconds (float)
    LIST = "list"          # comma separated
    ENUM = "enum"


_PARSERS: Dict[KeyType, Callable[[Any], Any]] = {
    KeyType.STRING: str,
    KeyType.INT: lambda v: int(str(v), 0) if not isinstance(v, int) else v,
    KeyType.FLOAT: float,
    KeyType.BOOL: parse_bool,
    KeyType.BYTES: parse_bytes,
    KeyType.DURATION: parse_duration_s,
    KeyType.LIST: lambda v: list(v) if isinstance(v, (list, tuple)) else [p for p in str(v).split(",") if p],
}


@dataclass(frozen=True)
class PropertyKey:
    """One typed configuration key."""

    name: str
    key_type: KeyType = KeyType.STRING
    default: Any = None
    description: str = ""
    scope: Scope = Scope.ALL
    consistency: ConsistencyLevel = ConsistencyLevel.IGNORE
    aliases: tuple = ()
    choices: tuple = ()  # for ENUM
    dynamic: bool = False  # may be updated at runtime (live reconfiguration)
    # Mirrors the reference's DisplayType.CREDENTIALS
    # (conf/PropertyKey.java): values must be masked on every config
    # display surface (web UI, REST, shell report).
    credentials: bool = False

    def parse(self, raw: Any) -> Any:
        if raw is None:
            return None
        if self.key_type is KeyType.ENUM:
            s = str(raw).upper()
            if self.choices and s not in self.choices:
                raise ValueError(
                    f"{self.name}: invalid value {raw!r}; choices: {self.choices}")
            return s
        return _PARSERS[self.key_type](raw)

    def __str__(self) -> str:
        return self.name


class KeyRegistry:
    """Global catalog of defined keys, with alias resolution."""

    def __init__(self) -> None:
        self._keys: Dict[str, PropertyKey] = {}
        self._aliases: Dict[str, str] = {}

    def register(self, key: PropertyKey) -> PropertyKey:
        existing = self._keys.get(key.name)
        if existing is not None:
            return existing
        self._keys[key.name] = key
        for a in key.aliases:
            self._aliases[a] = key.name
        return key

    def get(self, name: str) -> Optional[PropertyKey]:
        if name in self._keys:
            return self._keys[name]
        canonical = self._aliases.get(name)
        if canonical:
            return self._keys[canonical]
        return None

    def is_valid(self, name: str) -> bool:
        return self.get(name) is not None or Template.match(name) is not None

    def all_keys(self) -> Dict[str, PropertyKey]:
        return dict(self._keys)


REGISTRY = KeyRegistry()


def _k(name: str, key_type: KeyType = KeyType.STRING, default: Any = None,
       description: str = "", scope: Scope = Scope.ALL,
       consistency: ConsistencyLevel = ConsistencyLevel.IGNORE,
       aliases: tuple = (), choices: tuple = (), dynamic: bool = False,
       credentials: bool = False) -> PropertyKey:
    return REGISTRY.register(PropertyKey(
        name=name, key_type=key_type, default=default, description=description,
        scope=scope, consistency=consistency, aliases=aliases, choices=choices,
        dynamic=dynamic, credentials=credentials))


# Defensive net for keys minted outside the registry (templates, mount
# options echoed into config): anything that LOOKS like a secret is
# treated as one on display surfaces.
_CREDENTIAL_NAME_RE = re.compile(
    r"(?i)(password|secret|token|credential|access[._-]?key|[._-]key$)")


def is_credential_key(name: str) -> bool:
    """True if ``name`` must be masked on config display surfaces."""
    pk = REGISTRY.get(name)
    if pk is not None and pk.credentials:
        return True
    return _CREDENTIAL_NAME_RE.search(name) is not None


def mask_credential(name: str, value: Any) -> Any:
    """Value as it may appear on a display surface (web UI, REST, shell):
    credential keys come back as ``******`` unless unset."""
    if is_credential_key(name) and value not in (None, "", "None"):
        return "******"
    return value


@dataclass(frozen=True)
class Template:
    """A parameterized key family, e.g. per-tier worker storage settings.

    Reference: ``conf/PropertyKey.java:5668`` (``Template`` enum with regex
    matching).  ``WORKER_TIER_DIRS_PATH.format(0)`` mints the concrete key.
    """

    pattern: str  # str.format pattern with {} placeholders
    regex: str
    key_type: KeyType = KeyType.STRING
    default_fn: Callable[..., Any] = lambda *a: None
    scope: Scope = Scope.ALL

    _ALL: "list[Template]" = field(default_factory=list, repr=False)

    def format(self, *args) -> PropertyKey:
        name = self.pattern.format(*args)
        existing = REGISTRY.get(name)
        if existing:
            return existing
        return REGISTRY.register(PropertyKey(
            name=name, key_type=self.key_type, default=self.default_fn(*args),
            scope=self.scope))

    @classmethod
    def match(cls, name: str) -> Optional["Template"]:
        for t in _TEMPLATES:
            if re.fullmatch(t.regex, name):
                return t
        return None


_TEMPLATES: list = []


def _template(pattern: str, regex: str, key_type: KeyType = KeyType.STRING,
              default_fn: Callable[..., Any] = lambda *a: None,
              scope: Scope = Scope.ALL) -> Template:
    t = Template(pattern=pattern, regex=regex, key_type=key_type,
                 default_fn=default_fn, scope=scope)
    _TEMPLATES.append(t)
    return t


# ---------------------------------------------------------------------------
# Key catalog.  Naming follows the reference's dotted style with an `atpu.`
# prefix.  Only behavior-bearing keys are defined; the catalog grows with the
# framework.
# ---------------------------------------------------------------------------

class Keys:
    # --- cluster / common ---
    CLUSTER_NAME = _k("atpu.cluster.name", default="default-cluster",
                      consistency=ConsistencyLevel.ENFORCE)
    HOME = _k("atpu.home", default="/tmp/alluxio_tpu")
    USER_BLOCK_SIZE_BYTES_DEFAULT = _k(
        "atpu.user.block.size.bytes.default", KeyType.BYTES, default="64MB",
        description="Default block size for new files "
                    "(reference: alluxio.user.block.size.bytes.default).")
    TIERED_IDENTITY = _k(
        "atpu.locality.identity", KeyType.LIST, default=None,
        description="Ordered locality tiers 'host=h,slice=s,pod=p' "
                    "(reference: wire/TieredIdentity.java:36; TPU twist: "
                    "host < ICI slice < pod < DCN).")

    # --- security (reference: core/common/.../security) ---
    SECURITY_AUTH_TYPE = _k("atpu.security.authentication.type", KeyType.ENUM,
                            default="SIMPLE", choices=("NOSASL", "SIMPLE", "CUSTOM"),
                            consistency=ConsistencyLevel.ENFORCE)
    SECURITY_LOGIN_USERNAME = _k("atpu.security.login.username")
    SECURITY_AUTHORIZATION_PERMISSION_ENABLED = _k(
        "atpu.security.authorization.permission.enabled", KeyType.BOOL, default=True)
    SECURITY_AUTHORIZATION_PERMISSION_UMASK = _k(
        "atpu.security.authorization.permission.umask", KeyType.INT, default=0o022)
    SECURITY_AUTHORIZATION_PERMISSION_SUPERGROUP = _k(
        "atpu.security.authorization.permission.supergroup", default="supergroup",
        description="Members act as superusers (reference: "
                    "alluxio.security.authorization.permission.supergroup).")
    SECURITY_LOGIN_IMPERSONATION_USERNAME = _k(
        "atpu.security.login.impersonation.username",
        description="User to act as; the connecting user must be allowed by "
                    "the master's impersonation rules.")
    SECURITY_AUTH_CUSTOM_PROVIDER = _k(
        "atpu.security.authentication.custom.provider",
        description="dotted.module:attr of an AuthenticationProvider for "
                    "CUSTOM auth (reference: AuthenticationProvider SPI).")
    SECURITY_LOGIN_TOKEN = _k(
        "atpu.security.login.token",
        description="Opaque credential forwarded to a CUSTOM provider.",
        credentials=True)

    # --- master ---
    MASTER_HOSTNAME = _k("atpu.master.hostname", default="localhost", scope=Scope.ALL)
    MASTER_RPC_PORT = _k("atpu.master.rpc.port", KeyType.INT, default=19998)
    MASTER_RPC_ADDRESSES = _k(
        "atpu.master.rpc.addresses", scope=Scope.ALL,
        description="Comma-separated master addresses for HA deployments; "
                    "overrides hostname:port when set (reference: "
                    "alluxio.master.rpc.addresses).")
    MASTER_RPC_ADMISSION_ENABLED = _k(
        "atpu.master.rpc.admission.enabled", KeyType.BOOL, default=False,
        scope=Scope.MASTER,
        description="Per-principal token-bucket admission control on "
                    "the master RPC dispatch: calls beyond a "
                    "principal's rate are shed with a typed "
                    "ResourceExhausted carrying a retry-after hint "
                    "(which the client retry policy honors) instead "
                    "of queuing in the RPC executor. Off: dispatch is "
                    "byte-identical to a build without admission "
                    "control.")
    MASTER_RPC_ADMISSION_RATE = _k(
        "atpu.master.rpc.admission.rate", KeyType.FLOAT, default=200.0,
        scope=Scope.MASTER,
        description="Sustained master RPCs per second each principal "
                    "may issue before shedding starts.")
    MASTER_RPC_ADMISSION_BURST = _k(
        "atpu.master.rpc.admission.burst", KeyType.FLOAT, default=400.0,
        scope=Scope.MASTER,
        description="Token-bucket depth per principal: how far a "
                    "principal may briefly exceed the sustained rate.")
    MASTER_RPC_ADMISSION_MAX_PRINCIPALS = _k(
        "atpu.master.rpc.admission.max.principals", KeyType.INT,
        default=4096, scope=Scope.MASTER,
        description="Bound on tracked principal buckets (the key space "
                    "is client-controlled); beyond it the least-"
                    "recently-used bucket is evicted, so a spoofed-"
                    "principal flood cannot grow master memory.")
    MASTER_RPC_ADMISSION_EXEMPT = _k(
        "atpu.master.rpc.admission.exempt", KeyType.STRING,
        default="register,heartbeat,commit_block,get_worker_id,"
                "metrics_heartbeat,file_system_heartbeat,"
                "worker_heartbeat,register_worker",
        scope=Scope.MASTER,
        description="Comma-separated RPC method names never shed: "
                    "worker registration/heartbeats and block commits "
                    "are cluster-critical — shedding them would "
                    "destabilize the cluster faster than any tenant "
                    "flood.")
    MASTER_HA_ENABLED = _k(
        "atpu.master.ha.enabled", KeyType.BOOL, default=False,
        scope=Scope.MASTER,
        description="Run the master fault-tolerant: file-lock election on "
                    "the shared journal dir, standby tailing until primacy.")
    MASTER_HA_STANDBY_READS_ENABLED = _k(
        "atpu.master.ha.standby.reads.enabled", KeyType.BOOL, default=True,
        scope=Scope.MASTER,
        description="Standby masters serve GetStatus/ListStatus/Exists "
                    "off their tailing journal apply, stamped with the "
                    "standby's own (journal-deterministic) md_version; "
                    "every other RPC is refused with a typed "
                    "NotPrimaryError carrying the current leader hint "
                    "(docs/ha.md).")
    MASTER_HA_PUBLISH_INTERVAL = _k(
        "atpu.master.ha.publish.interval", KeyType.DURATION, default="1s",
        scope=Scope.MASTER,
        description="How often an HA master publishes its row (role, "
                    "applied sequence, term) into the shared-journal "
                    "master registry backing `fsadmin report masters` "
                    "and the quorum-degraded health rule.")
    MASTER_WEB_PORT = _k("atpu.master.web.port", KeyType.INT, default=19999)
    MASTER_WEB_ENABLED = _k(
        "atpu.master.web.enabled", KeyType.BOOL, default=False,
        scope=Scope.MASTER,
        description="Serve the read-only HTTP/JSON state endpoint "
                    "(reference: AlluxioMasterRestServiceHandler).")
    MASTER_MOUNT_TABLE_ROOT_UFS = _k(
        "atpu.master.mount.table.root.ufs", default="",
        scope=Scope.MASTER,
        description="UFS URI mounted at the namespace root (reference: "
                    "alluxio.master.mount.table.root.ufs). Empty: a "
                    "local directory under atpu.home.")
    MASTER_FASTPATH_ENABLED = _k(
        "atpu.master.fastpath.enabled", KeyType.BOOL, default=True,
        scope=Scope.MASTER,
        description="Serve metadata RPCs over a same-host Unix-socket "
                    "fast path (framed msgpack, no HTTP/2) alongside "
                    "gRPC; local clients short-circuit onto it and "
                    "remote ones keep using gRPC (rpc/fastpath.py).")
    MASTER_FASTPATH_DIR = _k(
        "atpu.master.fastpath.dir", default="/tmp",
        description="Directory for the fastpath Unix socket "
                    "(atpu-master-<rpc_port>.sock); clients probe the "
                    "same conventional path.")
    MASTER_JOURNAL_TYPE = _k("atpu.master.journal.type", KeyType.ENUM,
                             default="LOCAL", choices=("LOCAL", "UFS", "EMBEDDED", "NOOP"),
                             scope=Scope.MASTER)
    MASTER_JOURNAL_FOLDER = _k("atpu.master.journal.folder",
                               default="/tmp/alluxio_tpu/journal", scope=Scope.MASTER)
    MASTER_JOURNAL_FLUSH_BATCH_TIME = _k(
        "atpu.master.journal.flush.batch.time", KeyType.DURATION, default="5ms",
        scope=Scope.MASTER,
        description="Coalescing window of the dedicated journal flusher "
                    "(group commit, reference: AsyncJournalWriter): the "
                    "flusher accumulates up to this much arrival time "
                    "into one file write + fsync; operations block only "
                    "until their batch's fsync completes. 0 flushes "
                    "every wakeup without coalescing.")
    MASTER_JOURNAL_CHECKPOINT_PERIOD_ENTRIES = _k(
        "atpu.master.journal.checkpoint.period.entries", KeyType.INT,
        default=2_000_000, scope=Scope.MASTER)
    MASTER_EMBEDDED_JOURNAL_ADDRESSES = _k(
        "atpu.master.embedded.journal.addresses", default="",
        scope=Scope.ALL,
        description="Comma-separated host:port quorum member addresses for "
                    "the EMBEDDED (Raft) journal (reference: "
                    "alluxio.master.embedded.journal.addresses).")
    MASTER_EMBEDDED_JOURNAL_ADDRESS = _k(
        "atpu.master.embedded.journal.address", default="",
        scope=Scope.MASTER,
        description="This master's own quorum address; must appear in "
                    "atpu.master.embedded.journal.addresses.")
    MASTER_EMBEDDED_JOURNAL_ELECTION_TIMEOUT_MIN = _k(
        "atpu.master.embedded.journal.election.timeout.min",
        KeyType.DURATION, default="300ms", scope=Scope.MASTER)
    MASTER_EMBEDDED_JOURNAL_ELECTION_TIMEOUT_MAX = _k(
        "atpu.master.embedded.journal.election.timeout.max",
        KeyType.DURATION, default="600ms", scope=Scope.MASTER)
    MASTER_EMBEDDED_JOURNAL_HEARTBEAT_INTERVAL = _k(
        "atpu.master.embedded.journal.heartbeat.interval",
        KeyType.DURATION, default="100ms", scope=Scope.MASTER)
    MASTER_EMBEDDED_JOURNAL_SNAPSHOT_PERIOD_ENTRIES = _k(
        "atpu.master.embedded.journal.snapshot.period.entries", KeyType.INT,
        default=100_000, scope=Scope.MASTER)
    MASTER_JOURNAL_LOG_SIZE_BYTES_MAX = _k(
        "atpu.master.journal.log.size.bytes.max", KeyType.BYTES, default="64MB",
        scope=Scope.MASTER)
    MASTER_METASTORE = _k("atpu.master.metastore", KeyType.ENUM, default="HEAP",
                          choices=("HEAP", "SQLITE", "LSM", "CACHING",
                                   "CACHING:HEAP", "CACHING:SQLITE",
                                   "CACHING:LSM"), scope=Scope.MASTER,
                          description="Inode/edge store backend (reference: "
                                      "HEAP/ROCKS/caching metastore). HEAP "
                                      "serves from dicts; SQLITE spills to "
                                      "disk; LSM is the billion-inode "
                                      "capacity backend (WAL + memtable + "
                                      "sorted runs, always caching-wrapped); "
                                      "CACHING[:backing] fronts a backing "
                                      "store with a write-back LRU.")
    MASTER_METASTORE_DIR = _k("atpu.master.metastore.dir",
                              default="/tmp/alluxio_tpu/metastore", scope=Scope.MASTER)
    MASTER_METASTORE_INODE_CACHE_MAX_SIZE = _k(
        "atpu.master.metastore.inode.cache.max.size", KeyType.INT, default=100_000,
        scope=Scope.MASTER)
    MASTER_METASTORE_LSM_MEMTABLE_BYTES = _k(
        "atpu.master.metastore.lsm.memtable.bytes", KeyType.BYTES,
        default="8MB", scope=Scope.MASTER,
        description="LSM metastore memtable cap: the in-memory write "
                    "buffer is flushed to an immutable sorted run when "
                    "its encoded size crosses this bound.")
    MASTER_METASTORE_LSM_COMPACTION_TRIGGER = _k(
        "atpu.master.metastore.lsm.compaction.trigger", KeyType.INT,
        default=4, scope=Scope.MASTER,
        description="Size-tiered compaction fan-in: merge a tier once "
                    "this many adjacent same-tier runs accumulate.")
    MASTER_METASTORE_LSM_WAL_SYNC = _k(
        "atpu.master.metastore.lsm.wal.sync", KeyType.BOOL, default=False,
        scope=Scope.MASTER,
        description="fsync the metastore WAL on every append. Off by "
                    "default: the journal is the durability source of "
                    "truth and replays over the metastore on recovery.")
    MASTER_METASTORE_COMPACTION_DEBT_RUNS = _k(
        "atpu.master.metastore.compaction.debt.runs", KeyType.INT,
        default=24, scope=Scope.MASTER,
        description="Health threshold: mean Master.MetastoreRuns above "
                    "this sustained over the rule window fires the "
                    "metastore-compaction-debt alert (compaction is "
                    "not keeping up with flushes).")
    MASTER_WORKER_TIMEOUT = _k("atpu.master.worker.timeout", KeyType.DURATION,
                               default="5min", scope=Scope.MASTER,
                               description="Silent-worker expiry "
                                           "(reference: LostWorkerDetectionHeartbeatExecutor, "
                                           "DefaultBlockMaster.java:1087).")
    MASTER_LOST_WORKER_DETECTION_INTERVAL = _k(
        "atpu.master.lost.worker.detection.interval", KeyType.DURATION, default="10s",
        scope=Scope.MASTER)
    MASTER_TTL_CHECK_INTERVAL = _k("atpu.master.ttl.check.interval",
                                   KeyType.DURATION, default="1h", scope=Scope.MASTER)
    MASTER_ACTIVE_SYNC_INTERVAL = _k(
        "atpu.master.activesync.interval", KeyType.DURATION, default="30s",
        scope=Scope.MASTER,
        description="Poll interval for active sync points (reference: "
                    "ActiveSyncManager.java:81; polling replaces iNotify).")
    MASTER_UPDATE_CHECK_ENABLED = _k(
        "atpu.master.update.check.enabled", KeyType.BOOL, default=False,
        scope=Scope.MASTER,
        description="Periodically probe for a newer release (reference "
                    "UpdateChecker.java; OFF by default here — "
                    "phone-home is opt-in).")
    MASTER_UPDATE_CHECK_URL = _k(
        "atpu.master.update.check.url", scope=Scope.MASTER,
        description="JSON document with {\"latest\": \"x.y.z\"}; point "
                    "at an internal mirror.")
    MASTER_UPDATE_CHECK_INTERVAL = _k(
        "atpu.master.update.check.interval", KeyType.DURATION,
        default="1d", scope=Scope.MASTER)
    MASTER_REPLICATION_CHECK_INTERVAL = _k(
        "atpu.master.replication.check.interval", KeyType.DURATION, default="1min",
        scope=Scope.MASTER)
    MASTER_REPLICATION_MAX_INFLIGHT = _k(
        "atpu.master.replication.max.inflight", KeyType.INT, default=256,
        scope=Scope.MASTER,
        description="Replicate/evict jobs the replication checker keeps "
                    "in flight at once; deficits beyond it wait for the "
                    "next heartbeat (counted in "
                    "Master.ReplicationJobsDeferred) — bounds job-master "
                    "load after a mass worker loss.")
    MASTER_LOST_FILES_DETECTION_INTERVAL = _k(
        "atpu.master.lost.files.detection.interval", KeyType.DURATION,
        default="5min", scope=Scope.MASTER,
        description="How often the master scans lost blocks for files "
                    "with no recoverable copy (reference: "
                    "LostFileDetector.java).")
    MASTER_BLOCK_INTEGRITY_CHECK_INTERVAL = _k(
        "atpu.master.block.integrity.check.interval", KeyType.DURATION,
        default="1h", scope=Scope.MASTER,
        description="How often the master frees blocks whose owning file "
                    "is gone (reference: BlockIntegrityChecker.java).")
    MASTER_UFS_CLEANUP_INTERVAL = _k(
        "atpu.master.ufs.cleanup.interval", KeyType.DURATION,
        default="1h", scope=Scope.MASTER,
        description="How often mounted UFSes are swept for abandoned "
                    "persist temp files (reference: UfsCleaner.java).")
    MASTER_PERSISTENCE_TEMP_TTL = _k(
        "atpu.master.persistence.temp.ttl", KeyType.DURATION,
        default="1h", scope=Scope.MASTER,
        description="Age after which an .atpu_persist.* temp file is "
                    "considered abandoned.")
    TABLE_TRANSFORM_MONITOR_INTERVAL = _k(
        "atpu.table.transform.manager.job.monitor.interval", KeyType.DURATION,
        default="10s", scope=Scope.MASTER,
        description="How often the table master polls running transform "
                    "jobs and commits completed layouts (reference: "
                    "TransformManager.java:82 heartbeat).")
    MASTER_PERSISTENCE_SCHEDULER_INTERVAL = _k(
        "atpu.master.persistence.scheduler.interval", KeyType.DURATION, default="1s",
        scope=Scope.MASTER)
    MASTER_SAFEMODE_WAIT = _k("atpu.master.safemode.wait", KeyType.DURATION,
                              default="5s", scope=Scope.MASTER,
                              description="Window after primacy during which "
                                          "client ops are rejected while workers "
                                          "re-register (reference: DefaultSafeModeManager).")
    MASTER_UFS_PATH_CACHE_CAPACITY = _k(
        "atpu.master.ufs.path.cache.capacity", KeyType.INT, default=100_000,
        scope=Scope.MASTER)
    MASTER_JOURNAL_INIT_FROM_BACKUP = _k(
        "atpu.master.journal.init.from.backup",
        description="Backup file to seed an EMPTY journal from at boot "
                    "(reference: initFromBackup, "
                    "AlluxioMasterProcess.java:173-190).")
    MASTER_STANDBY_TAIL_INTERVAL = _k(
        "atpu.master.standby.journal.tail.interval", KeyType.DURATION,
        default="1s", scope=Scope.MASTER,
        description="Standby journal tailing period (reference: "
                    "UfsJournalCheckpointThread.java:47).")
    MASTER_BACKUP_DIR = _k("atpu.master.backup.directory",
                           default="/tmp/alluxio_tpu/backups", scope=Scope.MASTER)
    MASTER_DAILY_BACKUP_ENABLED = _k("atpu.master.daily.backup.enabled",
                                     KeyType.BOOL, default=False, scope=Scope.MASTER)
    MASTER_DAILY_BACKUP_INTERVAL = _k(
        "atpu.master.daily.backup.interval", KeyType.DURATION,
        default="24h", scope=Scope.MASTER,
        description="How often the scheduled-backup heartbeat lands a "
                    "metadata backup (reference: DailyMetadataBackup's "
                    "time-of-day schedule, interval-based here).")
    MASTER_DAILY_BACKUP_RETENTION = _k(
        "atpu.master.daily.backup.retention", KeyType.INT, default=3,
        scope=Scope.MASTER,
        description="Scheduled backups kept after pruning (reference: "
                    "alluxio.master.daily.backup.files.retained).")

    # --- worker ---
    WORKER_HOSTNAME = _k("atpu.worker.hostname", default="localhost")
    WORKER_RPC_PORT = _k("atpu.worker.rpc.port", KeyType.INT, default=29999)
    WORKER_WEB_PORT = _k("atpu.worker.web.port", KeyType.INT, default=30000)
    WORKER_WEB_ENABLED = _k(
        "atpu.worker.web.enabled", KeyType.BOOL, default=False,
        scope=Scope.WORKER,
        description="Serve the worker's read-only HTTP/JSON state "
                    "endpoint (reference: AlluxioWorkerRestServiceHandler).")
    WORKER_WEB_BIND_HOST = _k(
        "atpu.worker.web.bind.host", default="0.0.0.0",
        scope=Scope.WORKER)
    WORKER_DATA_FOLDER = _k("atpu.worker.data.folder", default="/tmp/alluxio_tpu/worker")
    WORKER_RAMDISK_SIZE = _k("atpu.worker.ramdisk.size", KeyType.BYTES, default="1GB")
    WORKER_TIERED_STORE_LEVELS = _k("atpu.worker.tieredstore.levels", KeyType.INT,
                                    default=2, scope=Scope.WORKER)
    WORKER_BLOCK_HEARTBEAT_INTERVAL = _k(
        "atpu.worker.block.heartbeat.interval", KeyType.DURATION, default="1s",
        scope=Scope.WORKER)
    WORKER_ALLOCATOR_CLASS = _k("atpu.worker.allocator.class", KeyType.ENUM,
                                default="MAX_FREE",
                                choices=("MAX_FREE", "ROUND_ROBIN", "GREEDY"),
                                scope=Scope.WORKER)
    WORKER_ANNOTATOR_CLASS = _k("atpu.worker.block.annotator.class", KeyType.ENUM,
                                default="LRU", choices=("LRU", "LRFU"),
                                scope=Scope.WORKER)
    WORKER_LRFU_STEP_FACTOR = _k("atpu.worker.block.annotator.lrfu.step.factor",
                                 KeyType.FLOAT, default=0.25, scope=Scope.WORKER)
    WORKER_LRFU_ATTENUATION_FACTOR = _k(
        "atpu.worker.block.annotator.lrfu.attenuation.factor", KeyType.FLOAT,
        default=2.0, scope=Scope.WORKER)
    WORKER_MANAGEMENT_TIER_ALIGN_ENABLED = _k(
        "atpu.worker.management.tier.align.enabled", KeyType.BOOL, default=True,
        scope=Scope.WORKER)
    WORKER_MANAGEMENT_TIER_PROMOTE_ENABLED = _k(
        "atpu.worker.management.tier.promote.enabled", KeyType.BOOL, default=True,
        scope=Scope.WORKER)
    WORKER_MANAGEMENT_TASK_INTERVAL = _k(
        "atpu.worker.management.task.interval", KeyType.DURATION, default="1s",
        scope=Scope.WORKER)
    WORKER_MANAGEMENT_PROMOTE_QUOTA_PERCENT = _k(
        "atpu.worker.management.tier.promote.quota.percent", KeyType.INT, default=90,
        scope=Scope.WORKER)
    WORKER_SHM_DIR = _k("atpu.worker.shm.dir", default="/dev/shm/alluxio_tpu",
                        scope=Scope.WORKER,
                        description="Backing dir for the MEM tier; files here are "
                                    "mmap-able by same-host clients for the "
                                    "short-circuit zero-copy read path.")
    WORKER_SHM_LEASE_TTL = _k(
        "atpu.worker.shm.lease.ttl", KeyType.DURATION, default="30s",
        scope=Scope.WORKER,
        description="TTL of a client's SHM segment lease. The lease pins "
                    "the block against eviction; clients renew lazily "
                    "(shm_renew) while a segment stays mapped, and a "
                    "crashed client's pins self-expire after one TTL — "
                    "the crash-safe reclamation path needs no death "
                    "detection.")
    WORKER_SHM_MAX_LEASES = _k(
        "atpu.worker.shm.max.leases", KeyType.INT, default=1024,
        scope=Scope.WORKER,
        description="Concurrent SHM leases the worker grants before "
                    "denying shm_open (clients fall back to the remote "
                    "path) — bounds how much of the MEM tier client pins "
                    "can hold unevictable.")
    WORKER_UFS_FETCH_STRIPE_SIZE = _k(
        "atpu.worker.ufs.fetch.stripe.size", KeyType.BYTES, default="4MB",
        scope=Scope.WORKER,
        description="Stripe size for striped parallel cold UFS block "
                    "fetches; also the streaming read-through's "
                    "time-to-first-byte unit (a waiter gets its first "
                    "chunk after one stripe lands, not the whole block).")
    WORKER_UFS_FETCH_CONCURRENCY = _k(
        "atpu.worker.ufs.fetch.concurrency", KeyType.INT, default=4,
        scope=Scope.WORKER,
        description="Stripes of one block fetched concurrently. "
                    "Effective parallelism is also bounded by "
                    "atpu.worker.ufs.fetch.per.mount.limit.")
    WORKER_UFS_FETCH_PER_MOUNT_LIMIT = _k(
        "atpu.worker.ufs.fetch.per.mount.limit", KeyType.INT, default=16,
        scope=Scope.WORKER,
        description="Concurrent UFS stripe reads per mount across ALL "
                    "in-flight block fetches — the worker's connection "
                    "budget against one backing store.")
    WORKER_ASYNC_CACHE_QUEUE_MAX = _k(
        "atpu.worker.async.cache.queue.max", KeyType.INT, default=512,
        scope=Scope.WORKER,
        description="Pending passive-cache requests held before new "
                    "submissions are rejected (counted in "
                    "Worker.AsyncCacheRejected). Passive caching is "
                    "advisory; an unbounded backlog only delays it "
                    "past usefulness.")
    WORKER_ASYNC_CACHE_THREADS = _k(
        "atpu.worker.async.cache.threads", KeyType.INT, default=2,
        scope=Scope.WORKER,
        description="Worker threads draining the passive-cache queue "
                    "(reference: alluxio.worker.network.async.cache."
                    "manager.threads.max).")
    WORKER_QOS_ENABLED = _k(
        "atpu.worker.qos.enabled", KeyType.BOOL, default=False,
        scope=Scope.WORKER,
        description="Priority-class scheduling + per-tenant quotas on "
                    "the worker data plane: the per-mount UFS stripe "
                    "executors and the async cache queue drain "
                    "ON_DEMAND > ASYNC_FILL > PREFETCH (on-demand "
                    "reads overtake QUEUED background work; in-flight "
                    "work is never interrupted), and per-tenant "
                    "concurrency caps apply. Also authenticates worker "
                    "RPCs (SIMPLE metadata identity) so requests carry "
                    "a principal. Off: FIFO drain, no caps — "
                    "byte-identical to a build without QoS.")
    WORKER_UFS_FETCH_TENANT_LIMIT = _k(
        "atpu.worker.ufs.fetch.tenant.limit", KeyType.INT, default=8,
        scope=Scope.WORKER,
        description="With worker QoS on: concurrent UFS stripe tasks "
                    "one tenant (principal) may occupy per mount; "
                    "excess work is parked until the tenant frees a "
                    "slot, so one flooding tenant cannot monopolize "
                    "the per-mount connection budget. 0 = unlimited.")

    # --- client / user ---
    USER_FILE_WRITE_TYPE_DEFAULT = _k(
        "atpu.user.file.writetype.default", KeyType.ENUM, default="ASYNC_THROUGH",
        choices=("MUST_CACHE", "CACHE_THROUGH", "THROUGH", "ASYNC_THROUGH", "NONE"),
        scope=Scope.CLIENT)
    USER_FILE_READ_TYPE_DEFAULT = _k(
        "atpu.user.file.readtype.default", KeyType.ENUM, default="CACHE",
        choices=("NO_CACHE", "CACHE", "CACHE_PROMOTE"), scope=Scope.CLIENT)
    USER_FILE_REPLICATION_MIN = _k("atpu.user.file.replication.min", KeyType.INT,
                                   default=0, scope=Scope.CLIENT)
    USER_FILE_REPLICATION_MAX = _k("atpu.user.file.replication.max", KeyType.INT,
                                   default=-1, scope=Scope.CLIENT)
    USER_FILE_PASSIVE_CACHE_ENABLED = _k(
        "atpu.user.file.passive.cache.enabled", KeyType.BOOL, default=True,
        scope=Scope.CLIENT)
    USER_BLOCK_READ_POLICY = _k(
        "atpu.user.block.read.location.policy", KeyType.ENUM, default="LOCAL_FIRST",
        choices=("LOCAL_FIRST", "LOCAL_FIRST_AVOID_EVICTION", "MOST_AVAILABLE",
                 "ROUND_ROBIN", "DETERMINISTIC_HASH", "SPECIFIC_HOST"),
        scope=Scope.CLIENT)
    USER_BLOCK_WRITE_POLICY = _k(
        "atpu.user.block.write.location.policy", KeyType.ENUM, default="LOCAL_FIRST",
        choices=("LOCAL_FIRST", "LOCAL_FIRST_AVOID_EVICTION", "MOST_AVAILABLE",
                 "ROUND_ROBIN", "DETERMINISTIC_HASH", "SPECIFIC_HOST"),
        scope=Scope.CLIENT)
    USER_SHORT_CIRCUIT_ENABLED = _k("atpu.user.short.circuit.enabled", KeyType.BOOL,
                                    default=True, scope=Scope.CLIENT)
    USER_STANDBY_READS_ENABLED = _k(
        "atpu.user.standby.reads.enabled", KeyType.BOOL, default=False,
        scope=Scope.CLIENT,
        description="Route read-marked metadata RPCs (GetStatus/"
                    "ListStatus/Exists) round-robin across the standby "
                    "masters of atpu.master.rpc.addresses instead of "
                    "the primary; responses carry the standby's "
                    "md_version stamp so the client metadata cache "
                    "stays coherent (docs/ha.md).  Requires "
                    "atpu.master.ha.standby.reads.enabled on the "
                    "masters.")
    USER_STREAMING_READER_CHUNK_SIZE = _k(
        "atpu.user.streaming.reader.chunk.size.bytes", KeyType.BYTES, default="1MB",
        scope=Scope.CLIENT)
    USER_STREAMING_WRITER_CHUNK_SIZE = _k(
        "atpu.user.streaming.writer.chunk.size.bytes", KeyType.BYTES, default="1MB",
        scope=Scope.CLIENT)
    USER_REMOTE_READ_STRIPE_SIZE = _k(
        "atpu.user.remote.read.stripe.size", KeyType.BYTES, default="4MB",
        scope=Scope.CLIENT,
        description="Stripe size for parallel remote (DCN) block reads: a "
                    "read larger than one stripe is split into ranges "
                    "fetched over concurrent read_block streams across "
                    "replicas / pooled channels. 0 disables striping "
                    "(byte-identical legacy single-stream reads).")
    USER_REMOTE_READ_CONCURRENCY = _k(
        "atpu.user.remote.read.concurrency", KeyType.INT, default=4,
        scope=Scope.CLIENT,
        description="Stripes of one remote read in flight concurrently; "
                    "also bounds the pooled-channel fan-out to a single "
                    "worker.")
    USER_REMOTE_READ_WINDOW_BYTES = _k(
        "atpu.user.remote.read.window.bytes", KeyType.BYTES, default="32MB",
        scope=Scope.CLIENT,
        description="In-flight window for striped remote reads: stripes "
                    "are only issued while their offset is within this "
                    "many bytes of the consumer's drain point, capping "
                    "readahead past the contiguous frontier. 0 removes "
                    "the cap (concurrency still bounds in-flight "
                    "stripes).")
    USER_REMOTE_READ_HEDGE_QUANTILE = _k(
        "atpu.user.remote.read.hedge.quantile", KeyType.FLOAT, default=0.95,
        scope=Scope.CLIENT,
        description="A stripe outliving this latency quantile of its "
                    "worker's rolling EWMA is re-issued to another "
                    "replica/channel; first answer wins, the loser is "
                    "cancelled. 0 disables hedging.")
    USER_SHM_ENABLED = _k(
        "atpu.user.shm.enabled", KeyType.BOOL, default=True,
        scope=Scope.CLIENT,
        description="Same-host zero-copy SHM transport: when the serving "
                    "worker is co-located, the client leases the block's "
                    "MEM-tier segment (shm_open RPC), mmaps it, and reads "
                    "through a memoryview with no RPC, serialization, or "
                    "copy per read. Fallback to the remote path is "
                    "transparent (segment unavailable, lease denied, "
                    "worker restart). Off: reads are byte-identical to a "
                    "build without the subsystem.")
    USER_SHM_SEGMENT_CACHE_MAX = _k(
        "atpu.user.shm.segment.cache.max", KeyType.INT, default=64,
        scope=Scope.CLIENT,
        description="Mapped SHM segments held per client process (LRU); "
                    "evicting a segment unmaps it and releases its worker "
                    "lease. Bounds client address-space use, not "
                    "correctness — a miss re-leases on next read.")
    USER_SHM_LEASE_RENEW_FRACTION = _k(
        "atpu.user.shm.lease.renew.fraction", KeyType.FLOAT, default=0.5,
        scope=Scope.CLIENT,
        description="A cached segment whose lease has consumed this "
                    "fraction of its TTL is renewed lazily on the next "
                    "read touching it (one shm_renew RPC amortized over "
                    "many zero-copy reads).")
    USER_BATCH_READ_ENABLED = _k(
        "atpu.user.batch.read.enabled", KeyType.BOOL, default=True,
        scope=Scope.CLIENT,
        description="Scatter/gather batch reads: read_many coalesces a "
                    "batch of small same-block reads into ONE read_many "
                    "RPC landing in one preallocated buffer (one "
                    "serialize + one wire round-trip instead of N). Off: "
                    "each read is an individual RPC, byte-identical to "
                    "today's per-op path.")
    USER_BATCH_READ_MAX_OP_BYTES = _k(
        "atpu.user.batch.read.max.op.bytes", KeyType.BYTES, default="64KB",
        scope=Scope.CLIENT,
        description="Reads at or below this size are eligible for "
                    "read_many coalescing; larger ops route to the "
                    "striped remote-read path where per-op RPC cost is "
                    "already amortized.")
    USER_BATCH_READ_MAX_OPS = _k(
        "atpu.user.batch.read.max.ops", KeyType.INT, default=256,
        scope=Scope.CLIENT,
        description="Ops coalesced into one read_many RPC; a larger "
                    "batch is split into ceil(n/max) RPCs so one "
                    "response message stays bounded.")
    USER_NATIVE_FASTPATH_ENABLED = _k(
        "atpu.user.native.fastpath.enabled", KeyType.BOOL, default=True,
        scope=Scope.CLIENT,
        description="Native (C++) fastpath for assembled small-read "
                    "plans: SHM batch copies, read_many response "
                    "scatter, and stripe commits execute as one packed "
                    "op table per batch with the GIL released for the "
                    "whole call (docs/native.md). Takes effect only "
                    "when the on-demand g++ build succeeds; a missing "
                    "toolchain or any native error falls back to the "
                    "byte-identical pure-Python path and counts "
                    "Client.NativeFallbacks. Off: the client is "
                    "byte-identical to a build without the subsystem.")
    USER_TABLE_PUSHDOWN_ENABLED = _k(
        "atpu.user.table.pushdown.enabled", KeyType.BOOL, default=True,
        scope=Scope.CLIENT,
        description="Projection-aware Parquet reads (docs/table_reads.md): "
                    "the table reader parses the footer once (cached), "
                    "plans the exact column-chunk byte ranges of the "
                    "projection per row group, and executes them through "
                    "the choose_route ladder — same-host chunks as SHM "
                    "zero-copy views, small wire-crossing chunks "
                    "coalesced into read_many batches, large chunks as "
                    "striped reads — with decode of row group k "
                    "overlapped against transfer of k+1. Off: reads go "
                    "through the legacy seek+read pyarrow path, "
                    "byte-identical to a build without the subsystem.")
    USER_TABLE_PIPELINE_DEPTH = _k(
        "atpu.user.table.pipeline.depth", KeyType.INT, default=2,
        scope=Scope.CLIENT,
        description="Row groups in flight ahead of the decoder in the "
                    "planned table-read pipeline: transfer of row group "
                    "k+depth is issued while k decodes, so decode time "
                    "hides under transfer time. 1 serializes transfer "
                    "and decode (no overlap); the depth bounds buffered "
                    "row-group bytes.")
    USER_TABLE_READ_PARALLELISM = _k(
        "atpu.user.table.read.parallelism", KeyType.INT, default=4,
        scope=Scope.CLIENT,
        description="Files a multi-file projection (read_columns over a "
                    "partitioned table) opens/plans/reads concurrently: "
                    "partition-spanning projections overlap their footer "
                    "fetches and row-group pipelines instead of running "
                    "file-serial. 1 restores the serial loop.")
    USER_TABLE_COALESCE_SLACK_BYTES = _k(
        "atpu.user.table.coalesce.slack.bytes", KeyType.BYTES,
        default="256KB", scope=Scope.CLIENT,
        description="Adjacent planned column-chunk ranges whose gap is "
                    "at or under this slack merge into one read — the "
                    "discarded gap bytes buy fewer round trips (gap "
                    "bytes are fetched and dropped). 0 never merges "
                    "across a gap (only touching ranges coalesce).")
    USER_TABLE_FOOTER_CACHE_MAX = _k(
        "atpu.user.table.footer.cache.max", KeyType.INT, default=256,
        scope=Scope.CLIENT,
        description="Parsed Parquet footers held per client process "
                    "(LRU), keyed on path + metadata version so a "
                    "rewritten file re-parses: a warm projection re-plans "
                    "from the cache with zero footer I/O.")
    USER_TABLE_FOOTER_READ_BYTES = _k(
        "atpu.user.table.footer.read.bytes", KeyType.BYTES, default="64KB",
        scope=Scope.CLIENT,
        description="First-guess tail read for a Parquet footer: one "
                    "range read of this many bytes replaces pyarrow's "
                    "probe-seek sequence of tiny reads; a footer larger "
                    "than the guess costs exactly one more ranged read "
                    "(sized from the footer-length trailer).")
    USER_QOS_STRIPE_LIMIT = _k(
        "atpu.user.qos.stripe.limit", KeyType.INT, default=0,
        scope=Scope.CLIENT,
        description="Per-tenant cap on concurrent remote-read stripe "
                    "streams (including hedges) across every striped "
                    "read this client process runs — keeps one "
                    "tenant's DCN fan-out from monopolizing a shared "
                    "client (FUSE mount, proxy). The frontier stripe "
                    "of each read always proceeds, so the cap shapes "
                    "readahead and hedging, never liveness. "
                    "0 = unlimited (today's behavior).")
    USER_CLIENT_CACHE_ENABLED = _k("atpu.user.client.cache.enabled", KeyType.BOOL,
                                   default=False, scope=Scope.CLIENT)
    USER_CLIENT_CACHE_SIZE = _k("atpu.user.client.cache.size", KeyType.BYTES,
                                default="512MB", scope=Scope.CLIENT)
    USER_CLIENT_CACHE_PAGE_SIZE = _k("atpu.user.client.cache.page.size",
                                     KeyType.BYTES, default="1MB", scope=Scope.CLIENT)
    USER_CLIENT_CACHE_DIR = _k("atpu.user.client.cache.dir",
                               default="/tmp/alluxio_tpu/client_cache",
                               scope=Scope.CLIENT)
    USER_CLIENT_CACHE_EVICTOR = _k("atpu.user.client.cache.evictor.class",
                                   KeyType.ENUM, default="LRU",
                                   choices=("LRU", "LFU"), scope=Scope.CLIENT)
    USER_CLIENT_CACHE_HBM_SIZE = _k(
        "atpu.user.client.cache.hbm.size", KeyType.BYTES, default="0",
        scope=Scope.CLIENT,
        description="Capacity of the HBM page-cache tier (pages as jax.Array). "
                    "0 disables the device tier. TPU-native addition; no "
                    "reference analogue.")
    USER_METADATA_CACHE_ENABLED = _k(
        "atpu.user.metadata.cache.enabled", KeyType.BOOL, default=False,
        scope=Scope.CLIENT,
        description="Cache GetStatus/ListStatus results client-side in a "
                    "bounded LRU kept coherent by master-pushed "
                    "invalidations on the metrics heartbeat (plus the "
                    "expiration-time TTL as a fallback bound) — warm "
                    "metadata reads become client-local. See "
                    "docs/metadata.md.")
    USER_METADATA_CACHE_MAX_SIZE = _k("atpu.user.metadata.cache.max.size",
                                      KeyType.INT, default=10_000,
                                      scope=Scope.CLIENT,
                                      description="Entry cap of the client "
                                                  "metadata cache (LRU).")
    USER_METADATA_CACHE_EXPIRATION_TIME = _k(
        "atpu.user.metadata.cache.expiration.time", KeyType.DURATION, default="10min",
        scope=Scope.CLIENT)
    USER_CONF_CLUSTER_DEFAULT_ENABLED = _k(
        "atpu.user.conf.cluster.default.enabled", KeyType.BOOL, default=True,
        description="Pull cluster-default configuration from the master at "
                    "client start (reference: meta_master.proto:196-211).")
    USER_CONF_SYNC_INTERVAL = _k("atpu.user.conf.sync.interval", KeyType.DURATION,
                                 default="1min", scope=Scope.CLIENT)
    PROXY_WEB_PORT = _k(
        "atpu.proxy.web.port", KeyType.INT, default=39999,
        scope=Scope.SERVER,
        description="Port for the REST/S3 proxy process (reference: "
                    "proxy/AlluxioProxy.java).")
    LOGSERVER_PORT = _k(
        "atpu.logserver.port", KeyType.INT, default=45600,
        scope=Scope.ALL,
        description="Port of the centralized log server (reference: "
                    "logserver/AlluxioLogServer.java).")
    LOGSERVER_HOSTNAME = _k(
        "atpu.logserver.hostname", KeyType.STRING, default="",
        scope=Scope.ALL,
        description="When set, processes ship their log records to this "
                    "log server.")
    LOGSERVER_LOGS_DIR = _k(
        "atpu.logserver.logs.dir", KeyType.STRING,
        default="/var/log/alluxio-tpu", scope=Scope.SERVER)
    LOGSERVER_BIND_HOST = _k(
        "atpu.logserver.bind.host", KeyType.STRING, default="127.0.0.1",
        scope=Scope.SERVER,
        description="Bind address for the log server; the record stream "
                    "carries no authentication, so the default is "
                    "loopback.")
    MASTER_WEB_BIND_HOST = _k(
        "atpu.master.web.bind.host", KeyType.STRING, default="0.0.0.0",
        scope=Scope.MASTER,
        description="Bind address for the read-only master web/REST "
                    "endpoint.")
    PROXY_BIND_HOST = _k(
        "atpu.proxy.bind.host", KeyType.STRING, default="127.0.0.1",
        scope=Scope.SERVER,
        description="Bind address for the S3 proxy. The S3 dialect "
                    "carries no authentication, so the default is "
                    "loopback; set 0.0.0.0 only behind a trusted "
                    "network boundary.")
    PROXY_S3_ROOT = _k(
        "atpu.proxy.s3.root", KeyType.STRING, default="/s3",
        scope=Scope.SERVER,
        description="Namespace directory whose children are S3 buckets.")
    FUSE_MOUNT_POINT = _k(
        "atpu.fuse.mount.point", KeyType.STRING,
        default="/mnt/alluxio-tpu", scope=Scope.CLIENT,
        description="Local path where the FUSE adapter mounts the "
                    "namespace (reference: fuse/AlluxioFuse.java).")
    FUSE_FS_ROOT = _k(
        "atpu.fuse.fs.root", KeyType.STRING, default="/",
        scope=Scope.CLIENT,
        description="Namespace subtree exposed at the mount point.")
    FUSE_MOUNT_OPTIONS = _k(
        "atpu.fuse.mount.options", KeyType.STRING, default="",
        scope=Scope.CLIENT,
        description="Extra -o mount options (e.g. allow_other).")
    TRACE_ENABLED = _k(
        "atpu.trace.enabled", KeyType.BOOL, default=False,
        scope=Scope.ALL,
        description="Record RPC/operation spans into the in-process "
                    "trace ring (served at /api/v1/master/trace). "
                    "Spans carry a W3C-traceparent context across RPC "
                    "hops, so client/worker/master spans stitch into "
                    "one trace.")
    TRACE_SAMPLE_RATE = _k(
        "atpu.trace.sample.rate", KeyType.FLOAT, default=1.0,
        scope=Scope.ALL,
        description="Probability a NEW root trace is recorded (0..1). "
                    "Child spans — local and remote — inherit the "
                    "root's decision, so traces never tear.")
    TRACE_RING_CAPACITY = _k(
        "atpu.trace.ring.capacity", KeyType.INT, default=4096,
        scope=Scope.ALL,
        description="Completed spans retained per process (oldest "
                    "evicted first). Workers/clients drain the ring to "
                    "the master on the metrics heartbeat.")
    PROFILE_ENABLED = _k(
        "atpu.profile.enabled", KeyType.BOOL, default=False,
        scope=Scope.ALL,
        description="Run the sampling thread-stack profiler "
                    "(utils/profiler.py): a daemon thread periodically "
                    "snapshots every thread's Python stack and merges "
                    "them into flame-graph counts, shipped to the "
                    "master on the metrics heartbeat. Off by default — "
                    "the read path must stay byte-identical when "
                    "profiling is disabled.")
    PROFILE_SAMPLE_INTERVAL_MS = _k(
        "atpu.profile.sample.interval.ms", KeyType.INT, default=97,
        scope=Scope.ALL,
        description="Milliseconds between stack samples. A prime-ish "
                    "default avoids beating against periodic work. "
                    "Each wake forces a GIL handoff against whatever "
                    "thread is running (~1ms observed), so the cost is "
                    "per-wake, not per-stack: ~10Hz keeps the tax "
                    "under the 2% obs-profile-overhead gate while "
                    "still resolving hot paths over a heartbeat "
                    "window.")
    PROFILE_MAX_STACKS = _k(
        "atpu.profile.max.stacks", KeyType.INT, default=2048,
        scope=Scope.ALL,
        description="Distinct merged stacks retained per process; "
                    "when full, new stacks are dropped (the hot paths "
                    "are by definition already in the table).")
    PROFILE_STACK_DEPTH = _k(
        "atpu.profile.stack.depth", KeyType.INT, default=24,
        scope=Scope.ALL,
        description="Frames kept per sampled stack, innermost first — "
                    "deeper frames are truncated to bound sample cost "
                    "and wire size.")
    MASTER_METRICS_MAX_SOURCES = _k(
        "atpu.master.metrics.max.sources", KeyType.INT, default=4096,
        scope=Scope.MASTER,
        description="Distinct reporting sources the master's metrics "
                    "store accepts; reports from new sources beyond it "
                    "are dropped (counted in "
                    "Master.MetricsReportsDropped) — bounds memory "
                    "against spoofed source-name floods.")
    MASTER_METRICS_HISTORY_ENABLED = _k(
        "atpu.master.metrics.history.enabled", KeyType.BOOL, default=True,
        scope=Scope.MASTER,
        description="Keep bounded per-(source, metric) time series of "
                    "the metric snapshots arriving on the metrics "
                    "heartbeat (raw + 1m/10m rollups), served at "
                    "/api/v1/master/metrics/history and `fsadmin "
                    "report history`.")
    MASTER_METRICS_HISTORY_CAPACITY = _k(
        "atpu.master.metrics.history.capacity", KeyType.INT, default=360,
        scope=Scope.MASTER,
        description="Samples retained per series per resolution (raw, "
                    "1m, 10m) — oldest evicted first. Total history "
                    "memory is bounded by max.series x 3 x capacity "
                    "points.")
    MASTER_METRICS_HISTORY_RETENTION = _k(
        "atpu.master.metrics.history.retention", KeyType.DURATION,
        default="1h", scope=Scope.MASTER,
        description="Raw samples older than this are pruned (1m "
                    "rollups keep 10x, 10m rollups 60x, still capped "
                    "by capacity).")
    MASTER_METRICS_HISTORY_MAX_SERIES = _k(
        "atpu.master.metrics.history.max.series", KeyType.INT,
        default=4096, scope=Scope.MASTER,
        description="Hard cap on distinct (source, metric) series; "
                    "samples for series beyond it (or outside the "
                    "prefix allowlist) are dropped and counted in "
                    "Master.MetricsHistorySamplesDropped — bounds "
                    "memory against metric-name cardinality floods.")
    MASTER_METRICS_HISTORY_ALLOW_PREFIXES = _k(
        "atpu.master.metrics.history.allow.prefixes", KeyType.STRING,
        default="Cluster.,Master.,Worker.,Client.,JobMaster.,"
                "JobWorker.,Process.",
        scope=Scope.MASTER,
        description="Comma-separated metric-name prefixes admitted "
                    "into the history store; anything else (e.g. a "
                    "spoofed-name flood) is dropped before it can "
                    "mint a series.")
    MASTER_HEALTH_ENABLED = _k(
        "atpu.master.health.enabled", KeyType.BOOL, default=True,
        scope=Scope.MASTER,
        description="Continuously evaluate the declarative health "
                    "rules (cluster doctor) over the metrics history; "
                    "verdicts at /api/v1/master/health and `fsadmin "
                    "report health`.")
    MASTER_HEALTH_EVAL_INTERVAL = _k(
        "atpu.master.health.eval.interval", KeyType.DURATION,
        default="10s", scope=Scope.MASTER,
        description="Period of the master's health-rule evaluation "
                    "heartbeat.")
    MASTER_HEALTH_STALL_THRESHOLD = _k(
        "atpu.master.health.stall.threshold", KeyType.FLOAT, default=0.5,
        scope=Scope.MASTER,
        description="InputBoundFraction above this (sustained over the "
                    "stall window) fires the input-stall alert.")
    MASTER_HEALTH_STALL_WINDOW = _k(
        "atpu.master.health.stall.window", KeyType.DURATION,
        default="60s", scope=Scope.MASTER,
        description="Evidence window the input-stall rule averages "
                    "over.")
    MASTER_HEALTH_METADATA_LOCK_WAIT_THRESHOLD = _k(
        "atpu.master.health.metadata.lock.wait.threshold",
        KeyType.DURATION, default="50ms", scope=Scope.MASTER,
        description="metadata-lock-contention rule: fire when the "
                    "master's inode-lock acquisition p99 "
                    "(Master.MetadataInodeLockWaitTime.p99) stays above "
                    "this over the stall window — sustained path-lock "
                    "contention on the metadata control plane.")
    MASTER_HEALTH_FIRE_AFTER = _k(
        "atpu.master.health.fire.after", KeyType.DURATION, default="30s",
        scope=Scope.MASTER,
        description="Debounce: a rule must stay violated this long "
                    "before its alert moves pending -> firing.")
    MASTER_HEALTH_RESOLVE_AFTER = _k(
        "atpu.master.health.resolve.after", KeyType.DURATION,
        default="60s", scope=Scope.MASTER,
        description="Debounce: a firing alert must stay clean this "
                    "long before it resolves.")
    MASTER_REMEDIATION_ENABLED = _k(
        "atpu.master.remediation.enabled", KeyType.BOOL, default=False,
        scope=Scope.MASTER,
        description="Act on firing health alerts with bounded, audited "
                    "remediations (quarantine, targeted re-replication, "
                    "client retuning pushed on the metrics heartbeat). "
                    "OFF by default: with it off the cluster behaves "
                    "exactly as if the engine did not exist. See "
                    "docs/self_healing.md.")
    MASTER_REMEDIATION_DRY_RUN = _k(
        "atpu.master.remediation.dry.run", KeyType.BOOL, default=False,
        scope=Scope.MASTER,
        description="Evaluate and AUDIT every remediation the engine "
                    "would take without executing any of them — the "
                    "recommended first week of production rollout.")
    MASTER_REMEDIATION_MAX_ACTIONS_PER_WINDOW = _k(
        "atpu.master.remediation.max.actions.per.window", KeyType.INT,
        default=4, scope=Scope.MASTER,
        description="Hard cap on remediation actions (executed or "
                    "dry-run) per sliding window; further actions are "
                    "suppressed-but-audited. A runaway rule can "
                    "quarantine at most this many workers per window.")
    MASTER_REMEDIATION_WINDOW = _k(
        "atpu.master.remediation.window", KeyType.DURATION,
        default="10min", scope=Scope.MASTER,
        description="Sliding window the action cap counts over.")
    MASTER_REMEDIATION_COOLDOWN = _k(
        "atpu.master.remediation.cooldown", KeyType.DURATION,
        default="5min", scope=Scope.MASTER,
        description="Minimum spacing between two actions of the same "
                    "kind on the same subject — a flapping alert cannot "
                    "thrash quarantine/release or re-replicate the same "
                    "worker's blocks in a loop.")
    MASTER_REMEDIATION_PROBATION = _k(
        "atpu.master.remediation.probation", KeyType.DURATION,
        default="60s", scope=Scope.MASTER,
        description="After the triggering alert resolves, a quarantined "
                    "worker (or pushed tuning overlay) is held this much "
                    "longer before release/revert — resolution debounce "
                    "on the action side.")
    MASTER_REMEDIATION_REREPLICATE_BLOCKS = _k(
        "atpu.master.remediation.rereplicate.blocks", KeyType.INT,
        default=8, scope=Scope.MASTER,
        description="Hottest blocks (top-tier residents) re-replicated "
                    "off a worker per re-replication action.")
    MASTER_REMEDIATION_QUARANTINE_MAX_FRACTION = _k(
        "atpu.master.remediation.quarantine.max.fraction", KeyType.FLOAT,
        default=0.5, scope=Scope.MASTER,
        description="Healthy-capacity floor: at most this fraction of "
                    "registered workers (min 1) may be quarantined at "
                    "once — a systemic condition that flags the whole "
                    "fleet must not let the engine empty the placement "
                    "set and amplify the outage.")
    METRICS_SINKS = _k(
        "atpu.metrics.sinks", KeyType.STRING, default="",
        scope=Scope.ALL,
        description="Comma-separated metric sinks to start (console, "
                    "csv, jsonl, graphite) — reference: "
                    "metrics/sink/*Sink.java.")
    METRICS_SINK_INTERVAL = _k(
        "atpu.metrics.sink.interval", KeyType.DURATION, default="10s",
        scope=Scope.ALL)
    METRICS_SINK_CSV_DIR = _k(
        "atpu.metrics.sink.csv.dir", KeyType.STRING,
        default="/tmp/atpu-metrics", scope=Scope.ALL,
        description="Directory for the CSV sink (one file per metric).")
    METRICS_SINK_JSONL_PATH = _k(
        "atpu.metrics.sink.jsonl.path", KeyType.STRING,
        default="/tmp/atpu-metrics/metrics.jsonl", scope=Scope.ALL)
    METRICS_SINK_GRAPHITE_ADDRESS = _k(
        "atpu.metrics.sink.graphite.address", KeyType.STRING,
        default="", scope=Scope.ALL,
        description="host:port of the Graphite/Carbon plaintext "
                    "listener (reference: metrics/sink/"
                    "GraphiteSink.java).")
    METRICS_SINK_GRAPHITE_PREFIX = _k(
        "atpu.metrics.sink.graphite.prefix", KeyType.STRING,
        default="alluxio-tpu", scope=Scope.ALL)
    METRICS_SINK_GRAPHITE_TIMEOUT = _k(
        "atpu.metrics.sink.graphite.timeout", KeyType.DURATION,
        default="5s", scope=Scope.ALL,
        description="Connect/send deadline for the Graphite sink. The "
                    "send also runs on a dedicated sender thread, so a "
                    "dead carbon host can never stall the shared "
                    "metrics-sink heartbeat.")
    USER_METRICS_COLLECTION_ENABLED = _k(
        "atpu.user.metrics.collection.enabled", KeyType.BOOL, default=False,
        scope=Scope.CLIENT,
        description="Ship client metric snapshots to the master for "
                    "cluster aggregation (reference: ClientMasterSync).")
    USER_METRICS_HEARTBEAT_INTERVAL = _k(
        "atpu.user.metrics.heartbeat.interval", KeyType.DURATION,
        default="10s", scope=Scope.CLIENT)
    WORKER_METRICS_HEARTBEAT_INTERVAL = _k(
        "atpu.worker.metrics.heartbeat.interval", KeyType.DURATION,
        default="10s", scope=Scope.WORKER,
        description="Cadence of worker metric snapshots shipped to the "
                    "master for cluster aggregation.")
    USER_FILE_METADATA_SYNC_INTERVAL = _k(
        "atpu.user.file.metadata.sync.interval", KeyType.DURATION, default="-1s",
        scope=Scope.CLIENT,
        description="-1 = never sync on access, 0 = always, >0 = min interval "
                    "(reference: common options sync interval, InodeSyncStream).")
    USER_BLOCK_WRITE_UNAVAILABLE_WINDOW = _k(
        "atpu.user.block.write.unavailable.window", KeyType.DURATION,
        default="15s", scope=Scope.CLIENT,
        description="How long a block write waits for a live worker before "
                    "failing. Covers the transient window where the only "
                    "worker missed heartbeats (host overload) and is "
                    "re-registering; 0 fails immediately (reference: client "
                    "UnavailableException retry on write).")
    USER_RPC_RETRY_MAX_DURATION = _k(
        "atpu.user.rpc.retry.max.duration", KeyType.DURATION,
        default="30s", scope=Scope.CLIENT,
        aliases=("atpu.user.rpc.retry.duration",),
        description="Wall-clock budget a client RPC retries transient "
                    "errors within before giving up (reference: "
                    "alluxio.user.rpc.retry.max.duration). The 30s "
                    "default matches the previously hard-coded client "
                    "behavior; overload drills shorten it so flooded "
                    "clients fail fast instead of piling 30s of "
                    "backoff behind a shedding master.")
    USER_RPC_RETRY_BASE_SLEEP = _k("atpu.user.rpc.retry.base.sleep", KeyType.DURATION,
                                   default="50ms", scope=Scope.CLIENT)
    USER_RPC_RETRY_MAX_SLEEP = _k("atpu.user.rpc.retry.max.sleep", KeyType.DURATION,
                                  default="3s", scope=Scope.CLIENT)

    # --- job service ---
    JOB_MASTER_HOSTNAME = _k("atpu.job.master.hostname", default="localhost")
    JOB_MASTER_RPC_PORT = _k("atpu.job.master.rpc.port", KeyType.INT, default=20001)
    JOB_MASTER_JOB_CAPACITY = _k("atpu.job.master.job.capacity", KeyType.INT,
                                 default=100_000, scope=Scope.JOB_MASTER)
    JOB_MASTER_WORKER_TIMEOUT = _k("atpu.job.master.worker.timeout",
                                   KeyType.DURATION, default="1min",
                                   scope=Scope.JOB_MASTER)
    JOB_MASTER_LOST_WORKER_INTERVAL = _k(
        "atpu.job.master.lost.worker.interval", KeyType.DURATION,
        default="10s", scope=Scope.JOB_MASTER)
    JOB_WORKER_THREADPOOL_SIZE = _k("atpu.job.worker.threadpool.size", KeyType.INT,
                                    default=8, scope=Scope.JOB_WORKER)
    JOB_WORKER_HEARTBEAT_INTERVAL = _k("atpu.job.worker.heartbeat.interval",
                                       KeyType.DURATION, default="1s",
                                       scope=Scope.JOB_WORKER)

    # --- clairvoyant prefetch service (prefetch/; NoPFS arxiv 2101.08734,
    #     Hoard arxiv 1812.00669 — no reference analogue) ---
    PREFETCH_ENABLED = _k(
        "atpu.prefetch.enabled", KeyType.BOOL, default=False,
        scope=Scope.CLIENT, aliases=("prefetch.enabled",),
        description="Run the clairvoyant prefetch control loop (oracle "
                    "-> scheduler -> agent) for seeded-shuffle reads. "
                    "Off: the loader's behavior is byte-identical to a "
                    "build without the subsystem.")
    PREFETCH_LOOKAHEAD_BLOCKS = _k(
        "atpu.prefetch.lookahead.blocks", KeyType.INT, default=16,
        scope=Scope.CLIENT, aliases=("prefetch.lookahead.blocks",),
        description="How many future accesses (per the oracle's exact "
                    "order, across epoch boundaries) the scheduler "
                    "plans placements for each tick.")
    PREFETCH_BUDGET_BYTES = _k(
        "atpu.prefetch.budget.bytes", KeyType.BYTES, default="256MB",
        scope=Scope.CLIENT, aliases=("prefetch.budget.bytes",),
        description="Ceiling on prefetched-ahead bytes (issued + ready, "
                    "not yet consumed) across all tiers; the planner "
                    "stops at the nearest-deadline block that no longer "
                    "fits (backpressure).")
    PREFETCH_HBM_FRACTION = _k(
        "atpu.prefetch.hbm.fraction", KeyType.FLOAT, default=0.25,
        scope=Scope.CLIENT, aliases=("prefetch.hbm.fraction",),
        description="Slice of the budget placed directly into the HBM "
                    "tier (device-resident jax.Array); the rest goes to "
                    "worker DRAM. Effective only when a loader with an "
                    "HBM store is bound.")
    PREFETCH_HEARTBEAT_INTERVAL = _k(
        "atpu.prefetch.heartbeat.interval.ms", KeyType.DURATION,
        default="100ms", scope=Scope.CLIENT,
        aliases=("prefetch.heartbeat.interval.ms",),
        description="Agent tick cadence: completions are observed and "
                    "the next placement plan issued once per tick.")

    # --- TPU / HBM data path (native additions) ---
    TPU_PREFETCH_BUFFER_BATCHES = _k("atpu.tpu.prefetch.buffer.batches", KeyType.INT,
                                     default=2,
                                     description="Device-side double-buffering depth "
                                                 "for the zero-copy iterator.")

    # --- fault injection (chaos / self-healing tests; see utils/faults.py)
    DEBUG_FAULT_READ_LATENCY = _k(
        "atpu.debug.fault.read.latency", KeyType.DURATION, default="0ms",
        scope=Scope.WORKER,
        description="FAULT INJECTION (tests/chaos only): extra latency "
                    "added to every warm read_block chunk this worker "
                    "serves — inflates Worker.ReadBlockTime so the p99 "
                    "regression rule can be exercised end to end.")
    DEBUG_FAULT_HEARTBEAT_FREEZE = _k(
        "atpu.debug.fault.worker.heartbeat.freeze", KeyType.BOOL,
        default=False, scope=Scope.WORKER,
        description="FAULT INJECTION (tests/chaos only): the worker "
                    "silently skips its metrics heartbeats — drives the "
                    "heartbeat-staleness rule without killing the "
                    "process.")
    DEBUG_FAULT_UFS_ERROR_RATE = _k(
        "atpu.debug.fault.ufs.error.rate", KeyType.FLOAT, default=0.0,
        scope=Scope.WORKER,
        description="FAULT INJECTION (tests/chaos only): deterministic "
                    "fraction (0..1) of UFS stripe reads that fail with "
                    "an injected IOError.")
    DEBUG_FAULT_RPC_REJECT_RATE = _k(
        "atpu.debug.fault.rpc.reject.rate", KeyType.FLOAT, default=0.0,
        scope=Scope.ALL,
        description="FAULT INJECTION (tests/chaos only): deterministic "
                    "fraction (0..1) of RPC dispatches shed with the "
                    "same typed ResourceExhausted + retry-after the "
                    "admission controller emits — drills shedding and "
                    "client retry-after honoring without a real "
                    "flood. The fault scope matches the RPC's "
                    "service.method key.")
    DEBUG_FAULT_SHM_MAP_ERROR_RATE = _k(
        "atpu.debug.fault.shm.map.error.rate", KeyType.FLOAT, default=0.0,
        scope=Scope.CLIENT,
        description="FAULT INJECTION (tests/chaos only): deterministic "
                    "fraction (0..1) of client SHM segment maps that "
                    "fail with an injected OSError — drills the "
                    "SHM->remote transparent-fallback path.")
    DEBUG_FAULT_SHM_LEASE_DENY_RATE = _k(
        "atpu.debug.fault.shm.lease.deny.rate", KeyType.FLOAT, default=0.0,
        scope=Scope.WORKER,
        description="FAULT INJECTION (tests/chaos only): deterministic "
                    "fraction (0..1) of worker shm_open lease grants "
                    "denied as if the lease table were full — drills "
                    "lease-denied fallback without filling "
                    "atpu.worker.shm.max.leases.")
    DEBUG_FAULT_NATIVE_EXEC_ERROR_RATE = _k(
        "atpu.debug.fault.native.exec.error.rate", KeyType.FLOAT,
        default=0.0, scope=Scope.CLIENT,
        description="FAULT INJECTION (tests/chaos only): deterministic "
                    "fraction (0..1) of native fastpath batches that "
                    "fail mid-table (one op poisoned, earlier ops "
                    "really write) — drills the byte-identical "
                    "fallback to the pure-Python read path.")
    DEBUG_FAULT_SCOPE = _k(
        "atpu.debug.fault.scope", KeyType.STRING, default="",
        scope=Scope.WORKER,
        description="Substring a node's locality host / metrics source "
                    "must contain for the atpu.debug.fault.* hooks to "
                    "apply; empty = every node that loaded the conf "
                    "(in-process miniclusters share one injector).")


# Parameterized families (reference: PropertyKey.Template, PropertyKey.java:5668)
class Templates:
    WORKER_TIER_ALIAS = _template(
        "atpu.worker.tieredstore.level{}.alias",
        r"atpu\.worker\.tieredstore\.level(\d+)\.alias",
        KeyType.STRING, lambda lvl: {0: "MEM", 1: "SSD", 2: "HDD"}.get(int(lvl)),
        Scope.WORKER)
    WORKER_TIER_DIRS_PATH = _template(
        "atpu.worker.tieredstore.level{}.dirs.path",
        r"atpu\.worker\.tieredstore\.level(\d+)\.dirs\.path",
        KeyType.LIST, lambda lvl: None, Scope.WORKER)
    WORKER_TIER_DIRS_QUOTA = _template(
        "atpu.worker.tieredstore.level{}.dirs.quota",
        r"atpu\.worker\.tieredstore\.level(\d+)\.dirs\.quota",
        KeyType.LIST, lambda lvl: None, Scope.WORKER)
    WORKER_TIER_HIGH_WATERMARK = _template(
        "atpu.worker.tieredstore.level{}.watermark.high.ratio",
        r"atpu\.worker\.tieredstore\.level(\d+)\.watermark\.high\.ratio",
        KeyType.FLOAT, lambda lvl: 0.95, Scope.WORKER)
    WORKER_TIER_LOW_WATERMARK = _template(
        "atpu.worker.tieredstore.level{}.watermark.low.ratio",
        r"atpu\.worker\.tieredstore\.level(\d+)\.watermark\.low\.ratio",
        KeyType.FLOAT, lambda lvl: 0.7, Scope.WORKER)
    MASTER_MOUNT_TABLE_OPTION = _template(
        "atpu.master.mount.table.{}.option.{}",
        r"atpu\.master\.mount\.table\.(\w+)\.option\.(.+)",
        KeyType.STRING, lambda *_: None, Scope.MASTER)
    MASTER_IMPERSONATION_USERS = _template(
        "atpu.master.security.impersonation.{}.users",
        r"atpu\.master\.security\.impersonation\.([^.]+)\.users",
        KeyType.LIST, lambda *_: None, Scope.MASTER)
    MASTER_IMPERSONATION_GROUPS = _template(
        "atpu.master.security.impersonation.{}.groups",
        r"atpu\.master\.security\.impersonation\.([^.]+)\.groups",
        KeyType.LIST, lambda *_: None, Scope.MASTER)
