"""Single-file HTML report for stress-suite results.

Re-design of the reference's stress graph generation
(``stress/common/.../graph/*`` — it renders JSON summaries to HTML
graphs): ``render_report`` turns the ``BENCH_SUITE.json`` records into
one self-contained page — a KPI row of headline numbers, one
horizontal bar chart per unit group (one axis per chart; magnitudes in
a single hue with direct end labels), and the full metric table.
No external assets; light/dark via CSS custom properties.
"""

from __future__ import annotations

import html
from typing import Dict, List, Sequence, Tuple

#: headline metric per bench family: (metrics key, unit label)
_HEADLINE = (
    ("gb_per_s", "GB/s"),
    ("mb_per_s", "MB/s"),
    ("ingest_mb_per_s", "MB/s"),
    ("projection_mb_per_s", "MB/s"),
    ("ops_per_s", "ops/s"),
)

_CSS = """
.viz-root {
  color-scheme: light;
  --surface-1: #fcfcfb; --text-primary: #0b0b0b;
  --text-secondary: #52514e; --series-1: #2a78d6;
  --grid: #e4e3df;
  background: var(--surface-1); color: var(--text-primary);
  font-family: system-ui, sans-serif; margin: 0; padding: 2rem;
}
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .viz-root {
    color-scheme: dark;
    --surface-1: #1a1a19; --text-primary: #ffffff;
    --text-secondary: #c3c2b7; --series-1: #3987e5;
    --grid: #3a3936;
  }
}
h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.6rem; }
.kpis { display: flex; flex-wrap: wrap; gap: 1rem; margin: 1rem 0; }
.tile { border: 1px solid var(--grid); border-radius: 6px;
        padding: .7rem 1rem; min-width: 10rem; }
.tile .v { font-size: 1.5rem; font-weight: 600; }
.tile .u { color: var(--text-secondary); font-size: .8rem; }
.tile .n { color: var(--text-secondary); font-size: .8rem;
           margin-bottom: .2rem; }
table { border-collapse: collapse; margin: .6rem 0; }
td, th { border: 1px solid var(--grid); padding: .25rem .6rem;
         font-size: .85rem; text-align: left; }
svg text { font-family: system-ui, sans-serif; }
"""


def _headline_of(rec: dict) -> "Tuple[str, float] | None":
    m = rec.get("metrics", {})
    for key, unit in _HEADLINE:
        if key in m:
            return unit, float(m[key])
    return None


def _bar_chart(unit: str, rows: Sequence[Tuple[str, float]]) -> str:
    """Horizontal bars, one hue, 4px rounded data ends, direct labels."""
    bar_h, gap, left, width = 22, 8, 230, 620
    h = len(rows) * (bar_h + gap) + gap
    vmax = max(v for _, v in rows) or 1.0
    parts = [f'<svg role="img" width="{width + 130}" height="{h}" '
             f'aria-label="{html.escape(unit)} by bench">']
    for i, (name, v) in enumerate(rows):
        y = gap + i * (bar_h + gap)
        w = max(2, int((width - left) * v / vmax))
        label = html.escape(name)
        parts.append(
            f'<text x="{left - 8}" y="{y + bar_h * 0.72}" '
            f'text-anchor="end" font-size="12" '
            f'fill="var(--text-secondary)">{label}</text>')
        parts.append(
            f'<rect x="{left}" y="{y}" width="{w}" height="{bar_h}" '
            f'rx="4" fill="var(--series-1)">'
            f'<title>{label}: {v:,.2f} {html.escape(unit)}</title>'
            f'</rect>')
        parts.append(
            f'<text x="{left + w + 6}" y="{y + bar_h * 0.72}" '
            f'font-size="12" fill="var(--text-primary)">'
            f'{v:,.2f}</text>')
    parts.append("</svg>")
    return "".join(parts)


def render_report(results: List[dict], *, title: str = "alluxio-tpu "
                  "stress suite") -> str:
    by_unit: Dict[str, List[Tuple[str, float]]] = {}
    tiles, tables = [], []
    for rec in results:
        name = rec.get("bench", "?")
        head = _headline_of(rec)
        if head is not None:
            unit, value = head
            by_unit.setdefault(unit, []).append((name, value))
            tiles.append(
                f'<div class="tile"><div class="n">{html.escape(name)}'
                f'</div><div class="v">{value:,.1f}</div>'
                f'<div class="u">{html.escape(unit)}</div></div>')
        metrics = rec.get("metrics", {})
        rows = "".join(
            f"<tr><td>{html.escape(str(k))}</td>"
            f"<td>{html.escape(str(v))}</td></tr>"
            for k, v in sorted(metrics.items()))
        tables.append(
            f"<h2>{html.escape(name)}</h2>"
            f"<table><tr><th>metric</th><th>value</th></tr>{rows}"
            f"</table>")
    charts = "".join(
        f"<h2>{html.escape(unit)}</h2>" + _bar_chart(unit, rows)
        for unit, rows in sorted(by_unit.items())
        if rows)
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title>"
            f"<style>{_CSS}</style></head>"
            f"<body class='viz-root'><h1>{html.escape(title)}</h1>"
            f"<div class='kpis'>{''.join(tiles)}</div>"
            f"{charts}"
            f"{''.join(tables)}"
            f"</body></html>")


def _load_results(path: str) -> List[dict]:
    """Accept BOTH result shapes: a JSON array (``bench.py --suite``'s
    BENCH_SUITE.json) and JSONL (``stress suite`` stdout redirected to
    a file — one record per line, possibly interleaved with log
    lines)."""
    import json

    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
        return data if isinstance(data, list) else [data]
    except json.JSONDecodeError:
        out = []
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        if not out:
            raise
        return out


def write_report(input_path: str, out_path: str) -> int:
    """Single entry used by both CLIs (``stress report`` and the
    standalone module)."""
    import json
    import sys

    try:
        results = _load_results(input_path)
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot read suite results {input_path!r}: {e}",
              file=sys.stderr)
        return 1
    with open(out_path, "w") as f:
        f.write(render_report(results))
    print(f"wrote {out_path} ({len(results)} benches)", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="stress report")
    p.add_argument("--input", default="BENCH_SUITE.json",
                   help="suite results (JSON array or JSONL)")
    p.add_argument("--out", default="BENCH_REPORT.html")
    args = p.parse_args(argv)
    return write_report(args.input, args.out)
