"""Remote warm-read bench (``make bench-remote-read``, suite row
``remote-warm-read``).

Measures the striped parallel remote-read pipeline
(``client/remote_read.py``) against the single-stream reader it
replaced, under a **bandwidth-limited-per-connection worker model**:
each opened range stream pays a fixed round trip to first byte and then
delivers at a fixed per-connection bandwidth — the DCN regime the paper
targets (and the one Hiding Latencies in Network-Based Image Loading,
arXiv 2503.22643, shows parallel connections close). All costs are
modeled sleeps, so the numbers isolate the client pipeline; sleeps are
tens of ms and dwarf host jitter.

Reported:

- ``single_gbps`` / ``striped_gbps`` — warm remote-read throughput of
  the legacy one-stream loop vs the striped reader at ``--stripes``
  concurrent range streams;
- ``single_ttfb_ms`` / ``striped_ttfb_ms`` — median time-to-first-byte;
- a hedge row: reads against a replica pair where one replica
  deterministically stalls, reporting hedges issued, hedge wins, and
  the straggler-suppressed read latency.

The suite row FAILS (``errors=1``) when striped throughput at 4 stripes
is below ``--min-speedup`` (default 1.5×) of single-stream, or when the
injected straggler produces zero hedge wins.
"""

from __future__ import annotations

import statistics
import sys
import threading
import time
from typing import List, Optional

from alluxio_tpu.stress.base import BenchResult


class ModeledWorkerSource:
    """A ``ReadSource`` over one modeled DCN connection to a replica:
    ``rtt`` to first byte, then ``conn_bytes_per_s`` per connection.
    ``stall_every`` > 0 makes every Nth open stall ``stall_s`` before
    its first byte — the injected straggler."""

    def __init__(self, key: str, data: bytes, *, rtt_s: float,
                 conn_bytes_per_s: float, stall_every: int = 0,
                 stall_s: float = 0.0) -> None:
        self.key = key
        self.worker_key = key
        self.address = None
        self._data = data
        self._rtt_s = rtt_s
        self._bw = conn_bytes_per_s
        self._stall_every = stall_every
        self._stall_s = stall_s
        self._opens = 0
        self._lock = threading.Lock()

    def set_stall(self, every: int, stall_s: float) -> None:
        with self._lock:
            self._stall_every = every
            self._stall_s = stall_s
            self._opens = 0

    def open(self, offset: int, length: int, chunk_size: int):
        with self._lock:
            self._opens += 1
            stalled = self._stall_every > 0 and \
                self._opens % self._stall_every == 0
        return _ModeledStream(self, offset, length, chunk_size, stalled)


class _ModeledStream:
    def __init__(self, src: ModeledWorkerSource, offset: int, length: int,
                 chunk_size: int, stalled: bool) -> None:
        self._src = src
        self._offset = offset
        self._length = length
        self._chunk = chunk_size
        self._stalled = stalled
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __iter__(self):
        src = self._src
        first = src._rtt_s + (src._stall_s if self._stalled else 0.0)
        pos = self._offset
        end = self._offset + self._length
        while pos < end:
            n = min(self._chunk, end - pos)
            # a cancelled stream stops costing bandwidth: sleep in small
            # slices so a hedge loser releases its modeled connection
            deadline = time.perf_counter() + first + n / src._bw
            first = 0.0
            while True:
                if self.cancelled:
                    return
                remain = deadline - time.perf_counter()
                if remain <= 0:
                    break
                time.sleep(min(remain, 0.01))
            yield {"data": src._data[pos:pos + n], "source": "MEM"}
            pos += n


def _single_stream_read(source: ModeledWorkerSource, length: int,
                        chunk_size: int):
    """The legacy ``GrpcBlockInStream.pread`` shape: one stream, chunks
    re-joined through a bytearray. Returns (bytes, ttfb_s)."""
    out = bytearray()
    t0 = time.perf_counter()
    ttfb: Optional[float] = None
    for msg in source.open(0, length, chunk_size):
        if ttfb is None:
            ttfb = time.perf_counter() - t0
        out.extend(msg["data"])
    return bytes(out), ttfb or 0.0


def run(*, block_mb: int = 4, stripe_kb: int = 1024, stripes: int = 4,
        rtt_ms: float = 20.0, conn_mbps: float = 16.0, blocks: int = 3,
        hedge_quantile: float = 0.95, stall_ms: float = 300.0,
        min_speedup: float = 1.5) -> BenchResult:
    import os

    from alluxio_tpu.client.remote_read import (
        RemoteReadConf, RemoteReadRuntime,
    )

    t_start = time.monotonic()
    block_bytes = block_mb << 20
    chunk = 256 << 10
    data = os.urandom(1 << 20) * block_mb
    bw = conn_mbps * (1 << 20)

    def mk(key: str, **kw) -> ModeledWorkerSource:
        return ModeledWorkerSource(key, data, rtt_s=rtt_ms / 1e3,
                                   conn_bytes_per_s=bw, **kw)

    # --- phase 1: throughput, single stream vs striped -------------------
    single_s: List[float] = []
    single_ttfb: List[float] = []
    for _ in range(blocks):
        t0 = time.perf_counter()
        out, ttfb = _single_stream_read(mk("w0"), block_bytes, chunk)
        single_s.append(time.perf_counter() - t0)
        single_ttfb.append(ttfb)
        assert out == data
    single_gbps = blocks * block_bytes / sum(single_s) / (1 << 30)

    conf = RemoteReadConf(stripe_size=stripe_kb << 10, concurrency=stripes,
                          window_bytes=0, hedge_quantile=0.0)
    rt = RemoteReadRuntime(conf)
    # pooled-channel model: one replica, `stripes` independent
    # connections — each source is its own modeled TCP stream
    pool = [mk(f"w0~{i}") for i in range(stripes)]
    # warm the stripe executor off the clock (thread spawn on a
    # throttled CI host is ms-scale and would land on the first block)
    rt.read(block_id=0, sources=pool, offset=0,
            length=conf.stripe_size * stripes, chunk_size=chunk).read_view()
    striped_s: List[float] = []
    striped_ttfb: List[float] = []
    for b in range(blocks):
        read = rt.read(block_id=b + 1, sources=pool, offset=0,
                       length=block_bytes, chunk_size=chunk)
        t0 = time.perf_counter()
        got = 0
        ttfb = None
        for view in read.iter_views(chunk_size=chunk):
            if ttfb is None:
                ttfb = time.perf_counter() - t0
            got += len(view)
        striped_s.append(time.perf_counter() - t0)
        striped_ttfb.append(ttfb or 0.0)
        assert got == block_bytes
        assert bytes(read.read_view()) == data
    striped_gbps = blocks * block_bytes / sum(striped_s) / (1 << 30)
    speedup = striped_gbps / single_gbps if single_gbps > 0 else 0.0
    print(f"[remoteread] single {single_gbps:.3f} GB/s / "
          f"{statistics.median(single_ttfb) * 1e3:.1f} ms ttfb, striped "
          f"x{stripes} {striped_gbps:.3f} GB/s / "
          f"{statistics.median(striped_ttfb) * 1e3:.1f} ms ttfb "
          f"({speedup:.2f}x)", file=sys.stderr, flush=True)
    rt.close()

    # --- phase 2: hedged requests vs an injected straggler replica -------
    hconf = RemoteReadConf(stripe_size=stripe_kb << 10, concurrency=stripes,
                           window_bytes=0, hedge_quantile=hedge_quantile)
    hrt = RemoteReadRuntime(hconf)
    fast = mk("w-fast")
    slow = mk("w-slow")
    replicas = [fast, slow]
    # seed the rolling EWMAs with clean reads while the straggler is
    # still healthy — a hedger needs a baseline to call anything a tail
    for b in range(3):
        r = hrt.read(block_id=100 + b, sources=replicas, offset=0,
                     length=block_bytes, chunk_size=chunk)
        assert bytes(r.read_view()) == data
    # now every 2nd stream on the straggler stalls before its first
    # byte — a tail, not a uniformly slow worker (cancelled losers are
    # never observed, so its EWMA stays honest)
    slow.set_stall(2, stall_ms / 1e3)
    hedges = wins = 0
    hedged_s: List[float] = []
    for b in range(blocks):
        r = hrt.read(block_id=200 + b, sources=replicas, offset=0,
                     length=block_bytes, chunk_size=chunk)
        t0 = time.perf_counter()
        assert bytes(r.read_view()) == data
        hedged_s.append(time.perf_counter() - t0)
        hedges += r.hedges
        wins += r.hedge_wins
    hrt.close()
    print(f"[remoteread] straggler phase: {hedges} hedges, {wins} wins, "
          f"median read {statistics.median(hedged_s) * 1e3:.1f} ms "
          f"(straggler stall {stall_ms:.0f} ms)",
          file=sys.stderr, flush=True)

    ok = speedup >= min_speedup and wins > 0
    if speedup < min_speedup:
        print(f"[remoteread] striped speedup {speedup:.2f}x is below the "
              f"{min_speedup}x gate", file=sys.stderr)
    if wins == 0:
        print("[remoteread] no hedge wins against the injected straggler",
              file=sys.stderr)

    return BenchResult(
        bench="remote-warm-read",
        params={"block_mb": block_mb, "stripe_kb": stripe_kb,
                "stripes": stripes, "rtt_ms": rtt_ms,
                "conn_mbps": conn_mbps, "blocks": blocks,
                "hedge_quantile": hedge_quantile, "stall_ms": stall_ms,
                "min_speedup": min_speedup},
        metrics={"single_gbps": round(single_gbps, 4),
                 "striped_gbps": round(striped_gbps, 4),
                 # report headline
                 "gb_per_s": round(striped_gbps, 4),
                 "speedup": round(speedup, 3),
                 "single_ttfb_ms": round(
                     statistics.median(single_ttfb) * 1e3, 2),
                 "striped_ttfb_ms": round(
                     statistics.median(striped_ttfb) * 1e3, 2),
                 "hedges": hedges, "hedge_wins": wins,
                 "hedged_read_ms": round(
                     statistics.median(hedged_s) * 1e3, 2),
                 "gate_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)
