"""BASELINE config #5: async write-through under cache-eviction pressure.

Reference analogue: ``TieredBlockStore`` eviction-on-allocation with the
LRFU annotator (``worker/block/TieredBlockStore.java:85``,
``annotator/LRFUAnnotator.java:29``). The bench writes an ASYNC_THROUGH
corpus several times larger than the MEM tier of a MEM+SSD worker, so
allocation continuously demotes cold blocks down-tier while the
persistence scheduler drains writes to the UFS in the background. Metrics:
ingest MB/s (client-visible write rate under pressure), time-to-durable
(all files persisted), and where the blocks ended up.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from alluxio_tpu.stress.base import BenchResult, drive, percentiles
from alluxio_tpu.stress.cluster import bench_cluster


def run(*, master: Optional[str] = None, threads: int = 4,
        num_files: int = 24, file_bytes: int = 8 << 20,
        mem_bytes: int = 64 << 20, block_size: int = 4 << 20,
        persist_timeout_s: float = 120.0,
        base_path: str = "/stress-write") -> BenchResult:
    from alluxio_tpu.client.streams import WriteType
    from alluxio_tpu.conf import Keys, Templates

    if master:
        raise NotImplementedError(
            "write bench provisions its own tiered cluster")
    rng = np.random.default_rng(0)
    total = num_files * file_bytes
    overrides = {
        Keys.WORKER_TIERED_STORE_LEVELS: 2,
        Keys.WORKER_ANNOTATOR_CLASS: "LRFU",
        # SSD tier big enough for everything MEM spills
        Templates.WORKER_TIER_DIRS_QUOTA.format(1): str(total + (64 << 20)),
    }
    with bench_cluster(None, num_workers=1, block_size=block_size,
                       worker_mem_bytes=mem_bytes,
                       conf_overrides=overrides,
                       start_job_service=True) as (fs, cluster):
        payload = rng.integers(0, 255, size=file_bytes, dtype=np.uint8
                               ).tobytes()
        files_per_thread = num_files // threads

        def op(t: int, i: int) -> int:
            fs.write_all(f"{base_path}/t{t}/f-{i:05d}", payload,
                         write_type=WriteType.ASYNC_THROUGH)
            return file_bytes

        res = drive(threads, op, ops_per_thread=files_per_thread)

        # durability: wait for the persistence scheduler to drain
        t0 = time.monotonic()
        deadline = t0 + persist_timeout_s
        pending = {f"{base_path}/t{t}/f-{i:05d}"
                   for t in range(threads) for i in range(files_per_thread)}
        while pending and time.monotonic() < deadline:
            pending = {p for p in pending if not fs.get_status(p).persisted}
            if pending:
                time.sleep(0.1)
        persist_wall = time.monotonic() - t0

        # tier occupancy after the dust settles
        store = cluster.workers[0].worker.store
        tier_usage = {t.alias: t.used_bytes for t in store.meta.tiers}

        return BenchResult(
            bench="write-through-eviction",
            params={"threads": threads, "num_files": num_files,
                    "file_bytes": file_bytes, "mem_bytes": mem_bytes,
                    "block_size": block_size, "annotator": "LRFU",
                    "pressure_x": round(total / mem_bytes, 1)},
            metrics={"ingest_mb_per_s": round(res.mb_per_s, 2),
                     "time_to_durable_s": round(persist_wall, 2),
                     "unpersisted": len(pending),
                     "tier_used_bytes": tier_usage,
                     **percentiles(res.latencies_s)},
            errors=res.errors + len(pending), duration_s=res.wall_s)
