"""HA failover drill: kill the primary under live load, gate MTTR and
the standby-read staleness contract (``make bench-ha``, suite row
``ha-failover``; docs/ha.md).

The drill runs a real 3-master EMBEDDED-journal quorum in process
(:class:`~alluxio_tpu.minicluster.ha_cluster.HaCluster`) with a writer
issuing creates through the multi-endpoint failover client and a prober
reading from whichever member is currently a standby.  Mid-run the
primary is killed.  Three things are measured, two gated:

- **MTTR** — last ack before the kill to first ack after it, as the
  CLIENT sees it (election + promotion + redirect, end to end).  Gate:
  ≤ 2 election timeouts (the issue's budget; election upper bound
  dominates, promotion and the leader-hint redirect must fit in the
  rest).
- **No acked write lost** — every create the client saw acknowledged
  must exist on the post-failover primary.  Gate: zero missing.
- **Standby staleness contract** — a standby response stamped
  ``md_version v`` must include every write whose primary-side stamp is
  ``<= v`` (the coherence contract standby reads ride on).  Gate: zero
  violations; observed standby visibility lag is reported p50/p99.

Slow-host note: election timeouts are seconds-scale here ON PURPOSE —
the quorum, writer and prober share one GIL, and the gate must measure
failover, not scheduler jitter (same discipline as bench-metadata's
modeled fsync).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import List, Optional, Tuple

from alluxio_tpu.stress.base import BenchResult, percentiles


def run(*, masters: int = 3, election_timeout_s: float = 2.0,
        warmup_s: float = 3.0, settle_s: float = 3.0,
        mttr_budget_timeouts: float = 2.0) -> BenchResult:
    import tempfile

    from alluxio_tpu.minicluster.ha_cluster import HaCluster, WriteLedger
    from alluxio_tpu.rpc.clients import FsMasterClient

    t_start = time.monotonic()
    lo = max(0.2, election_timeout_s / 2)
    # budget against the EFFECTIVE worst-member election timeout: rank
    # staggering (raft.py _reset_election_deadline: +rank * 15% of the
    # randomization band, split-vote avoidance) means the slowest
    # surviving member legitimately fires that much later than the
    # configured max — "2 election timeouts" must count what the
    # election design actually allows, not under-budget high ranks
    stagger_max = (masters - 1) * 0.15 * (election_timeout_s - lo)
    mttr_budget_s = mttr_budget_timeouts * (election_timeout_s
                                            + stagger_max)
    with tempfile.TemporaryDirectory() as base:
        cluster = HaCluster(
            base, num_masters=masters, num_workers=0,
            election_timeout=(f"{int(lo * 1000)}ms",
                              f"{int(election_timeout_s * 1000)}ms"))
        try:
            cluster.start()
            # max_sleep 0.5s: the default 3s backoff cap is tuned for
            # overload, not failover — one unlucky 2-3s sleep drawn just
            # as the new leader emerges would dominate the MTTR the gate
            # is trying to measure.  A real HA deployment tunes
            # atpu.user.rpc.retry.max.sleep the same way (docs/ha.md).
            writer = cluster.fs_client(retry_duration_s=60.0,
                                       max_sleep_s=0.5, fastpath=False)
            primary_reader = cluster.fs_client(retry_duration_s=10.0,
                                               max_sleep_s=0.5,
                                               fastpath=False)
            ledger = WriteLedger()
            acks: List[Tuple[str, float]] = []  # (path, t_ack)
            stop = threading.Event()
            writer_err: List[BaseException] = []
            writer.create_directory("/ha-bench")

            def write_loop() -> None:
                i = 0
                while not stop.is_set():
                    path = f"/ha-bench/w{i:06d}"
                    try:
                        writer.create_directory(path)
                    except BaseException as e:  # noqa: BLE001 gate input
                        writer_err.append(e)
                        return
                    t_ack = time.monotonic()
                    acks.append((path, t_ack))
                    # stamp a sample of writes for the staleness ledger
                    # (every write would double primary load)
                    if i % 5 == 0:
                        try:
                            _, stamp = primary_reader.get_status(
                                path, want_version=True)
                            ledger.record(path, stamp)
                        except Exception:  # noqa: BLE001 mid-failover
                            ledger.record(path, None)
                    else:
                        ledger.record(path, None)
                    i += 1
                    time.sleep(0.005)

            staleness_violations = 0
            standby_lag_s: List[float] = []
            seen_on_standby: dict = {}

            #: one probe client per standby port, reused across
            #: iterations: a fresh channel per 50ms tick adds setup
            #: jitter to the very lag percentiles the suite gates on
            probe_clients: dict = {}

            def probe_loop() -> None:
                nonlocal staleness_violations
                while not stop.is_set():
                    idxs = cluster.standby_indices()
                    port = None
                    for i in idxs:
                        m = cluster.masters[i]
                        if m is not None and m.standby_rpc_port:
                            port = m.standby_rpc_port
                            break
                    if port is None:
                        time.sleep(0.05)
                        continue
                    sc = probe_clients.get(port)
                    if sc is None:
                        sc = probe_clients[port] = FsMasterClient(
                            f"localhost:{port}", retry_duration_s=1.0,
                            fastpath=False)
                    try:
                        infos, stamp = sc.list_status(
                            "/ha-bench", want_version=True)
                    except Exception:  # noqa: BLE001 standby mid-churn
                        time.sleep(0.05)
                        continue
                    now = time.monotonic()
                    names = {"/ha-bench/" + x.name for x in infos}
                    staleness_violations += len(
                        ledger.staleness_violations(names, stamp))
                    for path, t_ack in list(acks):
                        if path in names and path not in seen_on_standby:
                            seen_on_standby[path] = now
                            standby_lag_s.append(max(0.0, now - t_ack))
                    time.sleep(0.05)

            wt = threading.Thread(target=write_loop, daemon=True)
            pt = threading.Thread(target=probe_loop, daemon=True)
            wt.start(), pt.start()
            time.sleep(warmup_s)
            t_kill = time.monotonic()
            cluster.kill_primary()
            # MTTR = kill START to the first ack landed after the old
            # primary is fully dead: an in-flight write acked inside the
            # server's stop grace must not read as an 18ms failover
            t_dead = time.monotonic()
            mttr_s: Optional[float] = None
            deadline = t_kill + 60.0
            while time.monotonic() < deadline and not writer_err:
                post = [t for _, t in acks if t > t_dead]
                if post:
                    mttr_s = post[0] - t_kill
                    break
                time.sleep(0.02)
            time.sleep(settle_s)  # let standby probing settle post-failover
            stop.set()
            wt.join(timeout=10), pt.join(timeout=10)

            lost = ledger.verify_durable(
                cluster.fs_client(retry_duration_s=30.0, fastpath=False))
            lag = percentiles(standby_lag_s)
            errors = 0
            if writer_err:
                errors += 1
                print(f"[ha] writer surfaced an error through failover: "
                      f"{writer_err[0]!r}", file=sys.stderr)
            if mttr_s is None:
                errors += 1
                print("[ha] no acknowledged write within 60s of the "
                      "kill — failover never completed", file=sys.stderr)
            elif mttr_s > mttr_budget_s:
                errors += 1
                print(f"[ha] MTTR {mttr_s:.2f}s exceeds the "
                      f"{mttr_budget_s:.2f}s budget "
                      f"({mttr_budget_timeouts:g} election timeouts)",
                      file=sys.stderr)
            if lost:
                errors += 1
                print(f"[ha] {len(lost)} ACKED writes missing after "
                      f"failover: {lost[:5]} ...", file=sys.stderr)
            if staleness_violations:
                errors += 1
                print(f"[ha] {staleness_violations} standby reads were "
                      f"staler than their advertised md_version",
                      file=sys.stderr)
            return BenchResult(
                bench="ha-failover",
                params={"masters": masters,
                        "election_timeout_s": election_timeout_s,
                        "mttr_budget_s": round(mttr_budget_s, 2)},
                metrics={
                    "mttr_s": round(mttr_s, 3) if mttr_s is not None
                    else None,
                    "mttr_ok": mttr_s is not None
                    and mttr_s <= mttr_budget_s,
                    "acked_writes": len(acks),
                    "lost_acked": len(lost),
                    "staleness_violations": staleness_violations,
                    "standby_reads_observed": len(standby_lag_s),
                    "standby_lag_p50_us": lag["p50_us"],
                    "standby_lag_p99_us": lag["p99_us"],
                },
                errors=errors,
                duration_s=time.monotonic() - t_start)
        finally:
            cluster.stop()
