"""Shared stress-bench machinery: result schema, latency stats, and the
threaded closed-loop driver.

Re-design of ``stress/common/src/main/java/alluxio/stress/
{BaseParameters.java:56,TaskResult,worker/IOTaskSummary.java}``: results
are a JSON line with throughput + latency percentiles; the driver runs N
closed-loop worker threads for a fixed duration (or op count) with an
optional shared token-bucket rate limiter (the MaxThroughput suite's
"target throughput" knob, ``cli/suite/MaxThroughput.java``).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["BenchResult", "DriveResult", "drive", "percentiles",
           "RateLimiter"]


def percentiles(samples_s: List[float]) -> Dict[str, float]:
    """p50/p95/p99/max of latency samples, reported in microseconds
    (matching the reference's IOTaskSummary histogram fields)."""
    if not samples_s:
        return {"p50_us": 0.0, "p95_us": 0.0, "p99_us": 0.0, "max_us": 0.0}
    s = sorted(samples_s)
    n = len(s)

    def at(q: float) -> float:
        return round(1e6 * s[min(n - 1, int(q * n))], 1)

    return {"p50_us": at(0.50), "p95_us": at(0.95), "p99_us": at(0.99),
            "max_us": round(1e6 * s[-1], 1)}


@dataclasses.dataclass
class BenchResult:
    """One bench outcome; ``json_line()`` is the wire contract every
    stress CLI prints (one line, stdout)."""

    bench: str
    params: Dict[str, Any]
    metrics: Dict[str, Any]
    errors: int = 0
    duration_s: float = 0.0

    def json_line(self) -> str:
        return json.dumps({
            "bench": self.bench,
            "params": self.params,
            "metrics": self.metrics,
            "errors": self.errors,
            "duration_s": round(self.duration_s, 3),
        }, sort_keys=True)


class RateLimiter:
    """Shared token bucket: ``acquire()`` blocks until the global op rate
    is under ``ops_per_s``. Coarse (100ms refill) but fair enough for a
    throughput search."""

    def __init__(self, ops_per_s: float) -> None:
        self._rate = float(ops_per_s)
        self._tokens = 0.0
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self) -> None:
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(self._rate,
                                   self._tokens + (now - self._last) * self._rate)
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                need = (1.0 - self._tokens) / self._rate
            time.sleep(min(need, 0.1))


@dataclasses.dataclass
class DriveResult:
    ops: int
    bytes: int
    errors: int
    latencies_s: List[float]
    wall_s: float

    @property
    def ops_per_s(self) -> float:
        return self.ops / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def mb_per_s(self) -> float:
        return self.bytes / self.wall_s / 1e6 if self.wall_s > 0 else 0.0


def drive(n_threads: int, op: Callable[[int, int], int], *,
          duration_s: float = 0.0, ops_per_thread: int = 0,
          rate_limiter: Optional[RateLimiter] = None,
          setup: Optional[Callable[[int], Any]] = None) -> DriveResult:
    """Closed-loop driver: each of ``n_threads`` threads calls
    ``op(thread_index, i)`` (returning bytes processed) until the wall
    clock passes ``duration_s`` OR it has issued ``ops_per_thread`` ops.
    ``setup(thread_index)`` runs once per thread before the clock starts
    (per-thread streams/clients — FileInStream is not thread-safe).
    Latencies are collected per-thread (no lock on the hot path).
    """
    if not duration_s and not ops_per_thread:
        raise ValueError("need duration_s or ops_per_thread")
    ctxs: List[Any] = [None] * n_threads
    if setup is not None:
        for t in range(n_threads):
            ctxs[t] = setup(t)
    lat: List[List[float]] = [[] for _ in range(n_threads)]
    counts = [0] * n_threads
    nbytes = [0] * n_threads
    errors = [0] * n_threads
    start_gate = threading.Barrier(n_threads + 1)
    stop = threading.Event()

    def worker(t: int) -> None:
        my_lat, i = lat[t], 0
        start_gate.wait()
        while not stop.is_set():
            if ops_per_thread and i >= ops_per_thread:
                break
            if rate_limiter is not None:
                rate_limiter.acquire()
                if stop.is_set():
                    break
            t0 = time.monotonic()
            try:
                nbytes[t] += op(t, i) or 0
                counts[t] += 1
            except Exception:  # noqa: BLE001 — counted, bench goes on
                errors[t] += 1
            my_lat.append(time.monotonic() - t0)
            i += 1

    threads = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(n_threads)]
    for th in threads:
        th.start()
    start_gate.wait()
    t0 = time.monotonic()
    if duration_s:
        stop.wait(duration_s)
        stop.set()
    for th in threads:
        th.join()
    wall = time.monotonic() - t0
    merged: List[float] = []
    for sub in lat:
        merged.extend(sub)
    return DriveResult(ops=sum(counts), bytes=sum(nbytes),
                       errors=sum(errors), latencies_s=merged, wall_s=wall)


def host_speed_stamp_ms() -> float:
    """10M-adds wall time in ms: the one host-speed calibration figure
    (CI-container CPU drifts 3-4x between allocations; GIL-bound op/s
    rows scale ~inversely with this). Used by the suite's
    host-calibration row and the bench's host-fallback rows under the
    SAME key name, ``python_10m_adds_ms``."""
    import time as _t

    t0 = _t.monotonic()
    x = 0
    for i in range(10_000_000):
        x += i
    return round((_t.monotonic() - t0) * 1000, 1)
