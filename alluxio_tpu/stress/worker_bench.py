"""StressWorkerBench analogue: warm-cache worker read throughput.

Modes (reference ``stress/shell/.../cli/worker/StressWorkerBench.java:47``):
  sequential — BASELINE config #1's measurement shape, full-shard streams
  random     — BASELINE config #2: random 4 KiB positioned reads over
               TFRecord-framed ImageNet-style shards (the alluxio-fuse
               random-read analogue, ``fuse/AlluxioFuseFileSystem.java``)

Data is written warm into the worker cache first; reads ride the
short-circuit mmap path when co-located, so this measures the framework's
cache read path, not the UFS.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional

import numpy as np

from alluxio_tpu.stress.base import BenchResult, drive, percentiles
from alluxio_tpu.stress.cluster import bench_cluster


def _masked_crc(data: bytes) -> int:
    """TFRecord's masked crc32c framing (crc32 stands in for crc32c —
    the framing layout, not the polynomial, is what the bench needs)."""
    crc = zlib.crc32(data) & 0xFFFFFFFF
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def make_tfrecord_shard(rng: np.random.Generator, shard_bytes: int,
                        record_bytes: int = 12 << 10) -> bytes:
    """A TFRecord-framed shard: [len u64][crc u32][payload][crc u32]*."""
    out = bytearray()
    payload = rng.integers(0, 255, size=record_bytes, dtype=np.uint8
                           ).tobytes()
    header = struct.pack("<QI", record_bytes, _masked_crc(
        struct.pack("<Q", record_bytes)))
    footer = struct.pack("<I", _masked_crc(payload))
    frame = header + payload + footer
    while len(out) + len(frame) <= shard_bytes:
        out.extend(frame)
    out.extend(b"\0" * (shard_bytes - len(out)))
    return bytes(out)


def run(*, mode: str = "random", master: Optional[str] = None,
        threads: int = 8, duration_s: float = 10.0,
        shard_bytes: int = 64 << 20, num_shards: int = 4,
        read_bytes: int = 4 << 10, base_path: str = "/stress-worker",
        _reuse_fs=None) -> BenchResult:
    """``_reuse_fs``: run against an existing cluster through this
    FileSystem client (the distributed stressbench job plan's mode)."""
    if _reuse_fs is not None:
        # live-cluster mode: overwrite stale shards from a previous run
        # and remove them afterwards — bench data must not occupy the
        # production cache or fail the next run with AlreadyExists
        try:
            return _run_against(_reuse_fs, mode=mode, master=master,
                                threads=threads, duration_s=duration_s,
                                shard_bytes=shard_bytes,
                                num_shards=num_shards,
                                read_bytes=read_bytes,
                                base_path=base_path)
        finally:
            try:
                _reuse_fs.delete(base_path, recursive=True)
            except Exception:  # noqa: BLE001 cleanup is best-effort
                pass
    with bench_cluster(master, block_size=min(shard_bytes, 32 << 20),
                       worker_mem_bytes=shard_bytes * num_shards + (256 << 20)
                       ) as (fs, _cluster):
        return _run_against(fs, mode=mode, master=master,
                            threads=threads, duration_s=duration_s,
                            shard_bytes=shard_bytes,
                            num_shards=num_shards, read_bytes=read_bytes,
                            base_path=base_path)


def _run_against(fs, *, mode, master, threads, duration_s, shard_bytes,
                 num_shards, read_bytes, base_path) -> BenchResult:
    from alluxio_tpu.client.streams import WriteType

    rng = np.random.default_rng(0)
    paths: List[str] = []
    for i in range(num_shards):
        p = f"{base_path}/shard-{i:05d}.tfrecord"
        fs.write_all(p, make_tfrecord_shard(rng, shard_bytes),
                     write_type=WriteType.MUST_CACHE, overwrite=True)
        paths.append(p)

    n_offsets = shard_bytes // read_bytes
    # per-thread streams: FileInStream is not thread-safe
    ctxs = [([fs.open_file(p) for p in paths],
             np.random.default_rng(t)) for t in range(threads)]

    if mode == "random":
        def op(t: int, i: int) -> int:
            streams, trng = ctxs[t]
            s = streams[int(trng.integers(len(streams)))]
            off = int(trng.integers(n_offsets)) * read_bytes
            data = s.pread(off, read_bytes)
            return len(data)
    elif mode == "sequential":
        chunk = 4 << 20

        def op(t: int, i: int) -> int:
            streams, _trng = ctxs[t]
            s = streams[(t + i) % len(streams)]
            pos = (i * chunk) % shard_bytes
            data = s.pread(pos, chunk)
            return len(data)
    else:
        raise ValueError(f"unknown mode {mode!r}")

    try:
        res = drive(threads, op, duration_s=duration_s)
    finally:
        for streams, _trng in ctxs:
            for s in streams:
                s.close()
    return BenchResult(
        bench=f"worker-{mode}",
        params={"threads": threads, "duration_s": duration_s,
                "shard_bytes": shard_bytes, "num_shards": num_shards,
                "read_bytes": read_bytes if mode == "random" else 4 << 20,
                "master": master or "in-process"},
        metrics={"ops_per_s": round(res.ops_per_s, 1),
                 "mb_per_s": round(res.mb_per_s, 2),
                 **percentiles(res.latencies_s)},
        errors=res.errors, duration_s=res.wall_s)
