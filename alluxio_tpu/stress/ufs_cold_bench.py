"""Cold UFS read bench (``make bench-ufs-cold``, suite row
``ufs-cold-read``).

Measures the striped fetch pipeline (``worker/ufs_fetch.py``) against
the naive single-range cold path it replaced, under a
**connection-limited UFS model**: each ``read_range`` call pays a fixed
round-trip latency and then streams at a fixed per-connection
bandwidth — the regime object stores actually exhibit (Hoard, arxiv
1812.00669: many modest streams beat one connection; the link is rarely
the limit, the connection is). Local-disk IO underneath is effectively
free next to the modeled sleeps, so the numbers isolate the pipeline.

Reported per concurrency level (1/4/16 readers, each reading its own
cold blocks):

- ``single_gbps`` / ``striped_gbps`` — aggregate cold-read throughput;
- ``single_ttfb_ms`` / ``striped_ttfb_ms`` — median time-to-first-byte
  (the streaming read-through's O(stripe) vs the naive path's O(block));
- a coalescing row: N readers of ONE cold block, proving the UFS saw
  exactly one fetch (reads == stripe count).

The suite row FAILS (``errors=1``) when striped throughput at 4
concurrent readers is below ``--min-speedup`` (default 1.5×) of the
single-stream baseline — the regression gate for this subsystem.
"""

from __future__ import annotations

import statistics
import sys
import threading
import time
from typing import Dict, List, Tuple

from alluxio_tpu.stress.base import BenchResult


class ConnectionLimitedUfs:
    """Wraps a real UFS; every ``read_range`` sleeps
    ``rtt + length/bandwidth`` first — one connection's cost model.
    Thread-safe call counting for the coalescing proof."""

    def __init__(self, delegate, *, rtt_s: float,
                 conn_bytes_per_s: float) -> None:
        self._ufs = delegate
        self._rtt_s = rtt_s
        self._bw = conn_bytes_per_s
        self.calls: List[Tuple[int, int]] = []
        self._lock = threading.Lock()

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        with self._lock:
            self.calls.append((offset, length))
        time.sleep(self._rtt_s + length / self._bw)
        return self._ufs.read_range(path, offset, length)


def _drive(readers: int, blocks_per_reader: int, block_bytes: int,
           read_one) -> Tuple[float, List[float]]:
    """Run ``readers`` threads, each cold-reading its own blocks via
    ``read_one(reader_i, block_i) -> ttfb_s``; returns (wall_s, ttfbs)."""
    barrier = threading.Barrier(readers + 1)
    ttfbs: List[float] = []
    lock = threading.Lock()
    errors: List[BaseException] = []

    def run(r: int) -> None:
        barrier.wait()
        local = []
        try:
            for b in range(blocks_per_reader):
                local.append(read_one(r, b))
        except BaseException as e:  # noqa: BLE001
            with lock:
                errors.append(e)
            return
        with lock:
            ttfbs.extend(local)

    threads = [threading.Thread(target=run, args=(r,))
               for r in range(readers)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return wall, ttfbs


def run(*, block_mb: int = 2, stripe_kb: int = 512,
        blocks_per_reader: int = 3, rtt_ms: float = 25.0,
        conn_mbps: float = 4.0, concurrency: int = 4,
        per_mount_limit: int = 64, coalesce_readers: int = 8,
        min_speedup: float = 1.5) -> BenchResult:
    import os
    import tempfile

    from alluxio_tpu.conf import Configuration, Keys
    from alluxio_tpu.underfs.local import LocalUnderFileSystem
    from alluxio_tpu.worker.process import build_store_from_conf
    from alluxio_tpu.worker.ufs_fetch import FetchConf, UfsBlockFetcher
    from alluxio_tpu.worker.ufs_io import UfsBlockDescriptor, UfsBlockReader

    t_start = time.monotonic()
    block_bytes = block_mb << 20
    next_block_id = iter(range(1, 1 << 30)).__next__

    with tempfile.TemporaryDirectory(prefix="atpu-ufscold-") as base:
        conf = Configuration(load_env=False)
        conf.set(Keys.WORKER_DATA_FOLDER, os.path.join(base, "worker"))
        conf.set(Keys.WORKER_SHM_DIR, os.path.join(base, "shm"))
        conf.set(Keys.WORKER_RAMDISK_SIZE, 1 << 20)  # cache off anyway
        store = build_store_from_conf(conf)
        obj = os.path.join(base, "object.bin")
        with open(obj, "wb") as f:
            f.write(os.urandom(1 << 20) * block_mb)
        local = LocalUnderFileSystem(base)
        ufs = ConnectionLimitedUfs(local, rtt_s=rtt_ms / 1e3,
                                   conn_bytes_per_s=conn_mbps * (1 << 20))
        naive = UfsBlockReader(store)
        fconf = FetchConf(stripe_size=stripe_kb << 10,
                          concurrency=concurrency,
                          per_mount_limit=per_mount_limit)
        fetcher = UfsBlockFetcher(store, fconf)
        # warm the stripe executor: thread spawn on a throttled CI host
        # costs ms-scale and would land entirely on the first block
        for _ in range(2):
            fetcher.fetch(ufs, UfsBlockDescriptor(
                block_id=next_block_id(), ufs_path=obj, offset=0,
                length=block_bytes), cache=False).result()
        levels: Dict[str, Dict[int, float]] = \
            {"single_gbps": {}, "striped_gbps": {},
             "single_ttfb_ms": {}, "striped_ttfb_ms": {}}
        # cache=False everywhere: the gate compares FETCH pipelines; a
        # synchronous naive-path cache fill would penalize the baseline
        # with disk-write time the striped path commits off-thread
        for readers in (1, 4, 16):
            def read_single(r: int, b: int) -> float:
                desc = UfsBlockDescriptor(
                    block_id=next_block_id(), ufs_path=obj,
                    offset=0, length=block_bytes)
                t0 = time.perf_counter()
                data = naive.read_block(ufs, desc, cache=False)
                assert len(data) == block_bytes
                return time.perf_counter() - t0  # first byte == last byte

            wall, ttfbs = _drive(readers, blocks_per_reader,
                                 block_bytes, read_single)
            total = readers * blocks_per_reader * block_bytes
            levels["single_gbps"][readers] = total / wall / (1 << 30)
            levels["single_ttfb_ms"][readers] = \
                statistics.median(ttfbs) * 1e3

            def read_striped(r: int, b: int) -> float:
                desc = UfsBlockDescriptor(
                    block_id=next_block_id(), ufs_path=obj,
                    offset=0, length=block_bytes)
                t0 = time.perf_counter()
                fetch = fetcher.fetch(ufs, desc, cache=False)
                ttfb = None
                n = 0
                for chunk in fetch.iter_range(0, block_bytes):
                    if ttfb is None:
                        ttfb = time.perf_counter() - t0
                    n += len(chunk)
                assert n == block_bytes
                return ttfb

            wall, ttfbs = _drive(readers, blocks_per_reader,
                                 block_bytes, read_striped)
            levels["striped_gbps"][readers] = total / wall / (1 << 30)
            levels["striped_ttfb_ms"][readers] = \
                statistics.median(ttfbs) * 1e3
            print(f"[ufscold] c={readers}: single "
                  f"{levels['single_gbps'][readers]:.3f} GB/s / "
                  f"{levels['single_ttfb_ms'][readers]:.1f} ms ttfb, "
                  f"striped {levels['striped_gbps'][readers]:.3f} GB/s / "
                  f"{levels['striped_ttfb_ms'][readers]:.1f} ms ttfb",
                  file=sys.stderr, flush=True)

        # coalescing: N concurrent readers of ONE cold block -> one fetch
        shared = UfsBlockDescriptor(block_id=next_block_id(),
                                    ufs_path=obj, offset=0,
                                    length=block_bytes)
        calls_before = len(ufs.calls)
        try:
            def read_shared(r: int, b: int) -> float:
                t0 = time.perf_counter()
                data = fetcher.fetch(ufs, shared, cache=False).result()
                assert len(data) == block_bytes
                return time.perf_counter() - t0

            _drive(coalesce_readers, 1, block_bytes, read_shared)
        finally:
            fetcher.close()
        coalesce_reads = len(ufs.calls) - calls_before
        expected_stripes = -(-block_bytes // (stripe_kb << 10))

    speedup_c4 = levels["striped_gbps"][4] / levels["single_gbps"][4] \
        if levels["single_gbps"][4] > 0 else 0.0
    # the gate is the throughput ratio; the exactly-one-fetch proof is
    # deterministic in tests/test_ufs_fetch.py (here thread scheduling
    # can legitimately let a late reader miss the in-flight window)
    ok = speedup_c4 >= min_speedup
    if not ok:
        print(f"[ufscold] striped speedup {speedup_c4:.2f}x at c=4 is "
              f"below the {min_speedup}x gate", file=sys.stderr)

    def _r(d: Dict[int, float]) -> Dict[str, float]:
        return {str(k): round(v, 4) for k, v in d.items()}

    return BenchResult(
        bench="ufs-cold-read",
        params={"block_mb": block_mb, "stripe_kb": stripe_kb,
                "blocks_per_reader": blocks_per_reader,
                "rtt_ms": rtt_ms, "conn_mbps": conn_mbps,
                "concurrency": concurrency,
                "per_mount_limit": per_mount_limit,
                "min_speedup": min_speedup},
        metrics={**{k: _r(v) for k, v in levels.items()},
                 # report headline: striped cold-read GB/s at 4 readers
                 "gb_per_s": round(levels["striped_gbps"][4], 4),
                 "speedup_c4": round(speedup_c4, 3),
                 "coalesce_readers": coalesce_readers,
                 "coalesce_ufs_reads": coalesce_reads,
                 "coalesce_expected_stripes": expected_stripes,
                 "gate_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)
