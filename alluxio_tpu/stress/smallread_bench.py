"""Small-read data-plane benches (``make bench-smallread``).

Two gated rows for the zero-copy/batching subsystem
(docs/small_reads.md):

- ``smallread-batch`` — random-4k reads over real gRPC against a live
  in-process cluster with short-circuit OFF (every op must cross the
  worker RPC boundary). Per-op ``pread`` loop vs one scatter/gather
  ``pread_many`` over the same offsets. FAILS below ``--min-speedup``
  (default 3x) batched-vs-per-op ops/s — the "one RPC per batch, not
  per op" claim, measured end to end. Byte equality between the two
  runs is asserted on the way (a fast wrong answer is a failure, not a
  result).
- ``smallread-shm-zerocopy`` — same-host reads through the SHM plane:
  the block stream must BE the SHM stream (``last_source == "SHM"``),
  every view must alias ONE underlying mmap (buffer identity via
  ``np.shares_memory`` + ``memoryview.obj`` identity — zero copies,
  not just "fast"), and a traced read burst must record ZERO
  ``wire``/``serialize`` phase time (the wire never ran; cf. the
  ``obs-critical-path`` row next to which this sits in the suite).
- ``smallread-native-fastpath`` — the same-host batched random-4k
  drill run twice: ``atpu.user.native.fastpath.enabled`` on (one
  packed op table per ``pread_many`` batch, GIL released for the
  whole call) vs off (the per-op pure-Python loop, i.e. the path
  before the native core existed). FAILS below ``--min-speedup``
  (default 5x) native-vs-python ops/s, on any byte difference between
  the two outputs and the written data, or when the native layer did
  not actually execute (``Client.NativeBatches`` must move).
"""

from __future__ import annotations

import sys
import time

from alluxio_tpu.stress.base import BenchResult


def _rand_offsets(rng, size: int, read_bytes: int, ops: int):
    return [rng.randrange(0, size - read_bytes) for _ in range(ops)]


def run_batch(*, file_mb: int = 2, ops: int = 400,
              read_bytes: int = 4096,
              min_speedup: float = 3.0) -> BenchResult:
    """``smallread-batch``: batched vs per-op random-4k ops/s over the
    remote read path."""
    import random
    import tempfile

    from alluxio_tpu.client.file_system import FileSystem
    from alluxio_tpu.conf import Keys
    from alluxio_tpu.minicluster.local_cluster import LocalCluster

    t_start = time.monotonic()
    rng = random.Random(0x4B)
    size = file_mb << 20
    with tempfile.TemporaryDirectory(prefix="atpu-smallread-") as base:
        with LocalCluster(base, num_workers=1,
                          worker_mem_bytes=8 * size) as c:
            conf = c.conf.copy()
            # force the wire: the row measures RPC coalescing, so the
            # same-host shortcuts (SHM map, path-lease mmap) are off
            conf.set(Keys.USER_SHORT_CIRCUIT_ENABLED, False)
            conf.set(Keys.USER_SHM_ENABLED, False)
            fs = FileSystem(c.master.address, conf=conf)
            try:
                path = "/smallread-batch.bin"
                payload = bytes(rng.randrange(256) for _ in range(4096))
                fs.write_all(path, payload * (size // 4096),
                             write_type="MUST_CACHE")
                with fs.open_file(path) as f:
                    # one block under test (a file spans several;
                    # offsets must stay inside block 0)
                    bs = f.block_stream(0)
                    offsets = _rand_offsets(rng, bs.length, read_bytes,
                                            ops)
                    sizes = [read_bytes] * ops
                    bs.pread(offsets[0], read_bytes)  # warm the channel
                    t0 = time.perf_counter()
                    per_op = [bs.pread(o, read_bytes) for o in offsets]
                    per_op_s = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    batched = bs.pread_many(offsets, sizes)
                    batched_s = time.perf_counter() - t0
            finally:
                fs.close()
    mismatches = sum(1 for a, b in zip(per_op, batched) if a != b)
    per_op_ops = ops / per_op_s if per_op_s > 0 else 0.0
    batched_ops = ops / batched_s if batched_s > 0 else 0.0
    speedup = (batched_ops / per_op_ops) if per_op_ops > 0 else 0.0
    ok = mismatches == 0 and speedup >= min_speedup
    if not ok:
        print(f"[smallread] batch speedup {speedup:.2f}x "
              f"(mismatches={mismatches}) misses the "
              f"{min_speedup}x gate", file=sys.stderr)
    return BenchResult(
        bench="smallread-batch",
        params={"file_mb": file_mb, "ops": ops,
                "read_bytes": read_bytes, "min_speedup": min_speedup},
        metrics={"per_op_ops_per_s": round(per_op_ops, 1),
                 "batched_ops_per_s": round(batched_ops, 1),
                 "speedup": round(speedup, 2),
                 "mismatches": mismatches,
                 "speedup_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def run_native(*, file_mb: int = 2, ops: int = 2000,
               read_bytes: int = 4096,
               min_speedup: float = 5.0) -> BenchResult:
    """``smallread-native-fastpath``: batched random-4k ops/s with the
    native plan executor on vs the pure-Python per-op path, byte
    identity asserted between both outputs and the source data."""
    import random
    import tempfile

    from alluxio_tpu.client import fastpath
    from alluxio_tpu.client.file_system import FileSystem
    from alluxio_tpu.conf import Keys
    from alluxio_tpu.metrics import metrics
    from alluxio_tpu.minicluster.local_cluster import LocalCluster

    t_start = time.monotonic()
    rng = random.Random(0x6D)
    size = file_mb << 20
    reps = 5
    native_ok = fastpath.available()
    shm_stream = False
    batches_moved = False
    mismatches = -1
    native_s = python_s = 0.0
    with tempfile.TemporaryDirectory(prefix="atpu-native-") as base:
        with LocalCluster(base, num_workers=1,
                          worker_mem_bytes=8 * size) as c:
            conf_off = c.conf.copy()
            conf_off.set(Keys.USER_NATIVE_FASTPATH_ENABLED, False)
            fs_on = c.file_system()
            fs_off = FileSystem(c.master.address, conf=conf_off)
            try:
                path = "/smallread-native.bin"
                payload = bytes(rng.randrange(256) for _ in range(4096))
                data = payload * (size // 4096)
                fs_on.write_all(path, data, write_type="MUST_CACHE")
                results = {}
                for tag, fs in (("native", fs_on), ("python", fs_off)):
                    with fs.open_file(path) as f:
                        bs = f.block_stream(0)
                        bs.pread(0, read_bytes)  # map the segment
                        if tag == "native":
                            shm_stream = bs.last_source == "SHM"
                            offsets = _rand_offsets(rng, bs.length,
                                                    read_bytes, ops)
                            sizes = [read_bytes] * ops
                        bs.pread_many(offsets[:8], sizes[:8])  # warm
                        before = metrics().counter(
                            "Client.NativeBatches").count
                        best = float("inf")
                        for _ in range(reps):
                            t0 = time.perf_counter()
                            out = bs.pread_many(offsets, sizes)
                            best = min(best,
                                       time.perf_counter() - t0)
                        results[tag] = out
                        if tag == "native":
                            native_s = best
                            batches_moved = metrics().counter(
                                "Client.NativeBatches").count > before
                        else:
                            python_s = best
                # byte identity: native == fallback == the written data
                expect = [data[o:o + read_bytes] for o in offsets]
                mismatches = sum(
                    1 for a, b, e in zip(results["native"],
                                         results["python"], expect)
                    if a != b or a != e)
            finally:
                fs_on.close()
                fs_off.close()
    native_ops = ops / native_s if native_s > 0 else 0.0
    python_ops = ops / python_s if python_s > 0 else 0.0
    speedup = (native_ops / python_ops) if python_ops > 0 else 0.0
    ok = (native_ok and shm_stream and batches_moved
          and mismatches == 0 and speedup >= min_speedup)
    if not ok:
        print(f"[smallread] native fastpath row failed: "
              f"available={native_ok} shm_stream={shm_stream} "
              f"native_ran={batches_moved} mismatches={mismatches} "
              f"speedup {speedup:.2f}x vs the {min_speedup}x gate",
              file=sys.stderr)
    return BenchResult(
        bench="smallread-native-fastpath",
        params={"file_mb": file_mb, "ops": ops,
                "read_bytes": read_bytes, "min_speedup": min_speedup},
        metrics={"native_available": native_ok,
                 "shm_stream": shm_stream,
                 "native_exec_ran": batches_moved,
                 "python_ops_per_s": round(python_ops, 1),
                 "native_ops_per_s": round(native_ops, 1),
                 "speedup": round(speedup, 2),
                 "mismatches": mismatches,
                 "speedup_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def run_shm(*, file_mb: int = 2, ops: int = 200,
            read_bytes: int = 4096) -> BenchResult:
    """``smallread-shm-zerocopy``: buffer-identity + no-wire fidelity
    of the same-host SHM plane."""
    import random
    import tempfile

    import numpy as np

    from alluxio_tpu.minicluster.local_cluster import LocalCluster
    from alluxio_tpu.utils.tracing import set_tracing_enabled, tracer

    t_start = time.monotonic()
    rng = random.Random(0x5C)
    size = file_mb << 20
    shm_stream = False
    identity_ok = False
    bytes_ok = False
    wire_ms = 0.0
    setup_phases = {}
    reads_per_s = 0.0
    try:
        with tempfile.TemporaryDirectory(prefix="atpu-shm-") as base:
            with LocalCluster(base, num_workers=1,
                              worker_mem_bytes=8 * size) as c:
                fs = c.file_system()
                path = "/smallread-shm.bin"
                payload = bytes(rng.randrange(256) for _ in range(4096))
                data = payload * (size // 4096)
                fs.write_all(path, data, write_type="MUST_CACHE")
                with fs.open_file(path) as f:
                    set_tracing_enabled(True)
                    tracer().clear()
                    with tracer().span("atpu.bench.shmread") as sp:
                        bs = f.block_stream(0)
                        first = bs.pread(0, read_bytes)
                        # block 0 only: a file spans several blocks and
                        # each block maps its own segment
                        offsets = _rand_offsets(rng, bs.length,
                                                read_bytes, ops)
                        t0 = time.perf_counter()
                        views = [bs.pread_view(o, read_bytes)
                                 for o in offsets]
                        elapsed = time.perf_counter() - t0
                    set_tracing_enabled(False)
                    shm_stream = bs.last_source == "SHM"
                    reads_per_s = ops / elapsed if elapsed > 0 else 0.0
                    bytes_ok = first == data[:read_bytes] and all(
                        bytes(v) == data[o:o + read_bytes]
                        for v, o in zip(views, offsets))
                    # buffer identity: every view aliases the ONE mmap
                    # (.obj is the exporting object), and the whole-
                    # block ndarray shares that memory — zero copies
                    nv = bs.numpy_view()
                    identity_ok = bool(views) and all(
                        v.obj is views[0].obj for v in views) and \
                        np.shares_memory(nv, np.asarray(views[0]))
                    for name, ms in (sp.phases or []):
                        if name in ("wire", "serialize"):
                            wire_ms += ms
                        else:
                            setup_phases[name] = round(
                                setup_phases.get(name, 0.0) + ms, 3)
                    del nv, views
                fs.close()
    finally:
        set_tracing_enabled(False)
        tracer().clear()
    ok = shm_stream and identity_ok and bytes_ok and wire_ms == 0.0
    if not ok:
        print(f"[smallread] shm row failed: shm_stream={shm_stream} "
              f"identity_ok={identity_ok} bytes_ok={bytes_ok} "
              f"wire_ms={wire_ms}", file=sys.stderr)
    return BenchResult(
        bench="smallread-shm-zerocopy",
        params={"file_mb": file_mb, "ops": ops,
                "read_bytes": read_bytes},
        metrics={"shm_stream": shm_stream,
                 "buffer_identity_ok": identity_ok,
                 "bytes_ok": bytes_ok,
                 "wire_serialize_ms": round(wire_ms, 3),
                 "setup_phases": setup_phases,
                 "reads_per_s": round(reads_per_s, 1),
                 "zerocopy_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)
