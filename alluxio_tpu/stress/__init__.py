"""Stress benchmark suite.

Re-design of the reference ``stress/`` module
(``stress/shell/src/main/java/alluxio/stress/cli/*``): each bench drives
one BASELINE.md config against an in-process LocalCluster (default) or a
live cluster (``--master``), and emits exactly one JSON result line on
stdout — the ``IOTaskSummary``/``MasterBenchSummary`` analogue.
"""

from alluxio_tpu.stress.base import BenchResult, drive, percentiles

__all__ = ["BenchResult", "drive", "percentiles"]
