"""On-device stages for BASELINE configs #2–#5, run by ``bench.py``
after the headline (config #1) on the SAME live cluster + device.

Each stage emits one structured row with an explicit ``vs_baseline``.
The baselines are self-calibrating against THIS environment's measured
ceilings (the r02 discipline: the axon tunnel's h2d rate drifts
minute-to-minute, so absolute targets would grade the weather, not the
framework):

  #2 random-4k    achieved 4k-record read->batch->HBM rate vs the raw
                  mmap+device_put ceiling measured adjacently (target
                  >=0.5x: batching small records costs at most half the
                  raw sequential path; the FUSE analogue in the
                  reference pays a kernel crossing per read instead)
  #3 prefetch     distributedLoad fan-out into 2 workers then stream to
                  HBM vs streaming a pre-warmed set (target >=0.7x: the
                  load job must not leave the tiers colder than a plain
                  warm-up)
  #4 projection   3-of-23-column Parquet read into device arrays vs the
                  full-scan wall time (target: speedup >= 3x, the
                  byte-selectivity bound from BENCH_SUITE history)
  #5 write-evict  CACHE_THROUGH ingest under 2x memory pressure with
                  LRFU eviction vs the unpressured cold-write rate of
                  config #1 (target >=0.5x: eviction + UFS write-through
                  may halve ingest but must not collapse it)

Reference analogues: ``AlluxioFuseFileSystem.java:52-55`` random reads,
``LoadDefinition.java:65`` fan-out, ``AlluxioCatalog.java:55`` +
transform path, ``TieredBlockStore.java:85`` + ``LRFUAnnotator.java:29``.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Callable, Dict, List

import numpy as np


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


#: merged into every row (and its TPU-CONFIG stderr line) — set by
#: ``run_all(row_extra=...)`` so a host-fallback run's rows are
#: labelled AT EMIT TIME, not post-hoc (an unlabelled stderr line
#: would read as device evidence to anyone grepping logs)
_ROW_EXTRA: Dict = {}


def _row(config: str, metric: str, value: float, unit: str,
         vs_baseline: float, **extra) -> Dict:
    row = {"config": config, "metric": metric,
           "value": round(value, 3), "unit": unit,
           "vs_baseline": round(vs_baseline, 3), **extra,
           **_ROW_EXTRA}
    log("TPU-CONFIG " + json.dumps(row, sort_keys=True))
    return row


def config2_random_4k(jax, fs, device, *, shard_bytes: int,
                      num_shards: int = 4, reads: int = 4096,
                      batch: int = 256) -> Dict:
    """Random 4k reads from the warm host tier, batched into HBM."""
    import jax.numpy as jnp

    from alluxio_tpu.client.streams import WriteType

    rng = np.random.default_rng(7)
    paths = []
    for i in range(num_shards):
        p = f"/bench/r4k-{i}"
        fs.write_all(p, rng.integers(0, 255, size=shard_bytes,
                                     dtype=np.uint8).tobytes(),
                     write_type=WriteType.MUST_CACHE)
        paths.append(p)
    # ceiling: sequential mmap of one shard + one device_put of it
    t0 = time.monotonic()
    blob = fs.read_all(paths[0])
    arr = np.frombuffer(blob, dtype=np.uint8)
    jax.device_put(arr, device).block_until_ready()
    ceil_rate = shard_bytes / (time.monotonic() - t0)

    handles = [fs.open_file(p) for p in paths]
    offsets = rng.integers(0, shard_bytes - 4096, size=reads)
    shards = rng.integers(0, num_shards, size=reads)
    t0 = time.monotonic()
    buf = np.empty((batch, 4096), dtype=np.uint8)
    done = 0
    devs = []
    for i in range(reads):
        h = handles[shards[i]]
        h.seek(int(offsets[i]))
        buf[done % batch] = np.frombuffer(h.read(4096), dtype=np.uint8)
        done += 1
        if done % batch == 0:  # batch lands in HBM
            devs.append(jax.device_put(buf.copy(), device))
    jax.block_until_ready(devs)
    dt = time.monotonic() - t0
    for h in handles:
        h.close()
    rate = reads * 4096 / dt
    return _row("2-random-4k",
                "random 4k reads batched into HBM", rate / 1e6, "MB/s",
                (rate / ceil_rate) / 0.5,
                ops_per_s=round(reads / dt, 1),
                ceiling_mb_per_s=round(ceil_rate / 1e6, 2),
                achieved_vs_ceiling=round(rate / ceil_rate, 3))


def config3_prefetch(jax, device, *, file_bytes: int,
                     num_files: int = 4, num_workers: int = 2) -> Dict:
    """DistributedLoad fan-out on its own multi-worker cluster, then
    stream the prefetched set into HBM (the cold corpus leg mirrors
    ``stress/prefetch_bench.py``; this adds the device leg)."""
    from alluxio_tpu.client.streams import WriteType
    from alluxio_tpu.conf import Keys
    from alluxio_tpu.stress.cluster import bench_cluster

    rng = np.random.default_rng(11)
    total = num_files * file_bytes
    with bench_cluster(None, num_workers=num_workers,
                       block_size=4 << 20,
                       worker_mem_bytes=total + (128 << 20),
                       start_job_service=True,
                       start_worker_heartbeats=True,
                       conf_overrides={
                           Keys.WORKER_BLOCK_HEARTBEAT_INTERVAL: "50ms",
                       }) as (fs, cluster):
        for i in range(num_files):
            fs.write_all(f"/pf/f-{i}",
                         rng.integers(0, 255, size=file_bytes,
                                      dtype=np.uint8).tobytes(),
                         write_type=WriteType.CACHE_THROUGH)
        # warm reference: cached set streamed to HBM
        t0 = time.monotonic()
        ref = [jax.device_put(
            np.frombuffer(fs.read_all(f"/pf/f-{i}"), dtype=np.uint8),
            device) for i in range(num_files)]
        jax.block_until_ready(ref)
        ref_rate = total / (time.monotonic() - t0)
        del ref
        # make the corpus cold, fan the load out, re-stream
        for i in range(num_files):
            fs.free(f"/pf/f-{i}", forced=True)
        job_client = cluster.job_client()
        t0 = time.monotonic()
        job_id = job_client.run({"type": "load", "path": "/pf",
                                 "replication": 1})
        info = job_client.wait_for_job(job_id, timeout_s=300.0)
        t_load = time.monotonic() - t0
        if info.status != "COMPLETED":
            raise RuntimeError(f"load job {info.status}: "
                               f"{info.error_message}")
        t0 = time.monotonic()
        out = [jax.device_put(
            np.frombuffer(fs.read_all(f"/pf/f-{i}"), dtype=np.uint8),
            device) for i in range(num_files)]
        jax.block_until_ready(out)
        rate = total / (time.monotonic() - t0)
        del out
        return _row("3-distributed-prefetch",
                    "post-prefetch stream to HBM", rate / 1e6, "MB/s",
                    (rate / ref_rate) / 0.7,
                    load_seconds=round(t_load, 2),
                    prefetch_mb_per_s=round(total / t_load / 1e6, 2),
                    warm_reference_mb_per_s=round(ref_rate / 1e6, 2))


def config4_projection(jax, fs, device, *, rows_per_part: int = 30_000,
                       partitions: int = 2) -> Dict:
    """Parquet column projection into device arrays vs full scan."""
    import io

    import pyarrow as pa
    import pyarrow.parquet as pq

    from alluxio_tpu.table.reader import open_parquet

    rng = np.random.default_rng(13)
    cols = {f"c{i}": rng.standard_normal(rows_per_part).astype(np.float32)
            for i in range(20)}
    cols["label"] = rng.integers(0, 1000, size=rows_per_part,
                                 dtype=np.int32)
    cols["id"] = np.arange(rows_per_part, dtype=np.int64)
    cols["weight"] = rng.standard_normal(rows_per_part).astype(np.float32)
    table = pa.table(cols)
    sink = io.BytesIO()
    pq.write_table(table, sink)
    blob = sink.getvalue()
    paths = []
    for p in range(partitions):
        path = f"/bench/proj-{p}.parquet"
        fs.write_all(path, blob)
        paths.append(path)
    want = ["c0", "label", "weight"]
    # warm footers
    for p in paths:
        open_parquet(fs, p)
    t0 = time.monotonic()
    full = [open_parquet(fs, p).read() for p in paths]
    t_full = time.monotonic() - t0
    n_full = sum(t.nbytes for t in full)
    del full
    t0 = time.monotonic()
    devs = []
    for p in paths:
        t = open_parquet(fs, p).read(columns=want)
        for name in want:
            devs.append(jax.device_put(
                np.ascontiguousarray(t.column(name).to_numpy()), device))
    jax.block_until_ready(devs)
    t_proj = time.monotonic() - t0
    speedup = t_full / t_proj if t_proj > 0 else 0.0
    return _row("4-parquet-projection",
                "3-of-23-column projection speedup into HBM", speedup,
                "x", speedup / 3.0,
                full_scan_s=round(t_full, 3),
                projection_s=round(t_proj, 3),
                full_bytes=n_full)


def config5_write_eviction(*, cold_write_rate: float) -> Dict:
    """CACHE_THROUGH ingest under memory pressure (dataset ~3x the MEM
    tier, LRFU, SSD spill): reuses the pressured-cluster write bench
    (``stress/write_bench.py``) and grades its ingest against the
    unpressured cold-write rate config #1 measured."""
    from alluxio_tpu.stress import write_bench

    r = write_bench.run()
    rate = r.metrics["ingest_mb_per_s"] * 1e6
    return _row("5-write-through-eviction",
                "CACHE_THROUGH ingest under memory pressure",
                rate / 1e6, "MB/s",
                (rate / cold_write_rate) / 0.5 if cold_write_rate else 0.0,
                unpressured_cold_write_mb_per_s=round(
                    cold_write_rate / 1e6, 2),
                time_to_durable_s=r.metrics.get("time_to_durable_s"),
                tier_used_bytes=r.metrics.get("tier_used_bytes"))


def run_all(jax, fs, device, *, shard_bytes: int,
            cold_write_rate: float, out_path: str = "",
            row_extra: Dict = None) -> List[Dict]:
    """Run the four stages, tolerating per-stage failure (a wedged stage
    must not cost the headline metric its stdout line). ``fs`` is the
    headline cluster's client (configs #2/#4 reuse its warm worker);
    configs #3/#5 provision their own clusters. ``row_extra`` is merged
    into every row + stderr line (host-fallback labelling)."""
    global _ROW_EXTRA
    _ROW_EXTRA = dict(row_extra or {})
    rows: List[Dict] = []
    stages: List[Callable[[], Dict]] = [
        lambda: config2_random_4k(jax, fs, device,
                                  shard_bytes=min(shard_bytes, 64 << 20)),
        lambda: config3_prefetch(jax, device,
                                 file_bytes=min(shard_bytes, 32 << 20)),
        lambda: config4_projection(jax, fs, device),
        lambda: config5_write_eviction(cold_write_rate=cold_write_rate),
    ]
    try:
        for stage in stages:
            try:
                rows.append(stage())
            except Exception as e:  # noqa: BLE001
                log(f"TPU-CONFIG stage failed: {type(e).__name__}: {e}")
    finally:
        _ROW_EXTRA = {}
    if out_path and rows:
        try:
            with open(out_path, "w") as f:
                json.dump(rows, f, indent=1, sort_keys=True)
        except OSError as e:
            log(f"could not write {out_path}: {e}")
    return rows
