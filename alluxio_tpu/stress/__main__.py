"""Stress CLI: ``python -m alluxio_tpu.stress <bench> [options]``.

Reference: ``stress/shell/src/main/java/alluxio/stress/cli/*`` — each
bench prints exactly ONE JSON summary line on stdout (diagnostics on
stderr), so drivers can pipe results.

Benches:
  worker       worker read throughput (--mode sequential|random) [#1/#2]
  master       master metadata op/s (--op CreateFile|GetStatus|...)
  maxthroughput  binary-search max sustainable master op/s
  prefetch     distributed load across N workers [#3]
  table        Parquet column-projection via the catalog [#4]
  write        async write-through under eviction pressure [#5]
  suite        run the whole BASELINE config family
"""

from __future__ import annotations

import argparse
import json
import sys


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--master", default=None,
                   help="host:port of a live cluster (default: in-process)")
    p.add_argument("--threads", type=int, default=8)
    p.add_argument("--duration", type=float, default=5.0,
                   metavar="SECONDS")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="alluxio-tpu stress")
    sub = ap.add_subparsers(dest="bench", required=True)

    w = sub.add_parser("worker", help="worker read bench (configs #1/#2)")
    _add_common(w)
    w.add_argument("--mode", choices=("sequential", "random"),
                   default="random")
    w.add_argument("--shard-mb", type=int, default=64)
    w.add_argument("--num-shards", type=int, default=4)
    w.add_argument("--read-bytes", type=int, default=4096)

    m = sub.add_parser("master", help="master metadata op/s")
    _add_common(m)
    from alluxio_tpu.stress.master_bench import OPS

    m.add_argument("--op", choices=OPS, default="CreateFile")
    m.add_argument("--fixed-count", type=int, default=200)
    m.add_argument("--target-ops", type=float, default=0.0)

    x = sub.add_parser("maxthroughput",
                       help="binary-search max sustainable master op/s")
    _add_common(x)
    x.add_argument("--op", choices=OPS, default="CreateFile")
    x.add_argument("--fixed-count", type=int, default=200)

    p = sub.add_parser("prefetch", help="distributed load (config #3)")
    p.add_argument("--num-workers", type=int, default=4)
    p.add_argument("--num-files", type=int, default=8)
    p.add_argument("--file-mb", type=int, default=16)
    p.add_argument("--replication", type=int, default=1)
    p.add_argument("--pressure", action="store_true",
                   help="size tiers so eviction fires mid-load")
    p.add_argument("--kill-worker", action="store_true",
                   help="stop a worker mid-job; plan must survive")
    p.add_argument("--clairvoyant", action="store_true",
                   help="run the oracle->scheduler->agent loop instead: "
                        "seeded multi-epoch DeviceBlockLoader read "
                        "reporting hit-rate + block-ready lateness")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--lookahead", type=int, default=16)
    p.add_argument("--budget-mb", type=int, default=128)
    p.add_argument("--hbm-fraction", type=float, default=0.0)

    t = sub.add_parser("table", help="column projection (config #4)")
    t.add_argument("--master", default=None)
    t.add_argument("--partitions", type=int, default=4)
    t.add_argument("--rows", type=int, default=40_000)
    t.add_argument("--row", choices=["projection", "pushdown"],
                   default="projection",
                   help="pushdown: planned-vs-legacy gated comparison "
                        "(docs/table_reads.md)")
    t.add_argument("--min-speedup", type=float, default=None,
                   help="gate: pushdown row fails below this planned/"
                        "legacy ratio (default 2.0); projection row "
                        "below this full-scan/projection ratio "
                        "(default 4.0)")

    wr = sub.add_parser("write", help="write-through eviction (config #5)")
    wr.add_argument("--threads", type=int, default=4)
    wr.add_argument("--num-files", type=int, default=24)
    wr.add_argument("--file-mb", type=int, default=8)
    wr.add_argument("--mem-mb", type=int, default=64)

    ob = sub.add_parser("obs", help="observability rows: tracing/"
                                    "profiler overhead + critical-path "
                                    "attribution fidelity")
    ob.add_argument("--row", choices=("tracing", "profile",
                                      "critical-path"),
                    default="tracing",
                    help="which obs row: tracing overhead (default), "
                         "stack-sampler overhead, or critical-path "
                         "attribution")
    ob.add_argument("--file-mb", type=int, default=4)
    ob.add_argument("--reads", type=int, default=60,
                    help="reads per alternating batch (tracing/profile) "
                         "or total random preads (critical-path)")
    ob.add_argument("--batches", type=int, default=5)
    ob.add_argument("--span-iterations", type=int, default=100_000)
    ob.add_argument("--sample-interval-ms", type=int, default=0,
                    help="profiler row: stack-sampling interval under "
                         "test (0 = the shipped conf default)")
    ob.add_argument("--read-bytes", type=int, default=4096,
                    help="critical-path row: bytes per random pread")
    ob.add_argument("--max-overhead-pct", type=float, default=2.0,
                    help="fail the overhead rows above this delta")
    ob.add_argument("--min-attributed-pct", type=float, default=90.0,
                    help="fail the critical-path row when named phases "
                         "explain less of root wall time than this")

    sr = sub.add_parser("smallread",
                        help="small-read data plane: batched random-4k "
                             "over real gRPC vs per-op RPCs, and "
                             "same-host SHM zero-copy fidelity "
                             "(buffer identity, no wire phase)")
    sr.add_argument("--row", choices=("batch", "shm", "native"),
                    default="batch",
                    help="which row: read_many coalescing speedup "
                         "(default), SHM zero-copy fidelity, or native "
                         "fastpath batched scatter speedup")
    sr.add_argument("--file-mb", type=int, default=2)
    sr.add_argument("--ops", type=int, default=None,
                    help="random preads measured (default: 400 batch "
                         "row, 200 shm row, 2000 native row)")
    sr.add_argument("--read-bytes", type=int, default=4096)
    sr.add_argument("--min-speedup", type=float, default=3.0,
                    help="batch row: fail below this batched/per-op "
                         "ops/s ratio")

    he = sub.add_parser("health", help="metrics-history ingestion "
                                       "overhead on the heartbeat hot "
                                       "path (fake-clock harness)")
    he.add_argument("--sources", type=int, default=64)
    he.add_argument("--metrics-per-source", type=int, default=120,
                    help="snapshot size per heartbeat (a live worker "
                         "ships ~100-150 entries once timers expand)")
    he.add_argument("--ticks", type=int, default=40)
    he.add_argument("--batches", type=int, default=8)
    he.add_argument("--max-overhead-pct", type=float, default=5.0,
                    help="fail the bench above this heartbeat-handling "
                         "overhead with history enabled")

    sh = sub.add_parser("selfheal",
                        help="remediation engine: detection->action "
                             "latency + health-tick overhead "
                             "(fake-clock harness)")
    sh.add_argument("--sources", type=int, default=64,
                    help="fleet size driving the health tick (matches "
                         "bench-health's model); the engine's cost is "
                         "per-tick constant, the tick scales with this")
    sh.add_argument("--ticks", type=int, default=60)
    sh.add_argument("--batches", type=int, default=6)
    sh.add_argument("--eval-interval", type=float, default=5.0,
                    help="simulated health-eval period (seconds)")
    sh.add_argument("--fire-after", type=float, default=10.0,
                    help="simulated alert fire debounce (seconds)")
    sh.add_argument("--max-overhead-pct", type=float, default=2.0,
                    help="fail the bench above this added health-tick "
                         "overhead with the engine attached")

    uc = sub.add_parser("ufscold", help="striped vs single-stream cold "
                                        "UFS reads (connection-limited "
                                        "UFS model)")
    uc.add_argument("--block-mb", type=int, default=2)
    uc.add_argument("--stripe-kb", type=int, default=512)
    uc.add_argument("--blocks-per-reader", type=int, default=3)
    uc.add_argument("--rtt-ms", type=float, default=25.0,
                    help="modeled per-connection round trip; must dwarf "
                         "the host's thread-wake jitter")
    uc.add_argument("--conn-mbps", type=float, default=4.0,
                    help="modeled per-connection UFS bandwidth")
    uc.add_argument("--concurrency", type=int, default=4,
                    help="stripes in flight per block")
    uc.add_argument("--per-mount-limit", type=int, default=64)
    uc.add_argument("--min-speedup", type=float, default=1.5,
                    help="fail below this striped/single throughput "
                         "ratio at 4 concurrent readers")

    rr = sub.add_parser("remoteread",
                        help="striped vs single-stream warm remote reads "
                             "(bandwidth-limited-per-connection worker "
                             "model) + hedged straggler drill")
    rr.add_argument("--block-mb", type=int, default=4)
    rr.add_argument("--stripe-kb", type=int, default=1024)
    rr.add_argument("--stripes", type=int, default=4,
                    help="concurrent range streams per read")
    rr.add_argument("--rtt-ms", type=float, default=20.0,
                    help="modeled per-stream round trip; must dwarf the "
                         "host's thread-wake jitter")
    rr.add_argument("--conn-mbps", type=float, default=16.0,
                    help="modeled per-connection worker bandwidth")
    rr.add_argument("--blocks", type=int, default=3,
                    help="blocks read per variant")
    rr.add_argument("--hedge-quantile", type=float, default=0.95)
    rr.add_argument("--stall-ms", type=float, default=300.0,
                    help="injected straggler stall before first byte")
    rr.add_argument("--min-speedup", type=float, default=1.5,
                    help="fail below this striped/single throughput ratio")

    qo = sub.add_parser("qos",
                        help="two-tenant QoS: victim read p99 under an "
                             "abusive tenant's flood with/without QoS, "
                             "plus admission-limiter bounded-memory "
                             "shedding (modeled UFS, fake-clock "
                             "limiter)")
    qo.add_argument("--rtt-ms", type=float, default=40.0,
                    help="modeled per-read UFS round trip; must dwarf "
                         "the host's thread-wake jitter")
    qo.add_argument("--block-kb", type=int, default=64)
    qo.add_argument("--victim-reads", type=int, default=12)
    qo.add_argument("--flood-blocks", type=int, default=48,
                    help="abusive-tenant backlog per wave (two waves)")
    qo.add_argument("--per-mount-limit", type=int, default=4)
    qo.add_argument("--tenant-limit", type=int, default=2)
    qo.add_argument("--max-degradation", type=float, default=2.0,
                    help="fail when the victim's flooded p99 exceeds "
                         "this multiple of its solo p99 with QoS on")
    qo.add_argument("--admission-checks", type=int, default=200_000)
    qo.add_argument("--admission-principals", type=int, default=20_000)
    qo.add_argument("--admission-max-principals", type=int, default=512)

    md = sub.add_parser("metadata",
                        help="metadata control-plane gates: striped "
                             "inode locking + journal group commit vs "
                             "the single-lock master (modeled slow "
                             "fsync), and warm client-metadata-cache "
                             "GetStatus vs uncached RPCs")
    md.add_argument("--row", choices=("striped", "journal", "cached",
                                      "hot-dir", "lsm-capacity"),
                    default="striped")
    md.add_argument("--threads", type=int, default=None,
                    help="driver threads (default 8; cached row 4)")
    md.add_argument("--duration", type=float, default=None,
                    metavar="SECONDS",
                    help="per-mode measure window (default 2.0; "
                         "cached row 1.5)")
    md.add_argument("--fsync-ms", type=float, default=3.0,
                    help="modeled journal fsync cost (local-disk/NFS "
                         "class); must dwarf scheduler jitter")
    md.add_argument("--batch-time-ms", type=float, default=2.0,
                    help="group-commit coalescing window under test")
    md.add_argument("--min-speedup", type=float, default=None,
                    help="gate ratio (defaults: striped 3x, journal "
                         "1.5x, cached 10x)")
    md.add_argument("--master", default=None,
                    help="cached row only: attach to a live cluster")
    md.add_argument("--inodes", type=int, default=10_000_000,
                    help="lsm-capacity row: namespace size to build "
                         "under the cap")
    md.add_argument("--cap-mb", type=int, default=2048,
                    help="lsm-capacity row: RLIMIT_AS cap per backend "
                         "subprocess (HEAP must blow it, LSM must fit)")

    ha = sub.add_parser("ha", help="HA failover drill: kill the primary "
                                   "under live load; gates MTTR <= 2 "
                                   "election timeouts, zero acked-write "
                                   "loss, standby staleness contract")
    ha.add_argument("--masters", type=int, default=3)
    ha.add_argument("--election-timeout", type=float, default=2.0,
                    metavar="SECONDS",
                    help="election timeout upper bound (seconds-scale "
                         "on purpose: the in-process quorum shares one "
                         "GIL with the load; the gate must measure "
                         "failover, not scheduler jitter)")
    ha.add_argument("--warmup", type=float, default=2.0,
                    help="seconds of load before the kill")

    sub.add_parser("suite", help="run the whole BASELINE config family")
    rp = sub.add_parser("report",
                        help="render suite JSON to a single-file HTML "
                             "report (graphs + tables)")
    rp.add_argument("--input", default="BENCH_SUITE.json")
    rp.add_argument("--out", default="BENCH_REPORT.html")
    return ap


SUITE = (
    ("worker-sequential", ["worker", "--mode", "sequential",
                           "--threads", "4", "--duration", "5"]),
    ("worker-random-4k", ["worker", "--mode", "random",
                          "--threads", "8", "--duration", "5"]),
    ("master-CreateFile", ["master", "--op", "CreateFile",
                           "--threads", "8", "--duration", "5"]),
    ("master-GetStatus", ["master", "--op", "GetStatus",
                          "--threads", "8", "--duration", "5"]),
    ("master-ListStatus", ["master", "--op", "ListStatus", "--threads",
                           "8", "--duration", "5",
                           "--fixed-count", "100"]),
    ("master-ListStatus-large", ["master", "--op", "ListStatusStream",
                                 "--threads", "2", "--duration", "6",
                                 "--fixed-count", "10000"]),
    ("master-DeleteFile", ["master", "--op", "DeleteFile", "--threads",
                           "8", "--duration", "5",
                           "--fixed-count", "2000"]),
    ("prefetch", ["prefetch", "--num-workers", "4", "--num-files", "8",
                  "--file-mb", "16"]),
    ("prefetch-fault-drill", ["prefetch", "--num-workers", "4",
                              "--num-files", "8", "--file-mb", "8",
                              "--replication", "2", "--pressure",
                              "--kill-worker"]),
    ("prefetch-clairvoyant", ["prefetch", "--clairvoyant",
                              "--num-workers", "1",
                              "--num-files", "4", "--file-mb", "8",
                              "--epochs", "2"]),
    ("table-projection", ["table"]),
    ("table-projection-pushdown", ["table", "--row", "pushdown"]),
    ("write-eviction", ["write"]),
    ("obs-tracing-overhead", ["obs"]),
    ("obs-profile-overhead", ["obs", "--row", "profile"]),
    ("obs-critical-path", ["obs", "--row", "critical-path",
                           "--file-mb", "2", "--reads", "80"]),
    ("smallread-batch", ["smallread", "--row", "batch"]),
    ("smallread-shm-zerocopy", ["smallread", "--row", "shm"]),
    ("smallread-native-fastpath", ["smallread", "--row", "native",
                                   "--min-speedup", "5.0"]),
    ("health-ingest-overhead", ["health"]),
    ("selfheal-remediation", ["selfheal"]),
    ("ufs-cold-read", ["ufscold"]),
    ("remote-warm-read", ["remoteread"]),
    ("qos-two-tenant", ["qos"]),
    ("metadata-striped", ["metadata", "--row", "striped"]),
    ("metadata-cached-getstatus", ["metadata", "--row", "cached"]),
    ("metadata-journal-batch", ["metadata", "--row", "journal"]),
    ("metadata-hot-dir", ["metadata", "--row", "hot-dir"]),
    # scaled down for the suite's per-bench timeout; `make
    # bench-metadata` runs the full 10M-inode row
    ("metadata-lsm-capacity", ["metadata", "--row", "lsm-capacity",
                               "--inodes", "1000000",
                               "--cap-mb", "1024"]),
    ("ha-failover", ["ha"]),
)


#: sentinel bench name for the host-speed stamp row — consumers
#: (bench.py suite counting) must exclude it by THIS constant
HOST_CALIBRATION_BENCH = "host-calibration"


def _host_calibration():
    """A suite run is only comparable to another on a like-for-like
    host: the CI container's per-core speed drifts several-fold between
    sessions (observed: 10M-adds 2126 ms on one allocation vs ~600 ms
    on another — every GIL-bound op/s row scales with it). This row
    stamps each BENCH_SUITE with the host's measured speed so later
    readers can normalize instead of mistaking allocation drift for
    code regressions."""
    import os
    import platform

    from alluxio_tpu.stress.base import BenchResult, host_speed_stamp_ms

    loop_ms = host_speed_stamp_ms()
    cores = os.cpu_count() or 0
    return BenchResult(
        bench=HOST_CALIBRATION_BENCH,
        params={"python": platform.python_version(), "cores": cores},
        metrics={"python_10m_adds_ms": loop_ms,
                 "note": "GIL-bound op/s rows scale ~inversely with "
                         "python_10m_adds_ms; compare suites only "
                         "after normalizing"},
        errors=0, duration_s=round(loop_ms / 1000, 3))


def run_suite() -> list:
    """The five BASELINE configs + master-op samples, each in its OWN
    subprocess: a bench must not inherit the previous one's page-cache
    pressure, lingering cluster threads or fragmented heap (sequential
    in-process runs measured 2-4x slower than isolated ones for the
    later benches). Returns the list of BenchResults."""
    import os
    import subprocess
    import time

    from alluxio_tpu.stress.base import BenchResult

    env = dict(os.environ)
    # accelerator plugin adds ~2.4s boot + a PJRT init to every child;
    # the stress suite is host-side
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    results = [_host_calibration()]
    print(results[0].json_line(), flush=True)
    for bench_i, (name, argv) in enumerate(SUITE):
        print(f"[suite] running {name} ...", file=sys.stderr, flush=True)
        proc = None
        try:
            if bench_i:
                # let the previous bench's teardown IO (tmpdir deletion,
                # page-cache writeback) drain — it measured 2-3x into
                # the next bench's tail latencies on a 1-core host
                os.sync()
                time.sleep(4)
            proc = subprocess.run(
                [sys.executable, "-m", "alluxio_tpu.stress", *argv],
                capture_output=True, text=True, timeout=600, env=env)
            out_lines = (proc.stdout or "").strip().splitlines()
            if not out_lines:
                raise RuntimeError(
                    f"bench child produced no output (rc="
                    f"{proc.returncode})")
            d = json.loads(out_lines[-1])
            r = BenchResult(bench=d["bench"], params=d["params"],
                            metrics=d["metrics"], errors=d["errors"],
                            duration_s=d["duration_s"])
        except Exception as e:  # noqa: BLE001 — record and continue
            r = BenchResult(bench=name, params={}, metrics={},
                            errors=1, duration_s=0.0)
            # on TimeoutExpired proc was never assigned, but
            # subprocess.run attaches the drained output to the
            # exception itself
            src = proc if proc is not None else e
            tail = getattr(src, "stderr", None) or ""
            if isinstance(tail, bytes):  # TimeoutExpired keeps bytes
                tail = tail.decode(errors="replace")
            tail = tail[-2000:]
            # the child's stderr tail goes IN THE ROW: a bare exception
            # name from the wrapper's own parse (observed:
            # 'IndexError' on empty stdout) is undiagnosable later
            r.metrics["error"] = f"{type(e).__name__}: {e}"
            if tail:
                r.metrics["child_stderr_tail"] = tail
            print(f"[suite] {name} FAILED: {e} {tail}", file=sys.stderr)
        print(r.json_line(), flush=True)
        results.append(r)
    return results


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.bench == "worker":
        from alluxio_tpu.stress.worker_bench import run

        r = run(mode=args.mode, master=args.master, threads=args.threads,
                duration_s=args.duration, shard_bytes=args.shard_mb << 20,
                num_shards=args.num_shards, read_bytes=args.read_bytes)
    elif args.bench == "master":
        from alluxio_tpu.stress.master_bench import run

        r = run(op=args.op, master=args.master, threads=args.threads,
                duration_s=args.duration, fixed_count=args.fixed_count,
                target_ops_per_s=args.target_ops)
    elif args.bench == "maxthroughput":
        from alluxio_tpu.stress.master_bench import run_max_throughput

        r = run_max_throughput(op=args.op, master=args.master,
                               threads=args.threads,
                               duration_s=args.duration,
                               fixed_count=args.fixed_count)
    elif args.bench == "prefetch":
        if args.clairvoyant:
            # flags of the DistributedLoad variant that the clairvoyant
            # run does not model — failing beats silently ignoring them
            if args.pressure or args.kill_worker or \
                    args.replication != 1:
                print("--pressure/--kill-worker/--replication do not "
                      "apply to --clairvoyant", file=sys.stderr)
                return 2
            from alluxio_tpu.stress.prefetch_bench import run_clairvoyant

            r = run_clairvoyant(num_workers=args.num_workers,
                                num_files=args.num_files,
                                file_bytes=args.file_mb << 20,
                                epochs=args.epochs, seed=args.seed,
                                lookahead_blocks=args.lookahead,
                                budget_bytes=args.budget_mb << 20,
                                hbm_fraction=args.hbm_fraction)
        else:
            from alluxio_tpu.stress.prefetch_bench import run

            r = run(num_workers=args.num_workers,
                    num_files=args.num_files,
                    file_bytes=args.file_mb << 20,
                    replication=args.replication, pressure=args.pressure,
                    kill_worker=args.kill_worker)
    elif args.bench == "table":
        if args.row == "pushdown":
            from alluxio_tpu.stress.table_bench import run_pushdown

            r = run_pushdown(master=args.master,
                             partitions=args.partitions,
                             rows_per_partition=args.rows,
                             min_speedup=args.min_speedup
                             if args.min_speedup is not None else 2.0)
        else:
            from alluxio_tpu.stress.table_bench import run

            r = run(master=args.master, partitions=args.partitions,
                    rows_per_partition=args.rows,
                    min_speedup=args.min_speedup
                    if args.min_speedup is not None else 4.0)
    elif args.bench == "write":
        from alluxio_tpu.stress.write_bench import run

        r = run(threads=args.threads, num_files=args.num_files,
                file_bytes=args.file_mb << 20,
                mem_bytes=args.mem_mb << 20)
    elif args.bench == "obs":
        if args.row == "profile":
            from alluxio_tpu.stress.obs_bench import run_profile_overhead

            r = run_profile_overhead(
                file_mb=args.file_mb, reads=args.reads,
                batches=args.batches,
                sample_interval_ms=args.sample_interval_ms,
                max_overhead_pct=args.max_overhead_pct)
        elif args.row == "critical-path":
            from alluxio_tpu.stress.obs_bench import run_critical_path

            r = run_critical_path(
                file_mb=args.file_mb, reads=args.reads,
                read_bytes=args.read_bytes,
                min_attributed_pct=args.min_attributed_pct)
        else:
            from alluxio_tpu.stress.obs_bench import run

            r = run(file_mb=args.file_mb, reads=args.reads,
                    batches=args.batches,
                    span_iterations=args.span_iterations,
                    max_overhead_pct=args.max_overhead_pct)
    elif args.bench == "smallread":
        if args.row == "shm":
            from alluxio_tpu.stress.smallread_bench import run_shm

            r = run_shm(file_mb=args.file_mb,
                        ops=args.ops if args.ops is not None else 200,
                        read_bytes=args.read_bytes)
        elif args.row == "native":
            from alluxio_tpu.stress.smallread_bench import run_native

            r = run_native(file_mb=args.file_mb,
                           ops=args.ops if args.ops is not None else 2000,
                           read_bytes=args.read_bytes,
                           min_speedup=args.min_speedup)
        else:
            from alluxio_tpu.stress.smallread_bench import run_batch

            r = run_batch(file_mb=args.file_mb,
                          ops=args.ops if args.ops is not None else 400,
                          read_bytes=args.read_bytes,
                          min_speedup=args.min_speedup)
    elif args.bench == "health":
        from alluxio_tpu.stress.health_bench import run

        r = run(sources=args.sources,
                metrics_per_source=args.metrics_per_source,
                ticks=args.ticks, batches=args.batches,
                max_overhead_pct=args.max_overhead_pct)
    elif args.bench == "selfheal":
        from alluxio_tpu.stress.selfheal_bench import run

        r = run(sources=args.sources, ticks=args.ticks,
                batches=args.batches,
                eval_interval_s=args.eval_interval,
                fire_after_s=args.fire_after,
                max_overhead_pct=args.max_overhead_pct)
    elif args.bench == "ufscold":
        from alluxio_tpu.stress.ufs_cold_bench import run

        r = run(block_mb=args.block_mb, stripe_kb=args.stripe_kb,
                blocks_per_reader=args.blocks_per_reader,
                rtt_ms=args.rtt_ms, conn_mbps=args.conn_mbps,
                concurrency=args.concurrency,
                per_mount_limit=args.per_mount_limit,
                min_speedup=args.min_speedup)
    elif args.bench == "remoteread":
        from alluxio_tpu.stress.remote_read_bench import run

        r = run(block_mb=args.block_mb, stripe_kb=args.stripe_kb,
                stripes=args.stripes, rtt_ms=args.rtt_ms,
                conn_mbps=args.conn_mbps, blocks=args.blocks,
                hedge_quantile=args.hedge_quantile,
                stall_ms=args.stall_ms, min_speedup=args.min_speedup)
    elif args.bench == "qos":
        from alluxio_tpu.stress.qos_bench import run

        r = run(rtt_ms=args.rtt_ms, block_kb=args.block_kb,
                victim_reads=args.victim_reads,
                flood_blocks=args.flood_blocks,
                per_mount_limit=args.per_mount_limit,
                tenant_limit=args.tenant_limit,
                max_degradation=args.max_degradation,
                admission_checks=args.admission_checks,
                admission_principals=args.admission_principals,
                admission_max_principals=args.admission_max_principals)
    elif args.bench == "metadata":
        from alluxio_tpu.stress.metadata_bench import run

        kw = {}
        if args.threads is not None:
            kw["threads"] = args.threads
        if args.duration is not None:
            kw["duration_s"] = args.duration
        if args.min_speedup is not None:
            kw["min_speedup"] = args.min_speedup
        if args.row == "cached":
            r = run(row="cached", master=args.master, **kw)
        elif args.row == "lsm-capacity":
            kw.pop("threads", None)
            kw.pop("duration_s", None)
            kw.pop("min_speedup", None)
            r = run(row="lsm-capacity", inodes=args.inodes,
                    cap_mb=args.cap_mb, **kw)
        else:
            r = run(row=args.row, fsync_ms=args.fsync_ms,
                    batch_time_ms=args.batch_time_ms, **kw)
    elif args.bench == "ha":
        from alluxio_tpu.stress.ha_bench import run

        r = run(masters=args.masters,
                election_timeout_s=args.election_timeout,
                warmup_s=args.warmup)
    elif args.bench == "suite":
        results = run_suite()
        return 0 if all(x.errors == 0 for x in results) else 1
    elif args.bench == "report":
        from alluxio_tpu.stress.report import write_report

        return write_report(args.input, args.out)
    else:  # pragma: no cover — argparse guards
        return 2
    print(r.json_line(), flush=True)
    return 0 if r.errors == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
