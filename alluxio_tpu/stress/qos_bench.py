"""Two-tenant QoS bench: a victim's read p99 under an abusive tenant's
flood, with and without QoS — plus the master admission limiter's
bounded-memory shedding throughput.

Model, not wall-clock luck (the bench-health/selfheal discipline): the
UFS is simulated with a fixed per-read round trip that DWARFS host
thread-wake jitter, so the p99s measure *queueing*, which is the thing
QoS changes.  Three legs:

1. **victim solo** — the well-behaved tenant reads cold blocks alone
   through a ``UfsBlockFetcher`` over a ``per_mount_limit``-bounded
   executor.  Its p99 is the baseline.
2. **victim under flood, QoS ON** — the abusive tenant pre-loads a deep
   backlog of PREFETCH-class fetches; the victim's ON_DEMAND reads must
   stay within ``--max-degradation`` (default 2x) of solo: the priority
   queue drains the victim first and the tenant cap
   (``tenant_limit < per_mount_limit``) keeps slots free for it.
   **This is the gate.**
3. **victim under flood, QoS OFF** — same flood over the FIFO executor
   (today's behavior).  Reported as the degradation QoS removes; the
   bench fails if FIFO is NOT worse than QoS (the flood failed to
   saturate, so leg 2 proved nothing).

The admission leg floods an :class:`AdmissionController` from far more
principals than its ``max_principals`` cap on a fake clock, asserting
bucket memory stays bounded while over-rate calls shed (not queue), and
reports checks/sec — the per-RPC cost of the gate.

One JSON line on stdout (suite row ``qos-two-tenant``).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import List

from alluxio_tpu.stress.base import BenchResult, percentiles


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


class _ModelUfs:
    """UFS stand-in: every ranged read costs one fixed round trip."""

    def __init__(self, rtt_s: float) -> None:
        self._rtt = rtt_s

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        time.sleep(self._rtt)
        return b"\0" * length


def _victim_latencies(fetcher, ufs, *, block_ids: List[int],
                      block_bytes: int, mount_id: int = 0) -> List[float]:
    from alluxio_tpu.qos import ON_DEMAND
    from alluxio_tpu.worker.ufs_io import UfsBlockDescriptor

    out = []
    for bid in block_ids:
        desc = UfsBlockDescriptor(block_id=bid, ufs_path=f"/v/{bid}",
                                  offset=0, length=block_bytes,
                                  mount_id=mount_id)
        t0 = time.monotonic()
        fetcher.fetch(ufs, desc, cache=False, priority=ON_DEMAND,
                      tenant="victim").result()
        out.append(time.monotonic() - t0)
    return out


def _flood(fetcher, ufs, *, blocks: int, block_bytes: int,
           first_block_id: int, mount_id: int = 0) -> None:
    from alluxio_tpu.qos import PREFETCH
    from alluxio_tpu.worker.ufs_io import UfsBlockDescriptor

    for i in range(blocks):
        bid = first_block_id + i
        desc = UfsBlockDescriptor(block_id=bid, ufs_path=f"/a/{bid}",
                                  offset=0, length=block_bytes,
                                  mount_id=mount_id)
        fetcher.fetch(ufs, desc, cache=False, priority=PREFETCH,
                      tenant="abuser")


def _drain(fetcher, timeout_s: float = 120.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        with fetcher._lock:
            if not fetcher._inflight:
                return
        time.sleep(0.01)


def run(*, rtt_ms: float = 40.0, block_kb: int = 64,
        victim_reads: int = 12, flood_blocks: int = 48,
        per_mount_limit: int = 4, tenant_limit: int = 2,
        max_degradation: float = 2.0,
        admission_checks: int = 200_000,
        admission_principals: int = 20_000,
        admission_max_principals: int = 512) -> BenchResult:
    from alluxio_tpu.qos.admission import AdmissionConf, AdmissionController
    from alluxio_tpu.worker.ufs_fetch import FetchConf, UfsBlockFetcher

    rtt_s = rtt_ms / 1000.0
    block_bytes = block_kb << 10
    ufs = _ModelUfs(rtt_s)
    errors = 0
    t_start = time.monotonic()

    def make_fetcher(qos: bool) -> UfsBlockFetcher:
        # one whole-block stripe per fetch: each fetch is one executor
        # task, so the queueing the bench measures is task queueing
        return UfsBlockFetcher(None, FetchConf(
            stripe_size=block_bytes, concurrency=1,
            per_mount_limit=per_mount_limit, qos_enabled=qos,
            tenant_limit=tenant_limit))

    # --- leg 1: victim solo (baseline) ----------------------------------
    f = make_fetcher(True)
    solo = _victim_latencies(f, ufs, block_ids=range(1, victim_reads + 1),
                             block_bytes=block_bytes)
    f.close()
    solo_p = percentiles(solo)
    log(f"[qos] victim solo p99 {solo_p['p99_us'] / 1e3:.1f} ms "
        f"(rtt {rtt_ms} ms)")

    def flooded_leg(qos: bool) -> dict:
        fetcher = make_fetcher(qos)
        _flood(fetcher, ufs, blocks=flood_blocks,
               block_bytes=block_bytes, first_block_id=10_000)
        # flood keeps coming while the victim reads: a second wave lands
        # mid-measurement from another thread, as a real tenant would
        refill = threading.Thread(
            target=_flood, args=(fetcher, ufs),
            kwargs=dict(blocks=flood_blocks, block_bytes=block_bytes,
                        first_block_id=20_000), daemon=True)
        refill.start()
        lat = _victim_latencies(
            fetcher, ufs, block_ids=range(30_000, 30_000 + victim_reads),
            block_bytes=block_bytes)
        refill.join(timeout=30)
        _drain(fetcher)
        fetcher.close()
        return percentiles(lat)

    # --- leg 2: flood with QoS ON (the gate) ----------------------------
    qos_p = flooded_leg(True)
    log(f"[qos] victim p99 under flood, QoS ON: "
        f"{qos_p['p99_us'] / 1e3:.1f} ms")
    # --- leg 3: flood with QoS OFF (the evidence) -----------------------
    fifo_p = flooded_leg(False)
    log(f"[qos] victim p99 under flood, QoS OFF: "
        f"{fifo_p['p99_us'] / 1e3:.1f} ms")

    degradation = qos_p["p99_us"] / max(1.0, solo_p["p99_us"])
    fifo_degradation = fifo_p["p99_us"] / max(1.0, solo_p["p99_us"])
    if degradation > max_degradation:
        errors += 1
        log(f"[qos] FAIL: victim p99 degraded {degradation:.2f}x under "
            f"flood with QoS on (max {max_degradation}x)")
    if fifo_p["p99_us"] <= qos_p["p99_us"]:
        errors += 1
        log("[qos] FAIL: FIFO flood was not worse than QoS — the flood "
            "did not saturate the executor, gate proves nothing")

    # --- admission leg: bounded-memory shedding -------------------------
    t = [0.0]
    adm = AdmissionController(
        AdmissionConf(enabled=True, rate=5.0, burst=10.0,
                      max_principals=admission_max_principals),
        clock=lambda: t[0])
    from alluxio_tpu.utils.exceptions import ResourceExhaustedError

    shed = 0
    t0 = time.monotonic()
    for i in range(admission_checks):
        t[0] += 1e-4  # 10k calls per fake second >> every rate
        # half the load is ONE flooding principal (must shed), half is
        # principal-name churn (must stay bounded, not shed — each
        # minted name is seen once and LRU-evicted)
        who = "abuser" if i % 2 else f"tenant-{i % admission_principals}"
        try:
            adm.check(who, "create_file")
        except ResourceExhaustedError:
            shed += 1
    admission_wall = time.monotonic() - t0
    checks_per_s = admission_checks / max(1e-9, admission_wall)
    tracked = adm.report()
    if tracked["admitted_total"] + tracked["shed_total"] \
            != admission_checks:
        errors += 1
        log("[qos] FAIL: admission counters do not add up")
    # bounded memory is the acceptance criterion: a 20k-principal flood
    # must not grow state past the configured cap
    principals_tracked = len(adm._buckets)
    if principals_tracked > admission_max_principals:
        errors += 1
        log(f"[qos] FAIL: {principals_tracked} principal buckets "
            f"tracked, cap {admission_max_principals}")
    if shed == 0:
        errors += 1
        log("[qos] FAIL: the flood shed nothing — limiter inert")
    log(f"[qos] admission: {checks_per_s / 1e3:.0f}k checks/s, "
        f"{shed} shed, {principals_tracked} buckets "
        f"(cap {admission_max_principals})")

    return BenchResult(
        bench="qos-two-tenant",
        params={"rtt_ms": rtt_ms, "block_kb": block_kb,
                "victim_reads": victim_reads,
                "flood_blocks": 2 * flood_blocks,
                "per_mount_limit": per_mount_limit,
                "tenant_limit": tenant_limit,
                "max_degradation_x": max_degradation,
                "admission_checks": admission_checks,
                "admission_principals": admission_principals},
        metrics={
            "victim_solo_p99_ms": round(solo_p["p99_us"] / 1e3, 2),
            "victim_flood_qos_p99_ms": round(qos_p["p99_us"] / 1e3, 2),
            "victim_flood_fifo_p99_ms": round(fifo_p["p99_us"] / 1e3, 2),
            "victim_degradation_qos_x": round(degradation, 3),
            "victim_degradation_fifo_x": round(fifo_degradation, 3),
            "gate": f"victim p99 under flood <= {max_degradation}x solo "
                    f"with QoS on",
            "admission_checks_per_s": round(checks_per_s, 0),
            "admission_shed": shed,
            "admission_buckets_tracked": principals_tracked,
            "admission_buckets_cap": admission_max_principals,
        },
        errors=errors, duration_s=time.monotonic() - t_start)
