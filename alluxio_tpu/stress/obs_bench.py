"""Observability overhead bench (``make bench-obs``).

Measures what the tracing layer costs when it is ON — the number that
justifies leaving it compiled into the hot path:

- **spans/sec**: raw span open/close throughput of the process tracer
  (the per-RPC fixed cost).
- **read latency delta**: median end-to-end cached-read latency through
  a live in-process cluster, tracing disabled vs enabled, interleaved
  in alternating batches so host-speed drift cancels out.

The suite row FAILS (``errors=1``) when the enabled-vs-disabled delta
exceeds ``--max-overhead-pct`` (default 2%), which is the budget the
"cheap enough to leave compiled in" claim makes.
"""

from __future__ import annotations

import statistics
import sys
import time

from alluxio_tpu.stress.base import BenchResult


def _median_read_s(fs, path: str, n: int) -> float:
    samples = []
    for _ in range(n):
        t0 = time.perf_counter()
        fs.read_all(path)
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def _span_throughput(iterations: int) -> float:
    from alluxio_tpu.utils.tracing import set_tracing_enabled, tracer

    set_tracing_enabled(True)
    t = tracer()
    t.clear()
    t0 = time.perf_counter()
    for _ in range(iterations):
        with t.span("bench.noop"):
            pass
    elapsed = time.perf_counter() - t0
    t.clear()
    set_tracing_enabled(False)
    return iterations / elapsed if elapsed > 0 else 0.0


def run(*, file_mb: int = 4, reads: int = 60, batches: int = 5,
        span_iterations: int = 100_000,
        max_overhead_pct: float = 2.0) -> BenchResult:
    import tempfile

    from alluxio_tpu.minicluster.local_cluster import LocalCluster
    from alluxio_tpu.utils.tracing import set_tracing_enabled, tracer

    t_start = time.monotonic()
    spans_per_s = _span_throughput(span_iterations)
    off_batches, on_batches = [], []
    with tempfile.TemporaryDirectory(prefix="atpu-obs-") as base:
        with LocalCluster(base, num_workers=1,
                          worker_mem_bytes=4 * (file_mb << 20)) as c:
            fs = c.file_system()
            path = "/obs-bench.bin"
            fs.write_all(path, b"\xab" * (file_mb << 20))
            _median_read_s(fs, path, reads)  # warm: cache + codepaths
            # alternate off/on batches: the container's per-core speed
            # drifts mid-run, and a sequential A-then-B layout folds
            # that drift straight into the delta
            for _ in range(batches):
                set_tracing_enabled(False)
                off_batches.append(_median_read_s(fs, path, reads))
                set_tracing_enabled(True)
                on_batches.append(_median_read_s(fs, path, reads))
                tracer().clear()  # bound ring memory between batches
            set_tracing_enabled(False)
    lat_off_s = statistics.median(off_batches)
    lat_on_s = statistics.median(on_batches)
    overhead_pct = (100.0 * (lat_on_s - lat_off_s) / lat_off_s) \
        if lat_off_s > 0 else 0.0
    ok = overhead_pct <= max_overhead_pct
    if not ok:
        print(f"[obs] tracing overhead {overhead_pct:.2f}% exceeds the "
              f"{max_overhead_pct}% budget", file=sys.stderr)
    return BenchResult(
        bench="obs-tracing-overhead",
        params={"file_mb": file_mb, "reads_per_batch": reads,
                "batches": batches, "span_iterations": span_iterations,
                "max_overhead_pct": max_overhead_pct},
        metrics={"spans_per_s": round(spans_per_s, 1),
                 "read_p50_off_ms": round(lat_off_s * 1e3, 4),
                 "read_p50_on_ms": round(lat_on_s * 1e3, 4),
                 "overhead_pct": round(overhead_pct, 3),
                 "overhead_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)
