"""Observability overhead + fidelity benches (``make bench-obs``).

Three gated rows:

- ``obs-tracing-overhead`` — what the tracing layer costs when it is
  ON: raw span open/close throughput plus the median cached-read
  latency delta (disabled vs enabled) through a live in-process
  cluster, interleaved in alternating batches so host-speed drift
  cancels out. FAILS (``errors=1``) above ``--max-overhead-pct``
  (default 2%) — the budget the "cheap enough to leave compiled in"
  claim makes.
- ``obs-profile-overhead`` — same interleaved-batch shape for the
  thread-stack sampler (``atpu.profile.enabled``), run at an interval
  more aggressive than the shipped default. Same 2% budget.
- ``obs-critical-path`` — fidelity, not overhead: random-4k reads with
  short-circuit OFF (forcing the remote striped-read path) through the
  minicluster, then the critical-path analyzer must attribute
  >= ``--min-attributed-pct`` (default 90%) of end-to-end wall time to
  named phases — the "readpath report explains where the time went"
  acceptance gate.
"""

from __future__ import annotations

import statistics
import sys
import time

from alluxio_tpu.stress.base import BenchResult


def _median_read_s(fs, path: str, n: int) -> float:
    samples = []
    for _ in range(n):
        t0 = time.perf_counter()
        fs.read_all(path)
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples)


def _span_throughput(iterations: int) -> float:
    from alluxio_tpu.utils.tracing import set_tracing_enabled, tracer

    set_tracing_enabled(True)
    t = tracer()
    t.clear()
    t0 = time.perf_counter()
    for _ in range(iterations):
        with t.span("bench.noop"):
            pass
    elapsed = time.perf_counter() - t0
    t.clear()
    set_tracing_enabled(False)
    return iterations / elapsed if elapsed > 0 else 0.0


def run(*, file_mb: int = 4, reads: int = 60, batches: int = 5,
        span_iterations: int = 100_000,
        max_overhead_pct: float = 2.0) -> BenchResult:
    import tempfile

    from alluxio_tpu.minicluster.local_cluster import LocalCluster
    from alluxio_tpu.utils.tracing import set_tracing_enabled, tracer

    t_start = time.monotonic()
    spans_per_s = _span_throughput(span_iterations)
    off_batches, on_batches = [], []
    with tempfile.TemporaryDirectory(prefix="atpu-obs-") as base:
        with LocalCluster(base, num_workers=1,
                          worker_mem_bytes=4 * (file_mb << 20)) as c:
            fs = c.file_system()
            path = "/obs-bench.bin"
            fs.write_all(path, b"\xab" * (file_mb << 20))
            _median_read_s(fs, path, reads)  # warm: cache + codepaths
            # alternate off/on batches: the container's per-core speed
            # drifts mid-run, and a sequential A-then-B layout folds
            # that drift straight into the delta
            for _ in range(batches):
                set_tracing_enabled(False)
                off_batches.append(_median_read_s(fs, path, reads))
                set_tracing_enabled(True)
                on_batches.append(_median_read_s(fs, path, reads))
                tracer().clear()  # bound ring memory between batches
            set_tracing_enabled(False)
    lat_off_s = statistics.median(off_batches)
    lat_on_s = statistics.median(on_batches)
    overhead_pct = (100.0 * (lat_on_s - lat_off_s) / lat_off_s) \
        if lat_off_s > 0 else 0.0
    ok = overhead_pct <= max_overhead_pct
    if not ok:
        print(f"[obs] tracing overhead {overhead_pct:.2f}% exceeds the "
              f"{max_overhead_pct}% budget", file=sys.stderr)
    return BenchResult(
        bench="obs-tracing-overhead",
        params={"file_mb": file_mb, "reads_per_batch": reads,
                "batches": batches, "span_iterations": span_iterations,
                "max_overhead_pct": max_overhead_pct},
        metrics={"spans_per_s": round(spans_per_s, 1),
                 "read_p50_off_ms": round(lat_off_s * 1e3, 4),
                 "read_p50_on_ms": round(lat_on_s * 1e3, 4),
                 "overhead_pct": round(overhead_pct, 3),
                 "overhead_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def run_profile_overhead(*, file_mb: int = 4, reads: int = 60,
                         batches: int = 5, sample_interval_ms: int = 0,
                         max_overhead_pct: float = 2.0) -> BenchResult:
    """``obs-profile-overhead``: enabled-vs-disabled read latency for
    the thread-stack sampler at the shipped default interval
    (``sample_interval_ms=0`` means "whatever the conf default is").
    The cost under test is per-WAKE (GIL handoff against the reading
    thread), so the interval is the lever that must keep this row
    green."""
    import tempfile

    from alluxio_tpu.minicluster.local_cluster import LocalCluster
    from alluxio_tpu.utils.profiler import profiler

    t_start = time.monotonic()
    p = profiler()
    p.stop()
    saved_interval = p.interval_ms
    off_batches, on_batches = [], []
    total_samples = 0
    try:
        with tempfile.TemporaryDirectory(prefix="atpu-obs-prof-") as base:
            with LocalCluster(base, num_workers=1,
                              worker_mem_bytes=4 * (file_mb << 20)) as c:
                fs = c.file_system()
                # AFTER cluster+client construction: their
                # apply_profile_conf calls reset the sampler to the
                # conf default, which is exactly what interval 0 wants
                if sample_interval_ms > 0:
                    p.interval_ms = int(sample_interval_ms)
                used_interval = p.interval_ms
                path = "/obs-prof.bin"
                fs.write_all(path, b"\xcd" * (file_mb << 20))
                _median_read_s(fs, path, reads)  # warm: cache + codepaths
                for _ in range(batches):
                    p.stop()
                    off_batches.append(_median_read_s(fs, path, reads))
                    p.start()
                    on_batches.append(_median_read_s(fs, path, reads))
                    flame = p.drain()  # bound table memory between batches
                    total_samples += (flame or {}).get("samples", 0)
    finally:
        p.stop()
        p.interval_ms = saved_interval
        p.drain()
    lat_off_s = statistics.median(off_batches)
    lat_on_s = statistics.median(on_batches)
    overhead_pct = (100.0 * (lat_on_s - lat_off_s) / lat_off_s) \
        if lat_off_s > 0 else 0.0
    ok = overhead_pct <= max_overhead_pct
    if not ok:
        print(f"[obs] profiler overhead {overhead_pct:.2f}% exceeds the "
              f"{max_overhead_pct}% budget", file=sys.stderr)
    return BenchResult(
        bench="obs-profile-overhead",
        params={"file_mb": file_mb, "reads_per_batch": reads,
                "batches": batches,
                "sample_interval_ms": used_interval,
                "max_overhead_pct": max_overhead_pct},
        metrics={"read_p50_off_ms": round(lat_off_s * 1e3, 4),
                 "read_p50_on_ms": round(lat_on_s * 1e3, 4),
                 "samples": total_samples,
                 "overhead_pct": round(overhead_pct, 3),
                 "overhead_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def run_critical_path(*, file_mb: int = 2, reads: int = 80,
                      read_bytes: int = 4096,
                      min_attributed_pct: float = 90.0) -> BenchResult:
    """``obs-critical-path``: random-4k reads with short-circuit OFF
    (the /dev/shm mmap path emits no remote-read phases — reads must
    cross the worker RPC), every trace sampled, then the critical-path
    profile over the ring must attribute >= ``min_attributed_pct`` of
    root wall time to named phases."""
    import random
    import tempfile

    from alluxio_tpu.client.file_system import FileSystem
    from alluxio_tpu.conf import Keys
    from alluxio_tpu.minicluster.local_cluster import LocalCluster
    from alluxio_tpu.utils.critical_path import profile
    from alluxio_tpu.utils.tracing import (
        set_tracing_enabled, stitch_spans, tracer,
    )

    t_start = time.monotonic()
    rng = random.Random(0xA77)
    prof: dict = {}
    try:
        with tempfile.TemporaryDirectory(prefix="atpu-obs-cp-") as base:
            with LocalCluster(base, num_workers=1,
                              worker_mem_bytes=8 * (file_mb << 20)) as c:
                conf = c.conf.copy()
                conf.set(Keys.USER_SHORT_CIRCUIT_ENABLED, False)
                # stripe below the op size so 4k preads engage the
                # striped scheduler (reads <= stripe_size ride the
                # legacy loop, which opens no client span)
                conf.set(Keys.USER_REMOTE_READ_STRIPE_SIZE,
                         max(512, read_bytes // 4))
                conf.set(Keys.TRACE_SAMPLE_RATE, 1.0)
                conf.set(Keys.TRACE_RING_CAPACITY, 16384)
                fs = FileSystem(c.master.address, conf=conf)
                try:
                    path = "/obs-cp.bin"
                    size = file_mb << 20
                    fs.write_all(path, b"\xee" * size,
                                 write_type="MUST_CACHE")
                    fs.read_all(path)  # warm the worker tier
                    set_tracing_enabled(True)
                    tracer().clear()
                    with fs.open_file(path) as f:
                        for _ in range(reads):
                            off = rng.randrange(0, size - read_bytes)
                            f.pread(off, read_bytes)
                    set_tracing_enabled(False)
                    stitched = stitch_spans(None, limit=16384)
                    prof = profile(stitched["spans"],
                                   root_prefix="atpu.client.remote_read",
                                   max_traces=reads) or {}
                finally:
                    fs.close()
    finally:
        set_tracing_enabled(False)
        tracer().clear()
    analyzed = prof.get("traces_analyzed", 0)
    attributed = float(prof.get("attributed_pct") or 0.0)
    top = (prof.get("phases") or [{}])[0]
    ok = analyzed >= reads // 2 and attributed >= min_attributed_pct
    if not ok:
        print(f"[obs] critical-path attribution {attributed:.1f}% over "
              f"{analyzed} traces misses the {min_attributed_pct}% gate",
              file=sys.stderr)
    return BenchResult(
        bench="obs-critical-path",
        params={"file_mb": file_mb, "reads": reads,
                "read_bytes": read_bytes,
                "min_attributed_pct": min_attributed_pct},
        metrics={"traces_analyzed": analyzed,
                 "wall_ms_p50": prof.get("wall_ms_p50", 0.0),
                 "wall_ms_p99": prof.get("wall_ms_p99", 0.0),
                 "attributed_pct": attributed,
                 "top_segment": str(top.get("key", "")),
                 "top_segment_pct": float(top.get("pct") or 0.0),
                 "attribution_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)
