"""History-ingestion overhead bench (``make bench-health``).

Gates the promise the metrics-history design makes: attaching the
history store to the master's metrics heartbeat must cost **<5%** on
the heartbeat-handling hot path, because ``MetricsHistory.offer`` is a
single deque append — the ring/rollup folding happens in ``drain()``
on the health heartbeat, off the RPC path.  The bench measures:

- **hot path**: ``MetricsMaster.handle_heartbeat`` per-call latency,
  history disabled vs enabled, interleaved in alternating batches so
  host-speed drift cancels (the slow-CI discipline from bench-obs);
- **drain throughput**: samples/sec folded into rings + rollups;
- **rule-eval latency**: one full ``HealthMonitor.evaluate`` pass over
  the populated history.

Both masters run on a **fake clock** advanced deterministically per
tick, so retention sweeps, rollup rollover and source GC happen at
exactly the same simulated instants in every run — the CI host's
ms-scale jitter cannot change *what work* either variant does, only
how long it takes, and that is what the alternating batches cancel.
"""

from __future__ import annotations

import statistics
import sys
import time

from alluxio_tpu.stress.base import BenchResult


class _FakeClock:
    """Deliberately NOT utils.clock.ManualClock: the history-enabled
    variant pays one extra clock call per heartbeat (``offer`` stamps
    the sample), so the bench clock must cost what the production
    clock costs (~a C-level ``time.time``).  ManualClock's per-call
    lock is ~5x dearer and bills ~0.7% of phantom "history overhead"
    to the gated delta — measured pushing the 5% gate from ~3.6% to
    ~5.4% on the CI host."""

    def __init__(self) -> None:
        self.now = 1_000_000.0

    def __call__(self) -> float:
        return self.now

    def advance(self, s: float) -> None:
        self.now += s


def _snapshots(sources: int, metrics_per_source: int, ticks: int):
    """Pre-built per-(tick, source) snapshot dicts: dict construction
    happens OUTSIDE the timed region, identically for both variants."""
    out = []
    for t in range(ticks):
        tick = []
        for s in range(sources):
            # lint: allow[metric-unknown] -- synthetic heartbeat payload: the bench floods the history store with fabricated names
            snap = {f"Worker.BenchMetric{m}": float(t * 7 + m)
                    for m in range(metrics_per_source - 2)}
            snap["Worker.ReadBlockTime.p99"] = 0.001 + 0.0001 * s
            snap["Client.InputBoundFraction"] = 0.1
            tick.append((f"worker-host{s}:29999", snap))
        out.append(tick)
    return out


def run(*, sources: int = 64, metrics_per_source: int = 120,
        ticks: int = 40, batches: int = 8, hb_interval_s: float = 5.0,
        max_overhead_pct: float = 5.0) -> BenchResult:
    from alluxio_tpu.master.health import HealthMonitor, default_rules
    from alluxio_tpu.master.metrics_master import MetricsMaster, MetricsStore
    from alluxio_tpu.metrics import metrics as _registry
    from alluxio_tpu.metrics.history import MetricsHistory

    t_start = time.monotonic()
    payload = _snapshots(sources, metrics_per_source, ticks)

    clock_off = _FakeClock()
    clock_on = _FakeClock()
    mm_off = MetricsMaster(store=MetricsStore(clock=clock_off))
    mm_on = MetricsMaster(
        store=MetricsStore(clock=clock_on),
        history=MetricsHistory(clock=clock_on, max_series=16384,
                               pending_max=sources + 8))
    # both variants run the SAME tick back to back, repeatedly: the CI
    # host's per-core speed drifts on second timescales, so only
    # sub-second pairing keeps the drift out of the delta (the
    # bench-obs discipline, one level finer)
    pairs = []
    drain_total = 0.0
    flip = False
    for _ in range(batches):
        for tick in payload:
            # alternate which variant goes first: whoever runs second
            # inherits warm caches from the first, and a fixed order
            # would bill that asymmetry to one side
            first, second = (mm_on, mm_off) if flip else (mm_off, mm_on)
            t0 = time.perf_counter()
            for source, snap in tick:
                first.handle_heartbeat({"source": source,
                                        "metrics": snap})
            t1 = time.perf_counter()
            for source, snap in tick:
                second.handle_heartbeat({"source": source,
                                         "metrics": snap})
            t2 = time.perf_counter()
            pairs.append((t2 - t1, t1 - t0, flip) if flip
                         else (t1 - t0, t2 - t1, flip))
            flip = not flip
            mm_on.drain_history(now=clock_on())
            drain_total += time.perf_counter() - t2
            clock_off.advance(hb_interval_s)
            clock_on.advance(hb_interval_s)
    off_med = statistics.median(p[0] for p in pairs) / sources
    on_med = statistics.median(p[1] for p in pairs) / sources
    # paired per-tick deltas, conditioned on run order: whichever
    # variant runs first right after a drain eats a cold-cache penalty,
    # so the pooled delta distribution is bimodal and its median
    # unstable — the two order-conditional medians see equal-and-
    # opposite bias and their average cancels it
    d_on_cold = statistics.median(
        on - off for off, on, fl in pairs if fl)
    d_off_cold = statistics.median(
        on - off for off, on, fl in pairs if not fl)
    delta = (d_on_cold + d_off_cold) / 2.0
    overhead_pct = 100.0 * delta / (off_med * sources) \
        if off_med > 0 else 0.0
    total_samples = batches * ticks * sources * metrics_per_source
    drain_per_s = total_samples / drain_total if drain_total > 0 else 0.0

    monitor = HealthMonitor(mm_on, rules=default_rules(),
                            clock=clock_on, registry=_registry())
    eval_samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        monitor.evaluate()
        eval_samples.append(time.perf_counter() - t0)
        clock_on.advance(10.0)
    eval_ms = 1e3 * statistics.median(eval_samples)

    ok = overhead_pct <= max_overhead_pct
    if not ok:
        print(f"[health] history ingestion overhead {overhead_pct:.2f}% "
              f"exceeds the {max_overhead_pct}% heartbeat budget",
              file=sys.stderr)
    return BenchResult(
        bench="health-ingest-overhead",
        params={"sources": sources,
                "metrics_per_source": metrics_per_source,
                "ticks": ticks, "batches": batches,
                "hb_interval_s": hb_interval_s,
                "max_overhead_pct": max_overhead_pct},
        metrics={"hb_off_us": round(1e6 * off_med, 3),
                 "hb_on_us": round(1e6 * on_med, 3),
                 "overhead_pct": round(overhead_pct, 3),
                 "overhead_ok": ok,
                 "drain_samples_per_s": round(drain_per_s, 1),
                 "history_series": mm_on.history.series_count(),
                 "rule_eval_ms": round(eval_ms, 3)},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)
