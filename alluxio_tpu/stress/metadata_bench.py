"""bench-metadata: metadata control-plane scale-out gates.

Five suite rows, the ratios against the pre-PR configuration:

- ``metadata-striped`` — mixed CreateFile/GetStatus/ListStatus/Delete
  across disjoint per-thread subtrees, striped inode locking + journal
  group commit vs the single tree-wide lock with inline fsync (the
  pre-PR master).  Gate: >= 3x ops/s.
- ``metadata-journal-batch`` — CreateFile-only under the same
  comparison, isolating the durability path.  Gate: >= 1.5x.
- ``metadata-cached-getstatus`` — warm client-metadata-cache GetStatus
  vs the uncached RPC round trip on a live in-process cluster.
  Gate: >= 10x.
- ``metadata-hot-dir`` — CreateFile with EVERY thread targeting ONE
  shared directory (the hot-directory worst case striping cannot
  help): WRITE_EDGE locking vs write-locking the shared parent inode,
  both sides striped + group commit.  Gate: >= 2x ops/s.
- ``metadata-lsm-capacity`` — builds, walks and random-stats a large
  namespace in a subprocess running under an enforced address-space
  cap (``resource.setrlimit``): the HEAP backend must BLOW the cap
  and the LSM backend must complete under it with every lookup
  served.  Gate: LSM ok AND HEAP out-of-memory.

The journal rides a **modeled slow fsync** (``--fsync-ms``, default
3ms — local-disk/NFS class): on tmpfs-backed CI an fsync is nearly
free, which would understate exactly the serialization the pre-PR
master suffers on real media.  The model follows the established
bench practice here (connection-limited worker/UFS models in
bench-remote-read / bench-ufs-cold).  Gates are RATIOS with wide
margins, so scheduler jitter moves both sides together.
"""

from __future__ import annotations

import itertools
import os
import shutil
import sys
import tempfile
import time
from typing import Optional

from alluxio_tpu.journal.system import LocalJournalSystem
from alluxio_tpu.stress.base import BenchResult, drive, percentiles


class _SlowFsyncJournal(LocalJournalSystem):
    """LocalJournalSystem whose fsync costs ``fsync_s`` extra — the
    disk model.  Counts fsyncs so batching is observable."""

    def __init__(self, folder: str, fsync_s: float, **kw) -> None:
        super().__init__(folder, **kw)
        self.fsync_s = fsync_s
        self.fsync_count = 0

    def _fsync(self, fd: int) -> None:
        self.fsync_count += 1
        if self.fsync_s > 0:
            time.sleep(self.fsync_s)
        os.fsync(fd)


class _Master:
    """An in-process FileSystemMaster + journal, pre-PR (coarse +
    inline fsync) or post-PR (striped + group commit) flavor."""

    def __init__(self, base: str, *, coarse: bool, batched: bool,
                 fsync_s: float, batch_time_s: float,
                 edge_locking: bool = True) -> None:
        from alluxio_tpu.master.block_master import BlockMaster
        from alluxio_tpu.master.file_master import FileSystemMaster

        self.journal = _SlowFsyncJournal(base, fsync_s)
        self.journal.start()
        self.journal.gain_primacy()
        if batched:
            self.journal.start_group_commit(batch_time_s)
        self.block_master = BlockMaster(self.journal)
        self.fsm = FileSystemMaster(self.block_master, self.journal,
                                    coarse_locking=coarse,
                                    edge_locking=edge_locking)
        self.fsm.start(None)

    def close(self) -> None:
        self.fsm.stop()
        self.journal.stop()


def _mixed_body(fsm, threads: int):
    """Per-thread cycle over its own subtree: create -> stat -> list ->
    delete.  Disjoint subtrees are the training-shard common case the
    striping targets."""
    for t in range(threads):
        fsm.create_directory(f"/t{t}", recursive=True, allow_exists=True)
    counters = [itertools.count() for _ in range(threads)]

    def body(t: int, i: int) -> int:
        j = next(counters[t])
        seq, phase = j // 4, j % 4
        if phase == 0:
            fsm.create_file(f"/t{t}/x-{seq:08d}")
        elif phase == 1:
            fsm.get_status(f"/t{t}/x-{seq:08d}")
        elif phase == 2:
            fsm.list_status(f"/t{t}")
        else:
            fsm.delete(f"/t{t}/x-{seq:08d}")
        return 0

    return body


def _create_body(fsm, threads: int):
    for t in range(threads):
        fsm.create_directory(f"/t{t}", recursive=True, allow_exists=True)
    counters = [itertools.count() for _ in range(threads)]

    def body(t: int, i: int) -> int:
        fsm.create_file(f"/t{t}/c-{next(counters[t]):09d}")
        return 0

    return body


def _run_mode(make_body, *, coarse: bool, batched: bool, threads: int,
              duration_s: float, fsync_s: float, batch_time_s: float,
              edge_locking: bool = True):
    base = tempfile.mkdtemp(prefix="atpu_mdbench_")
    master = _Master(base, coarse=coarse, batched=batched,
                     fsync_s=fsync_s, batch_time_s=batch_time_s,
                     edge_locking=edge_locking)
    try:
        body = make_body(master.fsm, threads)
        res = drive(threads, body, duration_s=duration_s)
        return res, master.journal.fsync_count
    finally:
        master.close()
        shutil.rmtree(base, ignore_errors=True)


def _ratio_row(bench: str, make_body, *, threads: int, duration_s: float,
               fsync_ms: float, batch_time_ms: float,
               min_speedup: float) -> BenchResult:
    t_start = time.monotonic()
    fsync_s, batch_s = fsync_ms / 1e3, batch_time_ms / 1e3
    base_res, base_fsyncs = _run_mode(
        make_body, coarse=True, batched=False, threads=threads,
        duration_s=duration_s, fsync_s=fsync_s, batch_time_s=batch_s)
    new_res, new_fsyncs = _run_mode(
        make_body, coarse=False, batched=True, threads=threads,
        duration_s=duration_s, fsync_s=fsync_s, batch_time_s=batch_s)
    speedup = new_res.ops_per_s / base_res.ops_per_s \
        if base_res.ops_per_s > 0 else 0.0
    ok = speedup >= min_speedup and base_res.errors == 0 and \
        new_res.errors == 0
    if not ok:
        print(f"[{bench}] speedup {speedup:.2f}x below the "
              f"{min_speedup}x gate (baseline "
              f"{base_res.ops_per_s:.0f} ops/s, striped+batched "
              f"{new_res.ops_per_s:.0f} ops/s, errors "
              f"{base_res.errors}+{new_res.errors})", file=sys.stderr)
    return BenchResult(
        bench=bench,
        params={"threads": threads, "duration_s": duration_s,
                "fsync_ms": fsync_ms, "batch_time_ms": batch_time_ms,
                "min_speedup": min_speedup},
        metrics={"baseline_ops_per_s": round(base_res.ops_per_s, 1),
                 "striped_batched_ops_per_s": round(new_res.ops_per_s, 1),
                 "speedup": round(speedup, 3),
                 "baseline_fsyncs": base_fsyncs,
                 "striped_fsyncs": new_fsyncs,
                 "baseline_" + "p99_us":
                     percentiles(base_res.latencies_s)["p99_us"],
                 "striped_p99_us":
                     percentiles(new_res.latencies_s)["p99_us"],
                 "gate_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def run_striped(*, threads: int = 8, duration_s: float = 2.0,
                fsync_ms: float = 3.0, batch_time_ms: float = 2.0,
                min_speedup: float = 3.0) -> BenchResult:
    return _ratio_row("metadata-striped", _mixed_body, threads=threads,
                      duration_s=duration_s, fsync_ms=fsync_ms,
                      batch_time_ms=batch_time_ms, min_speedup=min_speedup)


def run_journal_batch(*, threads: int = 8, duration_s: float = 2.0,
                      fsync_ms: float = 3.0, batch_time_ms: float = 2.0,
                      min_speedup: float = 1.5) -> BenchResult:
    return _ratio_row("metadata-journal-batch", _create_body,
                      threads=threads, duration_s=duration_s,
                      fsync_ms=fsync_ms, batch_time_ms=batch_time_ms,
                      min_speedup=min_speedup)


def run_cached_getstatus(*, master: Optional[str] = None, threads: int = 4,
                         duration_s: float = 1.5, files: int = 64,
                         min_speedup: float = 10.0) -> BenchResult:
    """Warm client-cache GetStatus vs the uncached RPC round trip on a
    live (in-process by default) cluster."""
    from alluxio_tpu.conf import Keys
    from alluxio_tpu.stress.cluster import bench_cluster

    t_start = time.monotonic()
    with bench_cluster(master, block_size=1 << 20,
                       worker_mem_bytes=64 << 20,
                       conf_overrides={
                           Keys.USER_METADATA_CACHE_ENABLED: True,
                       }) as (fs, _cluster):
        from alluxio_tpu.client.streams import WriteType

        base = "/md-cache-bench"
        fs.create_directory(base, recursive=True, allow_exists=True)
        paths = [f"{base}/f-{i:04d}" for i in range(files)]
        for p in paths:
            fs.write_all(p, b"", write_type=WriteType.MUST_CACHE)

        def uncached(t: int, i: int) -> int:
            fs.fs_master.get_status(paths[i % files])
            return 0

        cold = drive(threads, uncached, duration_s=duration_s)
        for p in paths:  # warm the cache
            fs.get_status(p)
        hits0 = fs._md_hits.count

        def cached(t: int, i: int) -> int:
            fs.get_status(paths[i % files])
            return 0

        warm = drive(threads, cached, duration_s=duration_s)
        hits = fs._md_hits.count - hits0
        try:
            fs.delete(base, recursive=True)
        except Exception:  # noqa: BLE001 cleanup is best-effort
            pass
    speedup = warm.ops_per_s / cold.ops_per_s if cold.ops_per_s else 0.0
    # the warm pass must have been served by the CACHE, not by fast RPCs
    ok = speedup >= min_speedup and hits >= warm.ops and \
        cold.errors == 0 and warm.errors == 0
    if not ok:
        print(f"[metadata-cached-getstatus] speedup {speedup:.2f}x "
              f"(gate {min_speedup}x), cache hits {hits}/{warm.ops}, "
              f"errors {cold.errors}+{warm.errors}", file=sys.stderr)
    return BenchResult(
        bench="metadata-cached-getstatus",
        params={"threads": threads, "duration_s": duration_s,
                "files": files, "min_speedup": min_speedup,
                "master": master or "in-process"},
        metrics={"uncached_ops_per_s": round(cold.ops_per_s, 1),
                 "cached_ops_per_s": round(warm.ops_per_s, 1),
                 "speedup": round(speedup, 3),
                 "cache_hits": hits,
                 "uncached_p99_us": percentiles(cold.latencies_s)["p99_us"],
                 "cached_p99_us": percentiles(warm.latencies_s)["p99_us"],
                 "gate_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def _hot_dir_body(fsm, threads: int):
    """Every thread creates in ONE shared directory — disjoint names,
    shared parent.  Striping is useless here (all paths hash to the
    parent's stripe); only WRITE_EDGE locking lets the siblings'
    journal-fsync waits overlap."""
    fsm.create_directory("/hot", recursive=True, allow_exists=True)
    counters = [itertools.count() for _ in range(threads)]

    def body(t: int, i: int) -> int:
        fsm.create_file(f"/hot/t{t}-{next(counters[t]):09d}")
        return 0

    return body


def run_hot_dir(*, threads: int = 8, duration_s: float = 2.0,
                fsync_ms: float = 3.0, batch_time_ms: float = 2.0,
                min_speedup: float = 2.0) -> BenchResult:
    """WRITE_EDGE vs parent-inode write locking under a single hot
    directory.  BOTH sides run striped + group commit — the ratio
    isolates the edge-locking change, not the striping PR."""
    t_start = time.monotonic()
    fsync_s, batch_s = fsync_ms / 1e3, batch_time_ms / 1e3
    base_res, base_fsyncs = _run_mode(
        _hot_dir_body, coarse=False, batched=True, threads=threads,
        duration_s=duration_s, fsync_s=fsync_s, batch_time_s=batch_s,
        edge_locking=False)
    new_res, new_fsyncs = _run_mode(
        _hot_dir_body, coarse=False, batched=True, threads=threads,
        duration_s=duration_s, fsync_s=fsync_s, batch_time_s=batch_s,
        edge_locking=True)
    speedup = new_res.ops_per_s / base_res.ops_per_s \
        if base_res.ops_per_s > 0 else 0.0
    ok = speedup >= min_speedup and base_res.errors == 0 and \
        new_res.errors == 0
    if not ok:
        print(f"[metadata-hot-dir] speedup {speedup:.2f}x below the "
              f"{min_speedup}x gate (parent-inode-lock "
              f"{base_res.ops_per_s:.0f} ops/s, edge-lock "
              f"{new_res.ops_per_s:.0f} ops/s, errors "
              f"{base_res.errors}+{new_res.errors})", file=sys.stderr)
    return BenchResult(
        bench="metadata-hot-dir",
        params={"threads": threads, "duration_s": duration_s,
                "fsync_ms": fsync_ms, "batch_time_ms": batch_time_ms,
                "min_speedup": min_speedup},
        metrics={"inode_lock_ops_per_s": round(base_res.ops_per_s, 1),
                 "edge_lock_ops_per_s": round(new_res.ops_per_s, 1),
                 "speedup": round(speedup, 3),
                 "inode_lock_fsyncs": base_fsyncs,
                 "edge_lock_fsyncs": new_fsyncs,
                 "inode_lock_p99_us":
                     percentiles(base_res.latencies_s)["p99_us"],
                 "edge_lock_p99_us":
                     percentiles(new_res.latencies_s)["p99_us"],
                 "gate_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def _capacity_child() -> None:
    """Subprocess body for ``metadata-lsm-capacity``: build a
    ``fanout``-wide directory namespace straight into one metastore
    backend under an enforced ``RLIMIT_AS`` cap, then walk every edge
    and random-stat a sample.  argv (after ``-c``): kind dir inodes
    cap_bytes fanout sample seed.  Prints one JSON line; blowing the
    cap is an expected outcome and reported as ``oom`` (or, when even
    the handler cannot allocate, as a nonzero exit the parent treats
    the same way)."""
    import gc
    import json
    import random
    import resource

    kind, directory = sys.argv[1], sys.argv[2]
    total, cap = int(sys.argv[3]), int(sys.argv[4])
    fanout, sample, seed = (int(sys.argv[5]), int(sys.argv[6]),
                            int(sys.argv[7]))
    resource.setrlimit(resource.RLIMIT_AS, (cap, cap))
    from alluxio_tpu.master.inode import Inode
    from alluxio_tpu.master.metastore import create_inode_store

    out = {"kind": kind, "ok": False, "oom": False, "built": 0}
    store = None
    built, next_id = 0, 1
    try:
        store = create_inode_store(kind, directory)
        t0 = time.monotonic()
        dir_ids = []
        while built < total:
            did = next_id
            next_id += 1
            dname = f"d{len(dir_ids):07d}"
            store.put(Inode(id=did, parent_id=0, name=dname,
                            is_directory=True))
            store.add_child(0, dname, did)
            dir_ids.append(did)
            built += 1
            for f in range(fanout):
                if built >= total:
                    break
                fid = next_id
                next_id += 1
                fname = f"f{f:05d}"
                store.put(Inode(id=fid, parent_id=did, name=fname,
                                length=4096, completed=True))
                store.add_child(did, fname, fid)
                built += 1
        out["built"] = built
        out["build_s"] = round(time.monotonic() - t0, 3)

        t0 = time.monotonic()
        edges = 0
        for parent in [0] + dir_ids:
            for _name, _cid in store.iter_edges(parent):
                edges += 1
        out["edges"] = edges
        out["walk_s"] = round(time.monotonic() - t0, 3)

        rng = random.Random(seed)
        t0 = time.monotonic()
        missing = 0
        for _ in range(sample):
            if store.get(rng.randrange(1, next_id)) is None:
                missing += 1
        out["missing"] = missing
        out["stat_s"] = round(time.monotonic() - t0, 3)
        out["store"] = {k: v for k, v in store.stats().items()
                        if isinstance(v, (int, float, str))}
        out["ok"] = edges == built and missing == 0
    except MemoryError:
        # free the namespace FIRST: json/print below must be able to
        # allocate inside the same rlimit that just fired
        store = None
        gc.collect()
        out["oom"] = True
        out["built"] = built
    out["maxrss_kb"] = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    print(json.dumps(out), flush=True)


def run_lsm_capacity(*, inodes: int = 10_000_000, cap_mb: int = 2048,
                     fanout: int = 1000, sample: int = 20_000,
                     seed: int = 7,
                     timeout_s: float = 5400.0) -> BenchResult:
    """The memory-cap gate behind the LSM metastore: the SAME build +
    full-walk + random-stat workload runs once per backend in a fresh
    subprocess capped with ``RLIMIT_AS``.  HEAP must run out of memory
    (proving the cap is real at this namespace size); LSM must finish
    under it with every edge walked and every sampled stat served."""
    import json
    import subprocess

    t_start = time.monotonic()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    child = ("import sys; "
             "from alluxio_tpu.stress.metadata_bench import "
             "_capacity_child; _capacity_child()")
    results = {}
    for kind in ("HEAP", "LSM"):
        base = tempfile.mkdtemp(prefix="atpu_mdcap_")
        try:
            proc = subprocess.run(
                [sys.executable, "-c", child, kind, base, str(inodes),
                 str(cap_mb << 20), str(fanout), str(sample), str(seed)],
                capture_output=True, text=True, timeout=timeout_s,
                env=env)
            lines = (proc.stdout or "").strip().splitlines()
            try:
                results[kind] = json.loads(lines[-1]) if lines else {}
            except json.JSONDecodeError:
                results[kind] = {}
            # a crash before the JSON line (MemoryError inside the
            # handler, rlimit-killed allocator) still means "blew the
            # cap" — record it as such rather than losing the signal
            if proc.returncode != 0 and not results[kind].get("ok"):
                results[kind].setdefault("oom", True)
                results[kind]["exit"] = proc.returncode
        finally:
            shutil.rmtree(base, ignore_errors=True)
    heap, lsm = results["HEAP"], results["LSM"]
    ok = bool(lsm.get("ok")) and bool(heap.get("oom")) and \
        not heap.get("ok")
    if not ok:
        print(f"[metadata-lsm-capacity] gate failed: LSM ok="
              f"{lsm.get('ok')} (built {lsm.get('built')}, edges "
              f"{lsm.get('edges')}, missing {lsm.get('missing')}), "
              f"HEAP oom={heap.get('oom')} ok={heap.get('ok')} under "
              f"{cap_mb} MB", file=sys.stderr)
    metrics = {
        "inodes": inodes, "cap_mb": cap_mb,
        "lsm_ok": bool(lsm.get("ok")),
        "heap_oom": bool(heap.get("oom")),
        "heap_built_before_oom": int(heap.get("built", 0) or 0),
        "lsm_build_s": float(lsm.get("build_s", 0.0) or 0.0),
        "lsm_walk_s": float(lsm.get("walk_s", 0.0) or 0.0),
        "lsm_stat_s": float(lsm.get("stat_s", 0.0) or 0.0),
        "lsm_maxrss_mb": round(
            float(lsm.get("maxrss_kb", 0) or 0) / 1024, 1),
        "heap_maxrss_mb": round(
            float(heap.get("maxrss_kb", 0) or 0) / 1024, 1),
        "gate_ok": ok,
    }
    if lsm.get("build_s"):
        metrics["lsm_build_ops_per_s"] = round(
            int(lsm.get("built", 0)) / float(lsm["build_s"]), 1)
    if lsm.get("stat_s") and sample:
        metrics["lsm_stat_ops_per_s"] = round(
            sample / float(lsm["stat_s"]), 1)
    for k in ("runs", "run_bytes", "flushes", "compactions",
              "compaction_bytes", "cache_hit_ratio"):
        if k in (lsm.get("store") or {}):
            metrics[f"lsm_{k}"] = lsm["store"][k]
    return BenchResult(
        bench="metadata-lsm-capacity",
        params={"inodes": inodes, "cap_mb": cap_mb, "fanout": fanout,
                "sample": sample, "seed": seed},
        metrics=metrics,
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def run(*, row: str = "striped", **kw) -> BenchResult:
    if row == "striped":
        return run_striped(**kw)
    if row == "journal":
        return run_journal_batch(**kw)
    if row == "cached":
        return run_cached_getstatus(**kw)
    if row == "hot-dir":
        return run_hot_dir(**kw)
    if row == "lsm-capacity":
        return run_lsm_capacity(**kw)
    raise ValueError(f"unknown metadata bench row {row!r}")
