"""bench-metadata: metadata control-plane scale-out gates.

Three suite rows, each a ratio against the pre-PR configuration:

- ``metadata-striped`` — mixed CreateFile/GetStatus/ListStatus/Delete
  across disjoint per-thread subtrees, striped inode locking + journal
  group commit vs the single tree-wide lock with inline fsync (the
  pre-PR master).  Gate: >= 3x ops/s.
- ``metadata-journal-batch`` — CreateFile-only under the same
  comparison, isolating the durability path.  Gate: >= 1.5x.
- ``metadata-cached-getstatus`` — warm client-metadata-cache GetStatus
  vs the uncached RPC round trip on a live in-process cluster.
  Gate: >= 10x.

The journal rides a **modeled slow fsync** (``--fsync-ms``, default
3ms — local-disk/NFS class): on tmpfs-backed CI an fsync is nearly
free, which would understate exactly the serialization the pre-PR
master suffers on real media.  The model follows the established
bench practice here (connection-limited worker/UFS models in
bench-remote-read / bench-ufs-cold).  Gates are RATIOS with wide
margins, so scheduler jitter moves both sides together.
"""

from __future__ import annotations

import itertools
import os
import shutil
import sys
import tempfile
import time
from typing import Optional

from alluxio_tpu.journal.system import LocalJournalSystem
from alluxio_tpu.stress.base import BenchResult, drive, percentiles


class _SlowFsyncJournal(LocalJournalSystem):
    """LocalJournalSystem whose fsync costs ``fsync_s`` extra — the
    disk model.  Counts fsyncs so batching is observable."""

    def __init__(self, folder: str, fsync_s: float, **kw) -> None:
        super().__init__(folder, **kw)
        self.fsync_s = fsync_s
        self.fsync_count = 0

    def _fsync(self, fd: int) -> None:
        self.fsync_count += 1
        if self.fsync_s > 0:
            time.sleep(self.fsync_s)
        os.fsync(fd)


class _Master:
    """An in-process FileSystemMaster + journal, pre-PR (coarse +
    inline fsync) or post-PR (striped + group commit) flavor."""

    def __init__(self, base: str, *, coarse: bool, batched: bool,
                 fsync_s: float, batch_time_s: float) -> None:
        from alluxio_tpu.master.block_master import BlockMaster
        from alluxio_tpu.master.file_master import FileSystemMaster

        self.journal = _SlowFsyncJournal(base, fsync_s)
        self.journal.start()
        self.journal.gain_primacy()
        if batched:
            self.journal.start_group_commit(batch_time_s)
        self.block_master = BlockMaster(self.journal)
        self.fsm = FileSystemMaster(self.block_master, self.journal,
                                    coarse_locking=coarse)
        self.fsm.start(None)

    def close(self) -> None:
        self.fsm.stop()
        self.journal.stop()


def _mixed_body(fsm, threads: int):
    """Per-thread cycle over its own subtree: create -> stat -> list ->
    delete.  Disjoint subtrees are the training-shard common case the
    striping targets."""
    for t in range(threads):
        fsm.create_directory(f"/t{t}", recursive=True, allow_exists=True)
    counters = [itertools.count() for _ in range(threads)]

    def body(t: int, i: int) -> int:
        j = next(counters[t])
        seq, phase = j // 4, j % 4
        if phase == 0:
            fsm.create_file(f"/t{t}/x-{seq:08d}")
        elif phase == 1:
            fsm.get_status(f"/t{t}/x-{seq:08d}")
        elif phase == 2:
            fsm.list_status(f"/t{t}")
        else:
            fsm.delete(f"/t{t}/x-{seq:08d}")
        return 0

    return body


def _create_body(fsm, threads: int):
    for t in range(threads):
        fsm.create_directory(f"/t{t}", recursive=True, allow_exists=True)
    counters = [itertools.count() for _ in range(threads)]

    def body(t: int, i: int) -> int:
        fsm.create_file(f"/t{t}/c-{next(counters[t]):09d}")
        return 0

    return body


def _run_mode(make_body, *, coarse: bool, batched: bool, threads: int,
              duration_s: float, fsync_s: float, batch_time_s: float):
    base = tempfile.mkdtemp(prefix="atpu_mdbench_")
    master = _Master(base, coarse=coarse, batched=batched,
                     fsync_s=fsync_s, batch_time_s=batch_time_s)
    try:
        body = make_body(master.fsm, threads)
        res = drive(threads, body, duration_s=duration_s)
        return res, master.journal.fsync_count
    finally:
        master.close()
        shutil.rmtree(base, ignore_errors=True)


def _ratio_row(bench: str, make_body, *, threads: int, duration_s: float,
               fsync_ms: float, batch_time_ms: float,
               min_speedup: float) -> BenchResult:
    t_start = time.monotonic()
    fsync_s, batch_s = fsync_ms / 1e3, batch_time_ms / 1e3
    base_res, base_fsyncs = _run_mode(
        make_body, coarse=True, batched=False, threads=threads,
        duration_s=duration_s, fsync_s=fsync_s, batch_time_s=batch_s)
    new_res, new_fsyncs = _run_mode(
        make_body, coarse=False, batched=True, threads=threads,
        duration_s=duration_s, fsync_s=fsync_s, batch_time_s=batch_s)
    speedup = new_res.ops_per_s / base_res.ops_per_s \
        if base_res.ops_per_s > 0 else 0.0
    ok = speedup >= min_speedup and base_res.errors == 0 and \
        new_res.errors == 0
    if not ok:
        print(f"[{bench}] speedup {speedup:.2f}x below the "
              f"{min_speedup}x gate (baseline "
              f"{base_res.ops_per_s:.0f} ops/s, striped+batched "
              f"{new_res.ops_per_s:.0f} ops/s, errors "
              f"{base_res.errors}+{new_res.errors})", file=sys.stderr)
    return BenchResult(
        bench=bench,
        params={"threads": threads, "duration_s": duration_s,
                "fsync_ms": fsync_ms, "batch_time_ms": batch_time_ms,
                "min_speedup": min_speedup},
        metrics={"baseline_ops_per_s": round(base_res.ops_per_s, 1),
                 "striped_batched_ops_per_s": round(new_res.ops_per_s, 1),
                 "speedup": round(speedup, 3),
                 "baseline_fsyncs": base_fsyncs,
                 "striped_fsyncs": new_fsyncs,
                 "baseline_" + "p99_us":
                     percentiles(base_res.latencies_s)["p99_us"],
                 "striped_p99_us":
                     percentiles(new_res.latencies_s)["p99_us"],
                 "gate_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def run_striped(*, threads: int = 8, duration_s: float = 2.0,
                fsync_ms: float = 3.0, batch_time_ms: float = 2.0,
                min_speedup: float = 3.0) -> BenchResult:
    return _ratio_row("metadata-striped", _mixed_body, threads=threads,
                      duration_s=duration_s, fsync_ms=fsync_ms,
                      batch_time_ms=batch_time_ms, min_speedup=min_speedup)


def run_journal_batch(*, threads: int = 8, duration_s: float = 2.0,
                      fsync_ms: float = 3.0, batch_time_ms: float = 2.0,
                      min_speedup: float = 1.5) -> BenchResult:
    return _ratio_row("metadata-journal-batch", _create_body,
                      threads=threads, duration_s=duration_s,
                      fsync_ms=fsync_ms, batch_time_ms=batch_time_ms,
                      min_speedup=min_speedup)


def run_cached_getstatus(*, master: Optional[str] = None, threads: int = 4,
                         duration_s: float = 1.5, files: int = 64,
                         min_speedup: float = 10.0) -> BenchResult:
    """Warm client-cache GetStatus vs the uncached RPC round trip on a
    live (in-process by default) cluster."""
    from alluxio_tpu.conf import Keys
    from alluxio_tpu.stress.cluster import bench_cluster

    t_start = time.monotonic()
    with bench_cluster(master, block_size=1 << 20,
                       worker_mem_bytes=64 << 20,
                       conf_overrides={
                           Keys.USER_METADATA_CACHE_ENABLED: True,
                       }) as (fs, _cluster):
        from alluxio_tpu.client.streams import WriteType

        base = "/md-cache-bench"
        fs.create_directory(base, recursive=True, allow_exists=True)
        paths = [f"{base}/f-{i:04d}" for i in range(files)]
        for p in paths:
            fs.write_all(p, b"", write_type=WriteType.MUST_CACHE)

        def uncached(t: int, i: int) -> int:
            fs.fs_master.get_status(paths[i % files])
            return 0

        cold = drive(threads, uncached, duration_s=duration_s)
        for p in paths:  # warm the cache
            fs.get_status(p)
        hits0 = fs._md_hits.count

        def cached(t: int, i: int) -> int:
            fs.get_status(paths[i % files])
            return 0

        warm = drive(threads, cached, duration_s=duration_s)
        hits = fs._md_hits.count - hits0
        try:
            fs.delete(base, recursive=True)
        except Exception:  # noqa: BLE001 cleanup is best-effort
            pass
    speedup = warm.ops_per_s / cold.ops_per_s if cold.ops_per_s else 0.0
    # the warm pass must have been served by the CACHE, not by fast RPCs
    ok = speedup >= min_speedup and hits >= warm.ops and \
        cold.errors == 0 and warm.errors == 0
    if not ok:
        print(f"[metadata-cached-getstatus] speedup {speedup:.2f}x "
              f"(gate {min_speedup}x), cache hits {hits}/{warm.ops}, "
              f"errors {cold.errors}+{warm.errors}", file=sys.stderr)
    return BenchResult(
        bench="metadata-cached-getstatus",
        params={"threads": threads, "duration_s": duration_s,
                "files": files, "min_speedup": min_speedup,
                "master": master or "in-process"},
        metrics={"uncached_ops_per_s": round(cold.ops_per_s, 1),
                 "cached_ops_per_s": round(warm.ops_per_s, 1),
                 "speedup": round(speedup, 3),
                 "cache_hits": hits,
                 "uncached_p99_us": percentiles(cold.latencies_s)["p99_us"],
                 "cached_p99_us": percentiles(warm.latencies_s)["p99_us"],
                 "gate_ok": ok},
        errors=0 if ok else 1,
        duration_s=time.monotonic() - t_start)


def run(*, row: str = "striped", **kw) -> BenchResult:
    if row == "striped":
        return run_striped(**kw)
    if row == "journal":
        return run_journal_batch(**kw)
    if row == "cached":
        return run_cached_getstatus(**kw)
    raise ValueError(f"unknown metadata bench row {row!r}")
