"""StressMasterBench analogue: master metadata op/s.

Reference ``stress/shell/.../cli/StressMasterBench.java``: N client
threads hammer one metadata op — CreateFile / GetStatus / ListStatus /
Delete / Rename — against the master for a fixed duration; the summary
reports op/s + latency percentiles. Each thread works under its own
directory (the reference's per-thread ``/stress-master-base/<id>`` dirs)
so Create/Delete don't contend on one parent inode's mutex.
"""

from __future__ import annotations

import itertools
from typing import Optional

from alluxio_tpu.stress.base import (
    BenchResult, RateLimiter, drive, percentiles,
)
from alluxio_tpu.stress.cluster import bench_cluster

OPS = ("CreateFile", "GetStatus", "ListStatus", "ListStatusStream",
       "DeleteFile", "RenameFile")


def _prep(fs, op: str, threads: int, fixed_count: int,
          base_path: str) -> None:
    """Pre-populate fixtures: read ops get ``fixed_count`` files per
    thread dir; delete/rename get a large pool to consume."""
    from alluxio_tpu.client.streams import WriteType

    for t in range(threads):
        fs.create_directory(f"{base_path}/{t}", allow_exists=True,
                            recursive=True)
    if op in ("GetStatus", "ListStatus", "ListStatusStream",
              "DeleteFile", "RenameFile"):
        for t in range(threads):
            for i in range(fixed_count):
                fs.write_all(f"{base_path}/{t}/f-{i:06d}", b"",
                             write_type=WriteType.MUST_CACHE)


def run(*, op: str = "CreateFile", master: Optional[str] = None,
        threads: int = 8, duration_s: float = 10.0,
        fixed_count: int = 200, base_path: str = "/stress-master",
        target_ops_per_s: float = 0.0,
        _reuse_fs=None) -> BenchResult:
    if op not in OPS:
        raise ValueError(f"op must be one of {OPS}, got {op!r}")

    def _run(fs) -> BenchResult:
        from alluxio_tpu.client.streams import WriteType

        _prep(fs, op, threads, fixed_count, base_path)
        counters = [itertools.count() for _ in range(threads)]

        if op == "CreateFile":
            def body(t: int, i: int) -> int:
                fs.write_all(f"{base_path}/{t}/c-{next(counters[t]):09d}",
                             b"", write_type=WriteType.MUST_CACHE)
                return 0
        elif op == "GetStatus":
            def body(t: int, i: int) -> int:
                fs.fs_master.get_status(
                    f"{base_path}/{t}/f-{i % fixed_count:06d}")
                return 0
        elif op == "ListStatus":
            def body(t: int, i: int) -> int:
                fs.fs_master.list_status(f"{base_path}/{t}")
                return 0
        elif op == "ListStatusStream":
            # the partial-response listing RPC (reference streams
            # ListStatus, file_system_master.proto:475-590) — sized for
            # LARGE directories where one-shot listing would build the
            # whole reply in memory
            def body(t: int, i: int) -> int:
                n = 0
                for _st in fs.fs_master.iter_status(f"{base_path}/{t}"):
                    n += 1
                if n < fixed_count:
                    raise RuntimeError(
                        f"stream returned {n} < {fixed_count} entries")
                return n  # drive() sums returns -> real entry counts
        elif op == "DeleteFile":
            def body(t: int, i: int) -> int:
                n = next(counters[t])
                if n >= fixed_count:  # pool drained: recreate then delete
                    fs.write_all(f"{base_path}/{t}/f-{n:09d}", b"",
                                 write_type=WriteType.MUST_CACHE)
                    fs.delete(f"{base_path}/{t}/f-{n:09d}")
                else:
                    fs.delete(f"{base_path}/{t}/f-{n:06d}")
                return 0
        else:  # RenameFile
            def body(t: int, i: int) -> int:
                n = next(counters[t])
                if n < fixed_count:  # drain the pre-created pool first
                    src = f"{base_path}/{t}/f-{n:06d}"
                else:  # pool drained: create-then-rename (distinct prefix)
                    src = f"{base_path}/{t}/s-{n:09d}"
                    fs.write_all(src, b"", write_type=WriteType.MUST_CACHE)
                fs.rename(src, f"{base_path}/{t}/d-{n:09d}")
                return 0

        limiter = RateLimiter(target_ops_per_s) if target_ops_per_s else None
        res = drive(threads, body, duration_s=duration_s,
                    rate_limiter=limiter)
        return BenchResult(
            bench=f"master-{op}",
            params={"threads": threads, "duration_s": duration_s,
                    "fixed_count": fixed_count,
                    "target_ops_per_s": target_ops_per_s,
                    "master": master or "in-process"},
            metrics={"ops_per_s": round(res.ops_per_s, 1),
                     **({"entries_per_s":
                         round(res.bytes / res.wall_s, 1)
                         if res.wall_s > 0 else 0.0}
                        if op == "ListStatusStream" else {}),
                     **percentiles(res.latencies_s)},
            errors=res.errors, duration_s=res.wall_s)

    if _reuse_fs is not None:
        try:  # live cluster: bench fixtures must not outlive the run
            return _run(_reuse_fs)
        finally:
            try:
                _reuse_fs.delete(base_path, recursive=True)
            except Exception:  # noqa: BLE001 cleanup is best-effort
                pass
    # metadata-only: tiny worker, tiny blocks (zero-byte files need no data)
    with bench_cluster(master, block_size=1 << 20,
                       worker_mem_bytes=64 << 20) as (fs, _cluster):
        return _run(fs)


def run_max_throughput(*, op: str = "CreateFile",
                       master: Optional[str] = None, threads: int = 8,
                       duration_s: float = 3.0, fixed_count: int = 200,
                       lower: float = 50.0, upper: float = 50000.0,
                       tolerance: float = 0.05) -> BenchResult:
    """MaxThroughput suite (``cli/suite/MaxThroughput.java``): binary
    search for the highest target op/s the master sustains — a target
    "passes" when achieved >= (1 - tolerance) * target. First an
    unthrottled probe bounds the search; then each iteration runs the
    bench rate-limited at the midpoint."""
    probe = run(op=op, master=master, threads=threads,
                duration_s=duration_s, fixed_count=fixed_count,
                base_path="/stress-maxtp-probe")
    achieved = probe.metrics["ops_per_s"]
    hi = min(upper, achieved * 2.0)
    lo = lower
    best = 0.0
    best_metrics = probe.metrics
    rounds = 0
    while hi - lo > max(1.0, 0.05 * hi) and rounds < 8:
        mid = (lo + hi) / 2.0
        r = run(op=op, master=master, threads=threads,
                duration_s=duration_s, fixed_count=fixed_count,
                base_path=f"/stress-maxtp-{rounds}",
                target_ops_per_s=mid)
        rounds += 1
        if r.metrics["ops_per_s"] >= (1.0 - tolerance) * mid:
            best, best_metrics, lo = mid, r.metrics, mid
        else:
            hi = mid
    return BenchResult(
        bench=f"master-maxthroughput-{op}",
        params={"threads": threads, "duration_s": duration_s,
                "rounds": rounds, "master": master or "in-process"},
        metrics={"max_sustained_ops_per_s": round(best if best else achieved,
                                                  1),
                 "unthrottled_ops_per_s": achieved,
                 **{k: v for k, v in best_metrics.items()
                    if k.endswith("_us")}},
        errors=0, duration_s=rounds * duration_s)
