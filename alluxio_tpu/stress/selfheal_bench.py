"""Self-healing remediation bench (``make bench-selfheal``).

Gates the two promises the remediation engine makes:

- **detection → action latency**: on a fake clock ticking the health
  evaluation every ``eval_interval_s``, the simulated time from the
  first straggler sample to the *executed* quarantine action must stay
  within ``fire_after + 2 x eval_interval`` — the engine adds at most
  one tick on top of the health engine's own debounce;
- **tick overhead**: attaching the engine as an alert listener must
  add **<2%** to the health-engine evaluation tick.  Measured by
  instrumenting the listener itself — per-tick engine time over
  per-tick rule-evaluation time — because the added work (~10-20us)
  sits far below this CI host's paired-run jitter (observed IQR
  ~±100us on a 1.1ms tick); A/B pairing would gate noise, not the
  engine.

Everything runs in-process against a stub block master — the bench
measures the engine's control loop, not gRPC.
"""

from __future__ import annotations

import statistics
import sys
import time
from typing import Dict, List

from alluxio_tpu.stress.base import BenchResult


class _FakeClock:
    """Same shape (and cost rationale) as health_bench's fake clock."""

    def __init__(self) -> None:
        self.now = 1_000_000.0

    def __call__(self) -> float:
        return self.now

    def advance(self, s: float) -> None:
        self.now += s


class _Addr:
    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.rpc_port = port


class _StubWorker:
    def __init__(self, wid: int, host: str, port: int,
                 blocks: Dict[int, str]) -> None:
        self.id = wid
        self.address = _Addr(host, port)
        self.capacity_bytes_on_tiers = {"MEM": 1 << 30}
        self.blocks = dict(blocks)


class _StubBlockMaster:
    """Just enough surface for the engine: listing, lookup,
    quarantine/release."""

    def __init__(self, workers: List[_StubWorker]) -> None:
        self._workers = {w.id: w for w in workers}
        self._by_source = {
            f"worker-{w.address.host}:{w.address.rpc_port}": w.id
            for w in workers}
        self.quarantined: Dict[int, float] = {}

    def get_worker_infos(self, include_lost: bool = False,
                         include_quarantined: bool = True):
        return [w for w in self._workers.values()
                if include_quarantined or w.id not in self.quarantined]

    def get_worker(self, wid: int):
        return self._workers.get(wid)

    def worker_id_for_source(self, source: str):
        # O(1) like the real BlockMaster's index — the bench gates the
        # engine's cost, not a stub's scan
        return self._by_source.get(source)

    def quarantine_worker(self, wid: int) -> bool:
        if wid not in self._workers:
            return False
        self.quarantined[wid] = 1.0
        return True

    def release_worker(self, wid: int) -> bool:
        return self.quarantined.pop(wid, None) is not None

    def quarantined_workers(self):
        return dict(self.quarantined)


def _heartbeat_all(mm, sources: int, straggler_p99: float = 0.0,
                   metrics_per_source: int = 40) -> None:
    """Realistic heartbeat payloads: a live worker ships ~100-150
    metric entries (bench-health models 120); the health tick's cost —
    the denominator of the gated overhead ratio — folds and probes all
    of them, so shipping 2 would deflate it ~20x and gate the engine
    against a toy tick."""
    for s in range(sources):
        p99 = 0.002
        if straggler_p99 and s == 0:
            p99 = straggler_p99
        # lint: allow[metric-unknown] -- synthetic heartbeat payload: the bench models realistic 40-metric reports with fabricated names
        snap = {f"Worker.BenchMetric{m}": float(s * 7 + m)
                for m in range(metrics_per_source - 1)}
        snap["Worker.ReadBlockTime.p99"] = p99
        mm.handle_heartbeat({"source": f"worker-host{s}:29999",
                             "metrics": snap})


def _build(clock, *, sources: int, with_engine: bool,
           fire_after_s: float, eval_interval_s: float):
    from alluxio_tpu.master.health import HealthMonitor, default_rules
    from alluxio_tpu.master.metrics_master import (
        MetricsMaster, MetricsStore,
    )
    from alluxio_tpu.master.remediation import RemediationEngine
    from alluxio_tpu.metrics.history import MetricsHistory

    mm = MetricsMaster(
        store=MetricsStore(clock=clock),
        history=MetricsHistory(clock=clock, max_series=16384,
                               pending_max=sources + 8))
    monitor = HealthMonitor(mm, rules=default_rules(),
                            fire_after_s=fire_after_s,
                            resolve_after_s=fire_after_s,
                            eval_interval_s=eval_interval_s,
                            clock=clock)
    engine = None
    if with_engine:
        workers = [_StubWorker(100 + s, f"host{s}", 29999,
                               {1000 + s: "MEM"})
                   for s in range(sources)]
        # cooldown/eval ratio matches the production defaults (300s /
        # 10s = 30 ticks): the overhead gate measures the engine at
        # its real duty cycle — acting ticks are bounded by cooldown
        # and the window cap, so their amortized cost is part of what
        # the 2% budget covers
        engine = RemediationEngine(
            _StubBlockMaster(workers), metrics_master=mm,
            cooldown_s=30.0 * eval_interval_s, probation_s=0.0,
            window_s=600.0, max_actions_per_window=8, clock=clock)
        monitor.alert_listeners.append(engine.on_alerts)
    return mm, monitor, engine


def run(*, sources: int = 64, ticks: int = 60, batches: int = 6,
        eval_interval_s: float = 5.0, fire_after_s: float = 10.0,
        max_overhead_pct: float = 2.0) -> BenchResult:
    t_start = time.monotonic()

    # ---- phase 1: detection -> action latency on the fake clock ------
    clock = _FakeClock()
    mm, monitor, engine = _build(clock, sources=sources, with_engine=True,
                                 fire_after_s=fire_after_s,
                                 eval_interval_s=eval_interval_s)
    # settle: healthy fleet, no alerts
    for _ in range(3):
        _heartbeat_all(mm, sources)
        monitor.evaluate()
        clock.advance(eval_interval_s)
    t_inject = clock()
    action_at = None
    for _ in range(40):
        _heartbeat_all(mm, sources, straggler_p99=0.5)
        monitor.evaluate()
        executed = [a for a in engine.report()["audit"]
                    if a["action"] == "quarantine"
                    and a["outcome"] == "executed"]
        if executed:
            action_at = executed[0]["at"]
            break
        clock.advance(eval_interval_s)
    detect_to_act_s = (action_at - t_inject) if action_at else float("inf")
    latency_budget_s = fire_after_s + 2 * eval_interval_s
    latency_ok = detect_to_act_s <= latency_budget_s

    # ---- phase 2: engine overhead on the health tick ------------------
    clock2 = _FakeClock()
    mm2, mon2, eng2 = _build(clock2, sources=sources, with_engine=True,
                             fire_after_s=fire_after_s,
                             eval_interval_s=eval_interval_s)
    # instrument the listener: its per-tick time IS the added cost —
    # timing it inline (not A/B) keeps the CI host's run-to-run drift
    # out of the gated ratio
    engine_times: List[float] = []
    inner = eng2.on_alerts

    def timed_listener(alerts, now=None):
        t0 = time.perf_counter()
        inner(alerts, now)
        engine_times.append(time.perf_counter() - t0)

    mon2.alert_listeners[:] = [timed_listener]
    tick_times: List[float] = []
    for b in range(batches):
        for t in range(ticks):
            # one straggler phase per batch so the engine pays its
            # acting cost inside the measured region, not just the
            # no-alert fast path
            p99 = 0.5 if (t % ticks) > ticks // 2 else 0.0
            _heartbeat_all(mm2, sources, straggler_p99=p99)
            t0 = time.perf_counter()
            mon2.evaluate()
            tick_times.append(time.perf_counter() - t0)
            clock2.advance(eval_interval_s)
    # MEANS, not medians: the engine's cost is spiky by design (audit
    # rows and history samples land on state changes), and the budget
    # bounds the total tax on the heartbeat, not the typical tick.
    # Top 1% of engine samples dropped: the engine window is ~2% of
    # the tick, so a host pause (GC, scheduler) landing inside it
    # bills milliseconds of machine noise to microseconds of work —
    # the design spikes (history ingest, ~40-70us, dozens per run)
    # survive a 1% trim
    cut = max(1, len(engine_times) // 100)
    engine_kept = sorted(engine_times)[:-cut]
    engine_mean = sum(engine_kept) / len(engine_kept)
    tick_mean = sum(tick_times) / len(tick_times)
    base_mean = tick_mean - engine_mean
    off_med = statistics.median(
        t - e for t, e in zip(tick_times, engine_times))
    on_med = statistics.median(tick_times)
    overhead_pct = 100.0 * engine_mean / base_mean \
        if base_mean > 0 else 0.0
    overhead_ok = overhead_pct <= max_overhead_pct

    errors = 0
    if not latency_ok:
        errors += 1
        print(f"[selfheal] detection->action {detect_to_act_s:.1f}s "
              f"exceeds the {latency_budget_s:.1f}s budget "
              f"(fire_after + 2 ticks)", file=sys.stderr)
    if not overhead_ok:
        errors += 1
        print(f"[selfheal] remediation adds {overhead_pct:.2f}% to the "
              f"health tick, over the {max_overhead_pct}% budget",
              file=sys.stderr)
    return BenchResult(
        bench="selfheal-remediation",
        params={"sources": sources, "ticks": ticks, "batches": batches,
                "eval_interval_s": eval_interval_s,
                "fire_after_s": fire_after_s,
                "max_overhead_pct": max_overhead_pct},
        metrics={"detect_to_act_s": round(detect_to_act_s, 3),
                 "latency_budget_s": latency_budget_s,
                 "latency_ok": latency_ok,
                 "eval_off_us": round(1e6 * off_med, 3),
                 "eval_on_us": round(1e6 * on_med, 3),
                 "overhead_pct": round(overhead_pct, 3),
                 "overhead_ok": overhead_ok},
        errors=errors,
        duration_s=time.monotonic() - t_start)
