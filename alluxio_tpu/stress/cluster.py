"""Cluster context for stress benches: in-process LocalCluster (default,
the reference's ``--in-process`` smoke mode, ``BaseParameters.java:81``)
or a live cluster via ``--master host:port`` (``--cluster`` mode)."""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
from typing import Dict, Iterator, Optional, Tuple


def write_cold_corpus(fs, block_client, paths_and_payloads, *,
                      timeout_s: float = 60.0) -> None:
    """Persist ``{path: payload}`` THROUGH to the UFS, then wait until
    every cached copy has been freed — the cold-start precondition the
    prefetch benches and tests measure from. THROUGH frees the cached
    copy asynchronously (the worker heartbeat applies the Free
    command), so writing alone does not make the corpus cold."""
    import time

    from alluxio_tpu.client.streams import WriteType

    for path, payload in paths_and_payloads.items():
        fs.write_all(path, payload, write_type=WriteType.THROUGH)
    deadline = time.monotonic() + timeout_s
    for path in paths_and_payloads:
        for fbi in fs.fs_master.get_file_block_info_list(path):
            while block_client.get_block_info(
                    fbi.block_info.block_id).locations:
                if time.monotonic() > deadline:
                    raise RuntimeError("corpus never went cold")
                time.sleep(0.02)


@contextlib.contextmanager
def bench_cluster(master: Optional[str] = None, *, num_workers: int = 1,
                  block_size: int = 32 << 20,
                  worker_mem_bytes: int = 1 << 30,
                  conf_overrides: Optional[Dict] = None,
                  start_job_service: bool = False,
                  start_worker_heartbeats: bool = False,
                  ) -> Iterator[Tuple[object, object]]:
    """Yields ``(fs, cluster_or_None)``. With ``master`` set, attaches a
    FileSystem client to the live cluster; otherwise stands up a scratch
    LocalCluster on /dev/shm (tears it down afterwards)."""
    if master:
        from alluxio_tpu.client.file_system import FileSystem
        from alluxio_tpu.conf import Configuration

        fs = FileSystem(master, conf=Configuration(load_env=False))
        try:
            yield fs, None
        finally:
            fs.close()
        return
    base = tempfile.mkdtemp(
        prefix="atpu_stress_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    try:
        from alluxio_tpu.minicluster import LocalCluster

        with LocalCluster(base, num_workers=num_workers,
                          block_size=block_size,
                          worker_mem_bytes=worker_mem_bytes,
                          conf_overrides=conf_overrides,
                          start_job_service=start_job_service,
                          start_worker_heartbeats=start_worker_heartbeats
                          ) as cluster:
            fs = cluster.file_system()
            try:
                yield fs, cluster
            finally:
                fs.close()
    finally:
        shutil.rmtree(base, ignore_errors=True)
