"""BASELINE config #3: distributed prefetch (DistributedLoad) GB/s.

Reference analogue: the job-service DistributedLoad path
(``job/server/src/main/java/alluxio/job/plan/load/LoadDefinition.java:65``)
— files persisted in the UFS but not cached are fanned out across N
workers' caches by load-plan tasks; the metric is aggregate prefetch
GB/s from job submission to every block landing in a worker tier.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from alluxio_tpu.stress.base import BenchResult
from alluxio_tpu.stress.cluster import bench_cluster


def run(*, master: Optional[str] = None, num_workers: int = 4,
        num_files: int = 8, file_bytes: int = 16 << 20,
        replication: int = 1, block_size: int = 4 << 20,
        base_path: str = "/stress-prefetch",
        pressure: bool = False, kill_worker: bool = False,
        rereplicate_timeout_s: float = 240.0) -> BenchResult:
    """``pressure=True`` sizes worker tiers so eviction must fire
    mid-load (tiers are pre-filled with MUST_CACHE filler the load then
    evicts). ``kill_worker=True`` stops one worker (block + job) while
    the load job runs; the plan must still COMPLETE (task failover) and
    the replication checker must restore the killed worker's copies —
    the failure envelope ``LoadDefinition.java:65``-style fan-out exists
    to survive."""
    from alluxio_tpu.client.streams import WriteType

    if master:
        raise NotImplementedError(
            "prefetch bench provisions its own multi-worker cluster")
    from alluxio_tpu.conf import Keys

    rng = np.random.default_rng(0)
    total = num_files * file_bytes
    per_worker_corpus = -(-total * max(replication, 1) // num_workers)
    mem = (per_worker_corpus + 2 * block_size + (8 << 20)) if pressure \
        else total + (128 << 20)
    overrides = {Keys.WORKER_BLOCK_HEARTBEAT_INTERVAL: "50ms"}
    if pressure:
        # single tier: MEM eviction must DROP blocks, not cascade-demote
        # into the default 64MB SSD tier (which would absorb the whole
        # pressure corpus and prove nothing)
        overrides[Keys.WORKER_TIERED_STORE_LEVELS] = 1
    if kill_worker:
        # the master must notice the kill quickly: lost-worker
        # detection drops its block locations, which is what arms the
        # replication checker
        overrides[Keys.MASTER_WORKER_TIMEOUT] = "2s"
        overrides[Keys.JOB_MASTER_WORKER_TIMEOUT] = "2s"
    with bench_cluster(None, num_workers=num_workers,
                       block_size=block_size,
                       worker_mem_bytes=mem,
                       start_job_service=True,
                       start_worker_heartbeats=True,
                       conf_overrides=overrides) as (fs, cluster):
        # THROUGH: persisted to the UFS, cached nowhere — the cold corpus
        payload = rng.integers(0, 255, size=file_bytes, dtype=np.uint8
                               ).tobytes()
        for i in range(num_files):
            fs.write_all(f"{base_path}/f-{i:05d}", payload,
                         write_type=WriteType.THROUGH)
        # THROUGH frees the cached copy asynchronously (worker heartbeat
        # applies the Free command): wait until the corpus is truly cold
        deadline = time.monotonic() + 60.0
        bc = cluster.block_client()
        for i in range(num_files):
            for fbi in fs.fs_master.get_file_block_info_list(
                    f"{base_path}/f-{i:05d}"):
                while bc.get_block_info(fbi.block_info.block_id).locations:
                    if time.monotonic() > deadline:
                        raise RuntimeError("corpus never went cold")
                    time.sleep(0.02)
        filler_paths = []
        if pressure:
            # fill ~the whole cluster capacity so the load can only
            # proceed by EVICTING (MUST_CACHE filler; LRU/LRFU decides
            # what goes; the last writes may already evict earlier
            # filler — that's the point)
            filler_each = max(block_size, mem // 2 - (1 << 20))
            fill = rng.integers(0, 255, size=filler_each,
                                dtype=np.uint8).tobytes()
            for w in range(num_workers * 2):
                p = f"{base_path}-fill/f-{w}"
                try:
                    fs.write_all(p, fill,
                                 write_type=WriteType.MUST_CACHE)
                    filler_paths.append(p)
                except Exception:  # noqa: BLE001 tier genuinely full
                    break

        killed_mid_job = False
        filler_prekill: dict = {}
        if kill_worker:
            # snapshot filler residency BEFORE the job: the post-kill
            # eviction accounting compares against this to tell
            # "evicted by pressure" from "lost with the worker" (a
            # snapshot at kill time would miss blocks the job already
            # evicted and under-count)
            for p in filler_paths:
                for fbi in fs.fs_master.get_file_block_info_list(p):
                    hosts = {loc.address.tiered_identity.value("host")
                             for loc in fbi.block_info.locations}
                    filler_prekill[(p, fbi.block_info.block_id)] = hosts

        job_client = cluster.job_client()
        t0 = time.monotonic()
        job_id = job_client.run({"type": "load", "path": base_path,
                                 "replication": replication})
        killed_host = ""
        if kill_worker:
            # arm durable-replication recovery NOW (not at write time:
            # a replication_min on a still-cold corpus would have the
            # 0.1s-tick checker churn failing replicate jobs for the
            # whole cold-wait, and race the measured load)
            for i in range(num_files):
                fs.set_attribute(f"{base_path}/f-{i:05d}",
                                 replication_min=max(replication, 1))
            # gate the kill on the job being observed RUNNING with
            # unfinished tasks — a fixed sleep races a fast load and
            # the drill would pass without exercising failover. 20ms:
            # tasks take at least one 50ms worker heartbeat to be
            # pulled, and get_status serializes the task list.
            gate = time.monotonic() + 10.0
            while time.monotonic() < gate:
                ji = job_client.get_status(job_id)
                unfinished = [t for t in ji.tasks
                              if t.status not in ("COMPLETED", "FAILED",
                                                  "CANCELED")]
                if ji.status == "RUNNING" and unfinished:
                    killed_mid_job = True
                    break
                if ji.status != "RUNNING" and ji.status != "CREATED":
                    break  # job already finished: kill is post-job
                time.sleep(0.02)
            victim = cluster.workers[0]
            killed_host = victim.worker.address.tiered_identity.value(
                "host")
            victim.stop()
            cluster.job_workers[0].stop()
        info = job_client.wait_for_job(job_id, timeout_s=300.0)
        wall = time.monotonic() - t0
        if info.status != "COMPLETED":
            raise RuntimeError(
                f"load job {job_id} ended {info.status}: "
                f"{info.error_message}")

        def replication_counts():
            blocks = cached = 0
            for i in range(num_files):
                for fbi in fs.fs_master.get_file_block_info_list(
                        f"{base_path}/f-{i:05d}"):
                    blocks += 1
                    if len(fbi.block_info.locations) >= replication:
                        cached += 1
            return blocks, cached

        blocks, cached = replication_counts()
        rerepl_wait = 0.0
        if kill_worker:
            # the killed worker's copies must come back: lost-worker
            # detection drops its locations, the ReplicationChecker
            # re-issues replicate jobs until the target holds again
            t1 = time.monotonic()
            deadline = t1 + rereplicate_timeout_s
            while cached < blocks and time.monotonic() < deadline:
                time.sleep(0.25)
                blocks, cached = replication_counts()
            rerepl_wait = time.monotonic() - t1
            if cached < blocks:
                raise RuntimeError(
                    f"re-replication never converged: {cached}/{blocks} "
                    f"blocks at replication {replication} after "
                    f"{rereplicate_timeout_s:.0f}s")
        evicted_filler = 0
        if pressure:
            for p in filler_paths:
                dropped_by_live = False
                for fbi in fs.fs_master.get_file_block_info_list(p):
                    cur = {loc.address.tiered_identity.value("host")
                           for loc in fbi.block_info.locations}
                    pre = filler_prekill.get(
                        (p, fbi.block_info.block_id))
                    if pre is None:  # no kill: any miss is an eviction
                        if not cur:
                            dropped_by_live = True
                    elif (pre - {killed_host}) - cur:
                        # a host OTHER than the killed one dropped the
                        # block -> genuine pressure eviction, not loss
                        dropped_by_live = True
                if dropped_by_live:
                    evicted_filler += 1
            if not evicted_filler:
                raise RuntimeError(
                    "pressure drill never forced an eviction — tier "
                    "sizing is wrong, the drill proved nothing")
        moved = total * replication
        return BenchResult(
            bench="distributed-prefetch",
            params={"num_workers": num_workers, "num_files": num_files,
                    "file_bytes": file_bytes, "replication": replication,
                    "block_size": block_size, "pressure": pressure,
                    "worker_killed": kill_worker},
            metrics={"gb_per_s": round(moved / wall / 1e9, 3),
                     "mb_per_s": round(moved / wall / 1e6, 2),
                     "blocks": blocks, "blocks_at_replication": cached,
                     "evicted_filler_files": evicted_filler,
                     "killed_mid_job": killed_mid_job,
                     "rereplication_wait_s": round(rerepl_wait, 2)},
            errors=blocks - cached, duration_s=wall)
