"""BASELINE config #3: distributed prefetch (DistributedLoad) GB/s.

Reference analogue: the job-service DistributedLoad path
(``job/server/src/main/java/alluxio/job/plan/load/LoadDefinition.java:65``)
— files persisted in the UFS but not cached are fanned out across N
workers' caches by load-plan tasks; the metric is aggregate prefetch
GB/s from job submission to every block landing in a worker tier.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from alluxio_tpu.stress.base import BenchResult
from alluxio_tpu.stress.cluster import bench_cluster


def run(*, master: Optional[str] = None, num_workers: int = 4,
        num_files: int = 8, file_bytes: int = 16 << 20,
        replication: int = 1, block_size: int = 4 << 20,
        base_path: str = "/stress-prefetch",
        pressure: bool = False, kill_worker: bool = False,
        rereplicate_timeout_s: float = 240.0) -> BenchResult:
    """``pressure=True`` sizes worker tiers so eviction must fire
    mid-load (tiers are pre-filled with MUST_CACHE filler the load then
    evicts). ``kill_worker=True`` stops one worker (block + job) while
    the load job runs; the plan must still COMPLETE (task failover) and
    the replication checker must restore the killed worker's copies —
    the failure envelope ``LoadDefinition.java:65``-style fan-out exists
    to survive."""
    from alluxio_tpu.client.streams import WriteType

    if master:
        raise NotImplementedError(
            "prefetch bench provisions its own multi-worker cluster")
    from alluxio_tpu.conf import Keys

    rng = np.random.default_rng(0)
    total = num_files * file_bytes
    per_worker_corpus = -(-total * max(replication, 1) // num_workers)
    mem = (per_worker_corpus + 2 * block_size + (8 << 20)) if pressure \
        else total + (128 << 20)
    overrides = {Keys.WORKER_BLOCK_HEARTBEAT_INTERVAL: "50ms"}
    if pressure:
        # single tier: MEM eviction must DROP blocks, not cascade-demote
        # into the default 64MB SSD tier (which would absorb the whole
        # pressure corpus and prove nothing)
        overrides[Keys.WORKER_TIERED_STORE_LEVELS] = 1
    if kill_worker:
        # the master must notice the kill quickly: lost-worker
        # detection drops its block locations, which is what arms the
        # replication checker
        overrides[Keys.MASTER_WORKER_TIMEOUT] = "2s"
        overrides[Keys.JOB_MASTER_WORKER_TIMEOUT] = "2s"
    with bench_cluster(None, num_workers=num_workers,
                       block_size=block_size,
                       worker_mem_bytes=mem,
                       start_job_service=True,
                       start_worker_heartbeats=True,
                       conf_overrides=overrides) as (fs, cluster):
        # THROUGH: persisted to the UFS, cached nowhere — the cold corpus
        from alluxio_tpu.stress.cluster import write_cold_corpus

        payload = rng.integers(0, 255, size=file_bytes, dtype=np.uint8
                               ).tobytes()
        write_cold_corpus(fs, cluster.block_client(),
                          {f"{base_path}/f-{i:05d}": payload
                           for i in range(num_files)})
        filler_paths = []
        if pressure:
            # fill ~the whole cluster capacity so the load can only
            # proceed by EVICTING (MUST_CACHE filler; LRU/LRFU decides
            # what goes; the last writes may already evict earlier
            # filler — that's the point)
            filler_each = max(block_size, mem // 2 - (1 << 20))
            fill = rng.integers(0, 255, size=filler_each,
                                dtype=np.uint8).tobytes()
            for w in range(num_workers * 2):
                p = f"{base_path}-fill/f-{w}"
                try:
                    fs.write_all(p, fill,
                                 write_type=WriteType.MUST_CACHE)
                    filler_paths.append(p)
                except Exception:  # noqa: BLE001 tier genuinely full
                    break

        killed_mid_job = False
        filler_prekill: dict = {}
        if kill_worker:
            # snapshot filler residency BEFORE the job: the post-kill
            # eviction accounting compares against this to tell
            # "evicted by pressure" from "lost with the worker" (a
            # snapshot at kill time would miss blocks the job already
            # evicted and under-count)
            for p in filler_paths:
                for fbi in fs.fs_master.get_file_block_info_list(p):
                    hosts = {loc.address.tiered_identity.value("host")
                             for loc in fbi.block_info.locations}
                    filler_prekill[(p, fbi.block_info.block_id)] = hosts

        job_client = cluster.job_client()
        t0 = time.monotonic()
        job_id = job_client.run({"type": "load", "path": base_path,
                                 "replication": replication})
        killed_host = ""
        if kill_worker:
            # arm durable-replication recovery NOW (not at write time:
            # a replication_min on a still-cold corpus would have the
            # 0.1s-tick checker churn failing replicate jobs for the
            # whole cold-wait, and race the measured load)
            for i in range(num_files):
                fs.set_attribute(f"{base_path}/f-{i:05d}",
                                 replication_min=max(replication, 1))
            # gate the kill on the job being observed RUNNING with
            # unfinished tasks — a fixed sleep races a fast load and
            # the drill would pass without exercising failover. 20ms:
            # tasks take at least one 50ms worker heartbeat to be
            # pulled, and get_status serializes the task list.
            gate = time.monotonic() + 10.0
            while time.monotonic() < gate:
                ji = job_client.get_status(job_id)
                unfinished = [t for t in ji.tasks
                              if t.status not in ("COMPLETED", "FAILED",
                                                  "CANCELED")]
                if ji.status == "RUNNING" and unfinished:
                    killed_mid_job = True
                    break
                if ji.status != "RUNNING" and ji.status != "CREATED":
                    break  # job already finished: kill is post-job
                time.sleep(0.02)
            victim = cluster.workers[0]
            killed_host = victim.worker.address.tiered_identity.value(
                "host")
            victim.stop()
            cluster.job_workers[0].stop()
        info = job_client.wait_for_job(job_id, timeout_s=300.0)
        wall = time.monotonic() - t0
        if info.status != "COMPLETED":
            raise RuntimeError(
                f"load job {job_id} ended {info.status}: "
                f"{info.error_message}")

        def replication_counts():
            blocks = cached = 0
            for i in range(num_files):
                for fbi in fs.fs_master.get_file_block_info_list(
                        f"{base_path}/f-{i:05d}"):
                    blocks += 1
                    if len(fbi.block_info.locations) >= replication:
                        cached += 1
            return blocks, cached

        blocks, cached = replication_counts()
        rerepl_wait = 0.0
        if kill_worker:
            # the killed worker's copies must come back: lost-worker
            # detection drops its locations, the ReplicationChecker
            # re-issues replicate jobs until the target holds again
            t1 = time.monotonic()
            deadline = t1 + rereplicate_timeout_s
            while cached < blocks and time.monotonic() < deadline:
                time.sleep(0.25)
                blocks, cached = replication_counts()
            rerepl_wait = time.monotonic() - t1
            if cached < blocks:
                raise RuntimeError(
                    f"re-replication never converged: {cached}/{blocks} "
                    f"blocks at replication {replication} after "
                    f"{rereplicate_timeout_s:.0f}s")
        evicted_filler = 0
        if pressure:
            for p in filler_paths:
                dropped_by_live = False
                for fbi in fs.fs_master.get_file_block_info_list(p):
                    cur = {loc.address.tiered_identity.value("host")
                           for loc in fbi.block_info.locations}
                    pre = filler_prekill.get(
                        (p, fbi.block_info.block_id))
                    if pre is None:  # no kill: any miss is an eviction
                        if not cur:
                            dropped_by_live = True
                    elif (pre - {killed_host}) - cur:
                        # a host OTHER than the killed one dropped the
                        # block -> genuine pressure eviction, not loss
                        dropped_by_live = True
                if dropped_by_live:
                    evicted_filler += 1
            if not evicted_filler:
                raise RuntimeError(
                    "pressure drill never forced an eviction — tier "
                    "sizing is wrong, the drill proved nothing")
        moved = total * replication
        return BenchResult(
            bench="distributed-prefetch",
            params={"num_workers": num_workers, "num_files": num_files,
                    "file_bytes": file_bytes, "replication": replication,
                    "block_size": block_size, "pressure": pressure,
                    "worker_killed": kill_worker},
            metrics={"gb_per_s": round(moved / wall / 1e9, 3),
                     "mb_per_s": round(moved / wall / 1e6, 2),
                     "blocks": blocks, "blocks_at_replication": cached,
                     "evicted_filler_files": evicted_filler,
                     "killed_mid_job": killed_mid_job,
                     "rereplication_wait_s": round(rerepl_wait, 2)},
            errors=blocks - cached, duration_s=wall)


def run_clairvoyant(*, num_workers: int = 1, num_files: int = 4,
                    file_bytes: int = 8 << 20,
                    block_size: int = 1 << 20, epochs: int = 2,
                    seed: int = 42, lookahead_blocks: int = 16,
                    budget_bytes: int = 128 << 20,
                    hbm_fraction: float = 0.0,
                    heartbeat_ms: int = 10,
                    base_path: str = "/stress-clairvoyant") -> BenchResult:
    """Clairvoyant prefetch bench: a seeded multi-epoch DeviceBlockLoader
    run with the oracle -> scheduler -> agent loop live (heartbeat
    thread, no test ticking). Reports the subsystem's own trajectory
    metrics — prefetch hit-rate and p50/p99 block-ready lateness — plus
    consume throughput."""
    import os

    from alluxio_tpu.client.jax_io import DeviceBlockLoader
    from alluxio_tpu.conf import Keys
    from alluxio_tpu.metrics import metrics, reset_metrics
    from alluxio_tpu.minicluster import LocalCluster
    from alluxio_tpu.prefetch import PrefetchService
    from alluxio_tpu.stress.cluster import write_cold_corpus
    import tempfile

    # the report reads process-global counters AND timer percentiles;
    # percentiles cannot be delta'd, so a prior in-process run (or any
    # earlier bench) would contaminate p50/p99 — start from zero
    reset_metrics()
    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory(prefix="atpu-clairvoyant-") as base:
        with LocalCluster(
                os.path.join(base, "cluster"), num_workers=num_workers,
                block_size=block_size,
                worker_mem_bytes=num_files * file_bytes + (64 << 20),
                start_worker_heartbeats=True,
                conf_overrides={
                    Keys.WORKER_BLOCK_HEARTBEAT_INTERVAL: "50ms",
                    Keys.MASTER_WORKER_TIMEOUT: "10000min",
                }) as cluster:
            fs = cluster.file_system()
            corpus = {
                f"{base_path}/f-{i:03d}": rng.integers(
                    0, 255, size=file_bytes, dtype=np.uint8).tobytes()
                for i in range(num_files)}
            write_cold_corpus(fs, cluster.block_client(), corpus)
            paths = list(corpus)
            conf = cluster.conf.copy()
            conf.set(Keys.PREFETCH_ENABLED, True)
            conf.set(Keys.PREFETCH_LOOKAHEAD_BLOCKS, lookahead_blocks)
            conf.set(Keys.PREFETCH_BUDGET_BYTES, budget_bytes)
            conf.set(Keys.PREFETCH_HBM_FRACTION, hbm_fraction)
            conf.set(Keys.PREFETCH_HEARTBEAT_INTERVAL,
                     f"{heartbeat_ms}ms")
            svc = PrefetchService.from_conf(conf, fs, paths, seed=seed)
            loader = DeviceBlockLoader(
                fs, paths, prefetch_service=svc,
                hbm_bytes=(budget_bytes if hbm_fraction > 0 else 0))
            base_stats = svc.stats()
            try:
                svc.start()
                # warm-up gate: let the agent land the first window so
                # the measurement reflects steady state, not cold boot
                svc.wait_ready(min(lookahead_blocks, len(loader)),
                               timeout_s=60.0)
                consumed_bytes = 0
                wall = 0.0  # consume time only: the inter-epoch gate
                # below must not deflate the reported throughput
                for e in range(epochs):
                    t0 = time.monotonic()
                    for arr in loader.epoch():
                        consumed_bytes += int(arr.nbytes)
                    wall += time.monotonic() - t0
                    if e + 1 < epochs:
                        # inter-epoch gate: a real consumer spends step
                        # time between epochs; this bench otherwise
                        # re-reads instantly and races the replan tick
                        svc.wait_ready(min(lookahead_blocks,
                                           len(loader)), timeout_s=60.0)
            finally:
                stall = loader.stall_report()  # input doctor, pre-close
                loader.close()
                svc.close()
            stats = svc.stats()
            ready = metrics().timer("Client.PrefetchBlockReady")
            hits = stats["hits"] - base_stats["hits"]
            late = stats["late"] - base_stats["late"]
            misses = stats["misses"] - base_stats["misses"]
            consumed = hits + late + misses
            stall_metrics = {
                f"stall_{b}_s": v["wait_s"]
                for b, v in stall["buckets"].items()}
            stall_metrics["input_bound_fraction"] = \
                stall["input_bound_fraction"]
            stall_metrics["stall_verdict"] = stall["verdict"]
            return BenchResult(
                bench="clairvoyant-prefetch",
                params={"num_workers": num_workers,
                        "num_files": num_files, "file_bytes": file_bytes,
                        "block_size": block_size, "epochs": epochs,
                        "seed": seed, "lookahead_blocks": lookahead_blocks,
                        "budget_bytes": budget_bytes,
                        "hbm_fraction": hbm_fraction,
                        "heartbeat_ms": heartbeat_ms},
                metrics={"hit_rate": round(hits / consumed, 4)
                         if consumed else 0.0,
                         "hits": hits, "late": late, "misses": misses,
                         "late_arrivals": stats["late_arrivals"] -
                         base_stats["late_arrivals"],
                         "p50_block_ready_ms": round(
                             ready.percentile(50) * 1e3, 3),
                         "p99_block_ready_ms": round(
                             ready.percentile(99) * 1e3, 3),
                         "gb_per_s": round(
                             consumed_bytes / wall / 1e9, 3),
                         "blocks_per_epoch": len(loader),
                         **stall_metrics},
                errors=misses, duration_s=wall)
