"""BASELINE config #3: distributed prefetch (DistributedLoad) GB/s.

Reference analogue: the job-service DistributedLoad path
(``job/server/src/main/java/alluxio/job/plan/load/LoadDefinition.java:65``)
— files persisted in the UFS but not cached are fanned out across N
workers' caches by load-plan tasks; the metric is aggregate prefetch
GB/s from job submission to every block landing in a worker tier.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from alluxio_tpu.stress.base import BenchResult
from alluxio_tpu.stress.cluster import bench_cluster


def run(*, master: Optional[str] = None, num_workers: int = 4,
        num_files: int = 8, file_bytes: int = 16 << 20,
        replication: int = 1, block_size: int = 4 << 20,
        base_path: str = "/stress-prefetch") -> BenchResult:
    from alluxio_tpu.client.streams import WriteType

    if master:
        raise NotImplementedError(
            "prefetch bench provisions its own multi-worker cluster")
    from alluxio_tpu.conf import Keys

    rng = np.random.default_rng(0)
    total = num_files * file_bytes
    with bench_cluster(None, num_workers=num_workers,
                       block_size=block_size,
                       worker_mem_bytes=total + (128 << 20),
                       start_job_service=True,
                       start_worker_heartbeats=True,
                       conf_overrides={
                           Keys.WORKER_BLOCK_HEARTBEAT_INTERVAL: "50ms",
                       }) as (fs, cluster):
        # THROUGH: persisted to the UFS, cached nowhere — the cold corpus
        payload = rng.integers(0, 255, size=file_bytes, dtype=np.uint8
                               ).tobytes()
        for i in range(num_files):
            fs.write_all(f"{base_path}/f-{i:05d}", payload,
                         write_type=WriteType.THROUGH)
        # THROUGH frees the cached copy asynchronously (worker heartbeat
        # applies the Free command): wait until the corpus is truly cold
        deadline = time.monotonic() + 60.0
        bc = cluster.block_client()
        for i in range(num_files):
            for fbi in fs.fs_master.get_file_block_info_list(
                    f"{base_path}/f-{i:05d}"):
                while bc.get_block_info(fbi.block_info.block_id).locations:
                    if time.monotonic() > deadline:
                        raise RuntimeError("corpus never went cold")
                    time.sleep(0.02)
        job_client = cluster.job_client()
        t0 = time.monotonic()
        job_id = job_client.run({"type": "load", "path": base_path,
                                 "replication": replication})
        info = job_client.wait_for_job(job_id, timeout_s=300.0)
        wall = time.monotonic() - t0
        if info.status != "COMPLETED":
            raise RuntimeError(
                f"load job {job_id} ended {info.status}: "
                f"{info.error_message}")
        # verify every block is cached with the requested replication
        blocks = cached = 0
        for i in range(num_files):
            for fbi in fs.fs_master.get_file_block_info_list(
                    f"{base_path}/f-{i:05d}"):
                blocks += 1
                if len(fbi.block_info.locations) >= replication:
                    cached += 1
        moved = total * replication
        return BenchResult(
            bench="distributed-prefetch",
            params={"num_workers": num_workers, "num_files": num_files,
                    "file_bytes": file_bytes, "replication": replication,
                    "block_size": block_size},
            metrics={"gb_per_s": round(moved / wall / 1e9, 3),
                     "mb_per_s": round(moved / wall / 1e6, 2),
                     "blocks": blocks, "blocks_at_replication": cached},
            errors=blocks - cached, duration_s=wall)
