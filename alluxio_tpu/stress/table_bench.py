"""BASELINE config #4: Parquet column-projection read through the
table service (TPC-DS-style wide fact table).

Reference analogue: Presto projecting columns through the catalog +
caching data plane (``table/server/master/.../AlluxioCatalog.java:55``;
``LocalCacheFileInStream`` page reads). The bench writes a partitioned
Hive-layout Parquet table into the warm cache, attaches it as an ``fs``
under-database, and measures a k-of-N column projection via
``table.reader.read_partition_columns`` — reporting projection GB/s and
the byte selectivity vs a full scan.
"""

from __future__ import annotations

import io
import sys
import time
from typing import Optional

import numpy as np

from alluxio_tpu.stress.base import BenchResult
from alluxio_tpu.stress.cluster import bench_cluster

# store_sales-flavored wide schema: 20 numeric + 3 string columns
_N_NUM = 20
_PROJECT = ["ss_sold_date_sk", "ss_quantity", "ss_net_paid"]


def _make_parquet(rng: np.random.Generator, rows: int) -> bytes:
    import pyarrow as pa
    import pyarrow.parquet as pq

    cols = {}
    names = [f"ss_col_{i}" for i in range(_N_NUM - 3)] + _PROJECT
    for name in names:
        cols[name] = rng.integers(0, 1 << 30, size=rows, dtype=np.int64)
    for name in ("ss_item_desc", "ss_store_name", "ss_promo"):
        base = rng.integers(0, 26, size=rows, dtype=np.uint8) + 65
        cols[name] = [chr(b) * 24 for b in base]
    table = pa.table(cols)
    buf = io.BytesIO()
    pq.write_table(table, buf, compression="none", row_group_size=8192)
    return buf.getvalue()


def _pyarrow_missing() -> Optional[BenchResult]:
    """Skip row cleanly (errors=0) when the image has no pyarrow."""
    try:
        import pyarrow  # noqa: F401

        return None
    except Exception:  # noqa: BLE001 - any import failure means skip
        return BenchResult(
            bench="table-projection-pushdown",
            params={"skipped": "pyarrow unavailable"},
            metrics={"skipped": 1}, errors=0, duration_s=0.0)


def _attach(fs, cluster, master, base_path):
    if cluster is not None:
        table_master = cluster.master.table_master
        db = table_master.attach_database("fs", f"{base_path}/db")
        return table_master.get_table(db, "store_sales")
    from alluxio_tpu.rpc.table_service import TableMasterClient

    client = TableMasterClient(master)
    db = client.attach_database("fs", f"{base_path}/db")
    return client.get_table(db, "store_sales")


class _ModeledStream:
    """A ``FileInStream`` behind a modeled wire: every round trip costs
    one RTT plus bytes/bandwidth (the same modeled-sleep isolation the
    remote-read bench uses). Both read paths pay the identical tariff —
    the planned path just makes fewer, coalesced, pipelined trips."""

    def __init__(self, inner, rtt_s: float, bw: float) -> None:
        self._inner = inner
        self._rtt_s = rtt_s
        self._bw = bw

    def _charge(self, nbytes: int, trips: int = 1) -> None:
        time.sleep(trips * self._rtt_s + nbytes / self._bw)

    def read(self, n: int = -1) -> bytes:
        out = self._inner.read(n)
        self._charge(len(out))
        return out

    def pread(self, offset: int, n: int) -> bytes:
        out = self._inner.pread(offset, n)
        self._charge(len(out))
        return out

    def pread_ranges(self, ranges, *, route_stats=None):
        outs = self._inner.pread_ranges(ranges, route_stats=route_stats)
        # one modeled trip per coalesced range (conservative: the real
        # plane batches small ranges into single read_many RPCs)
        self._charge(sum(len(o) for o in outs), trips=max(1, len(outs)))
        return outs

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _ModeledFs:
    """FS proxy whose data streams ride :class:`_ModeledStream`."""

    def __init__(self, fs, rtt_s: float, bw: float) -> None:
        self._fs = fs
        self._rtt_s = rtt_s
        self._bw = bw

    def open_file(self, path, **kw):
        return _ModeledStream(self._fs.open_file(path, **kw),
                              self._rtt_s, self._bw)

    def __getattr__(self, name):
        return getattr(self._fs, name)


def run_pushdown(*, master: Optional[str] = None, partitions: int = 4,
                 rows_per_partition: int = 40_000, repeats: int = 3,
                 min_speedup: float = 2.0, rtt_ms: float = 2.0,
                 conn_mbps: float = 1000.0,
                 base_path: str = "/stress-table-pd") -> BenchResult:
    """Planned vs legacy projection over the same warm table behind a
    modeled wire (``rtt_ms`` per round trip + bytes over ``conn_mbps``,
    the remote-read bench's isolation technique): the same
    ``read_partition_columns`` call with ``atpu.user.table.pushdown
    .enabled`` toggled, gated on ``min_speedup`` and on the two results
    being byte-identical (``pa.Table.equals`` — content comparison)."""
    skip = _pyarrow_missing()
    if skip is not None:
        return skip
    from alluxio_tpu.client.streams import WriteType
    from alluxio_tpu.conf import Keys
    from alluxio_tpu.table.reader import read_partition_columns

    rng = np.random.default_rng(1)
    with bench_cluster(master, block_size=32 << 20,
                       worker_mem_bytes=1 << 30) as (fs, cluster):
        total_file_bytes = 0
        for p in range(partitions):
            data = _make_parquet(rng, rows_per_partition)
            total_file_bytes += len(data)
            fs.write_all(
                f"{base_path}/db/store_sales/ss_date={2020 + p}/"
                f"part-0.parquet",
                data, write_type=WriteType.MUST_CACHE)
        table_wire = _attach(fs, cluster, master, base_path)
        conf = fs.conf
        mfs = _ModeledFs(fs, rtt_ms / 1e3, conn_mbps * (1 << 20) / 8)

        def timed(enabled: bool):
            conf.set(Keys.USER_TABLE_PUSHDOWN_ENABLED, enabled)
            # warm pass: footer cache + worker-cache residency for this
            # path, excluded from timing for both sides
            out = read_partition_columns(mfs, table_wire,
                                         columns=_PROJECT)
            t0 = time.monotonic()
            for _ in range(repeats):
                out = read_partition_columns(mfs, table_wire,
                                             columns=_PROJECT)
            return out, (time.monotonic() - t0) / repeats

        legacy, legacy_wall = timed(False)
        planned, planned_wall = timed(True)
        conf.set(Keys.USER_TABLE_PUSHDOWN_ENABLED, True)

        identical = planned.equals(legacy)
        speedup = legacy_wall / planned_wall if planned_wall else 0.0
        ok = identical and speedup >= min_speedup
        if not ok:
            print(f"table-projection-pushdown FAILED gate: "
                  f"identical={identical} speedup={speedup:.2f}x vs "
                  f"{min_speedup}x gate", file=sys.stderr)
        return BenchResult(
            bench="table-projection-pushdown",
            params={"partitions": partitions,
                    "rows_per_partition": rows_per_partition,
                    "columns_projected": len(_PROJECT),
                    "repeats": repeats, "min_speedup": min_speedup,
                    "rtt_ms": rtt_ms, "conn_mbps": conn_mbps,
                    "master": master or "in-process"},
            metrics={
                "legacy_ms": round(legacy_wall * 1e3, 2),
                "planned_ms": round(planned_wall * 1e3, 2),
                "speedup": round(speedup, 2),
                "byte_identical": int(identical),
                "projected_mb_per_s": round(
                    planned.nbytes / planned_wall / 1e6, 2)
                if planned_wall else 0.0,
                "file_bytes": total_file_bytes},
            errors=0 if ok else 1,
            duration_s=(legacy_wall + planned_wall) * repeats)


def run(*, master: Optional[str] = None, partitions: int = 4,
        rows_per_partition: int = 40_000, repeats: int = 3,
        min_speedup: float = 0.0,
        base_path: str = "/stress-table") -> BenchResult:
    from alluxio_tpu.client.streams import WriteType
    from alluxio_tpu.table.reader import read_partition_columns

    rng = np.random.default_rng(0)
    with bench_cluster(master, block_size=32 << 20,
                       worker_mem_bytes=1 << 30) as (fs, cluster):
        total_file_bytes = 0
        for p in range(partitions):
            data = _make_parquet(rng, rows_per_partition)
            total_file_bytes += len(data)
            fs.write_all(
                f"{base_path}/db/store_sales/ss_date={2020 + p}/part-0.parquet",
                data, write_type=WriteType.MUST_CACHE)

        table_wire = _attach(fs, cluster, master, base_path)

        # warm the footers + projected column chunks
        proj = read_partition_columns(fs, table_wire, columns=_PROJECT)
        proj_bytes = proj.nbytes

        t0 = time.monotonic()
        for _ in range(repeats):
            proj = read_partition_columns(fs, table_wire, columns=_PROJECT)
        proj_wall = (time.monotonic() - t0) / repeats

        t0 = time.monotonic()
        full = read_partition_columns(fs, table_wire, columns=None)
        full_wall = time.monotonic() - t0
        rows = full.num_rows

        speedup = full_wall / proj_wall if proj_wall else 0.0
        ok = rows == partitions * rows_per_partition and \
            speedup >= min_speedup
        if not ok:
            print(f"table-column-projection FAILED gate: rows={rows} "
                  f"projection_speedup={speedup:.2f}x vs "
                  f"{min_speedup}x gate", file=sys.stderr)
        return BenchResult(
            bench="table-column-projection",
            params={"partitions": partitions,
                    "rows_per_partition": rows_per_partition,
                    "columns_projected": len(_PROJECT),
                    "columns_total": len(table_wire["schema"]),
                    "min_speedup": min_speedup,
                    "master": master or "in-process"},
            metrics={
                "projection_mb_per_s": round(proj_bytes / proj_wall / 1e6, 2),
                "full_scan_mb_per_s": round(full.nbytes / full_wall / 1e6, 2),
                "projection_speedup": round(speedup, 2),
                "byte_selectivity": round(proj_bytes / full.nbytes, 4),
                "rows": rows, "file_bytes": total_file_bytes},
            errors=0 if ok else 1,
            duration_s=proj_wall * repeats + full_wall)
