"""BASELINE config #4: Parquet column-projection read through the
table service (TPC-DS-style wide fact table).

Reference analogue: Presto projecting columns through the catalog +
caching data plane (``table/server/master/.../AlluxioCatalog.java:55``;
``LocalCacheFileInStream`` page reads). The bench writes a partitioned
Hive-layout Parquet table into the warm cache, attaches it as an ``fs``
under-database, and measures a k-of-N column projection via
``table.reader.read_partition_columns`` — reporting projection GB/s and
the byte selectivity vs a full scan.
"""

from __future__ import annotations

import io
import time
from typing import Optional

import numpy as np

from alluxio_tpu.stress.base import BenchResult
from alluxio_tpu.stress.cluster import bench_cluster

# store_sales-flavored wide schema: 20 numeric + 3 string columns
_N_NUM = 20
_PROJECT = ["ss_sold_date_sk", "ss_quantity", "ss_net_paid"]


def _make_parquet(rng: np.random.Generator, rows: int) -> bytes:
    import pyarrow as pa
    import pyarrow.parquet as pq

    cols = {}
    names = [f"ss_col_{i}" for i in range(_N_NUM - 3)] + _PROJECT
    for name in names:
        cols[name] = rng.integers(0, 1 << 30, size=rows, dtype=np.int64)
    for name in ("ss_item_desc", "ss_store_name", "ss_promo"):
        base = rng.integers(0, 26, size=rows, dtype=np.uint8) + 65
        cols[name] = [chr(b) * 24 for b in base]
    table = pa.table(cols)
    buf = io.BytesIO()
    pq.write_table(table, buf, compression="none", row_group_size=8192)
    return buf.getvalue()


def run(*, master: Optional[str] = None, partitions: int = 4,
        rows_per_partition: int = 40_000, repeats: int = 3,
        base_path: str = "/stress-table") -> BenchResult:
    from alluxio_tpu.client.streams import WriteType
    from alluxio_tpu.table.reader import read_partition_columns

    rng = np.random.default_rng(0)
    with bench_cluster(master, block_size=32 << 20,
                       worker_mem_bytes=1 << 30) as (fs, cluster):
        total_file_bytes = 0
        for p in range(partitions):
            data = _make_parquet(rng, rows_per_partition)
            total_file_bytes += len(data)
            fs.write_all(
                f"{base_path}/db/store_sales/ss_date={2020 + p}/part-0.parquet",
                data, write_type=WriteType.MUST_CACHE)

        if cluster is not None:
            table_master = cluster.master.table_master
            db = table_master.attach_database("fs", f"{base_path}/db")
            table_wire = table_master.get_table(db, "store_sales")
        else:
            from alluxio_tpu.rpc.table_service import TableMasterClient

            client = TableMasterClient(master)
            db = client.attach_database("fs", f"{base_path}/db")
            table_wire = client.get_table(db, "store_sales")

        # warm the footers + projected column chunks
        proj = read_partition_columns(fs, table_wire, columns=_PROJECT)
        proj_bytes = proj.nbytes

        t0 = time.monotonic()
        for _ in range(repeats):
            proj = read_partition_columns(fs, table_wire, columns=_PROJECT)
        proj_wall = (time.monotonic() - t0) / repeats

        t0 = time.monotonic()
        full = read_partition_columns(fs, table_wire, columns=None)
        full_wall = time.monotonic() - t0
        rows = full.num_rows

        return BenchResult(
            bench="table-column-projection",
            params={"partitions": partitions,
                    "rows_per_partition": rows_per_partition,
                    "columns_projected": len(_PROJECT),
                    "columns_total": len(table_wire["schema"]),
                    "master": master or "in-process"},
            metrics={
                "projection_mb_per_s": round(proj_bytes / proj_wall / 1e6, 2),
                "full_scan_mb_per_s": round(full.nbytes / full_wall / 1e6, 2),
                "projection_speedup": round(full_wall / proj_wall, 2),
                "byte_selectivity": round(proj_bytes / full.nbytes, 4),
                "rows": rows, "file_bytes": total_file_bytes},
            errors=0 if rows == partitions * rows_per_partition else 1,
            duration_s=proj_wall * repeats + full_wall)
