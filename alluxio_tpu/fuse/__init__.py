"""FUSE adapter: mount the namespace as a local POSIX filesystem
(re-design of ``integration/fuse``; see ``process.py``)."""

from alluxio_tpu.fuse.fs import FuseFs  # noqa: F401

__all__ = ["FuseFs"]
