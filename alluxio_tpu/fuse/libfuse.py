"""ctypes binding to libfuse 2.9 (the high-level API, FUSE_USE_VERSION 26).

The image ships ``libfuse.so.2`` but no Python binding, so the adapter
binds the four calls it needs (``fuse_mount`` / ``fuse_new`` /
``fuse_loop`` / ``fuse_unmount`` + teardown) and the ``fuse_operations``
callback table directly. x86_64 Linux ABI only (struct stat layout).
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional

c_off_t = ctypes.c_longlong
c_mode_t = ctypes.c_uint
c_dev_t = ctypes.c_ulonglong
c_uid_t = ctypes.c_uint
c_gid_t = ctypes.c_uint


class Stat(ctypes.Structure):
    """``struct stat`` (x86_64 glibc layout)."""

    _fields_ = [
        ("st_dev", ctypes.c_ulong),
        ("st_ino", ctypes.c_ulong),
        ("st_nlink", ctypes.c_ulong),
        ("st_mode", ctypes.c_uint),
        ("st_uid", ctypes.c_uint),
        ("st_gid", ctypes.c_uint),
        ("__pad0", ctypes.c_uint),
        ("st_rdev", ctypes.c_ulong),
        ("st_size", ctypes.c_long),
        ("st_blksize", ctypes.c_long),
        ("st_blocks", ctypes.c_long),
        ("st_atime_sec", ctypes.c_long),
        ("st_atime_nsec", ctypes.c_long),
        ("st_mtime_sec", ctypes.c_long),
        ("st_mtime_nsec", ctypes.c_long),
        ("st_ctime_sec", ctypes.c_long),
        ("st_ctime_nsec", ctypes.c_long),
        ("__glibc_reserved", ctypes.c_long * 3),
    ]


class Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


class FuseFileInfo(ctypes.Structure):
    """``struct fuse_file_info`` (libfuse 2.9)."""

    _fields_ = [
        ("flags", ctypes.c_int),
        ("fh_old", ctypes.c_ulong),
        ("writepage", ctypes.c_int),
        ("bits", ctypes.c_uint),  # direct_io/keep_cache/... bitfield
        ("fh", ctypes.c_uint64),
        ("lock_owner", ctypes.c_uint64),
    ]


class FuseArgs(ctypes.Structure):
    _fields_ = [
        ("argc", ctypes.c_int),
        ("argv", ctypes.POINTER(ctypes.c_char_p)),
        ("allocated", ctypes.c_int),
    ]


class FuseContext(ctypes.Structure):
    _fields_ = [
        ("fuse", ctypes.c_void_p),
        ("uid", c_uid_t),
        ("gid", c_gid_t),
        ("pid", ctypes.c_int),
        ("private_data", ctypes.c_void_p),
        ("umask", c_mode_t),
    ]


# int (*fuse_fill_dir_t)(void *buf, const char *name,
#                        const struct stat *stbuf, off_t off)
fill_dir_t = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                              ctypes.c_char_p, ctypes.POINTER(Stat),
                              c_off_t)

_CB = ctypes.CFUNCTYPE
_p = ctypes.POINTER

getattr_t = _CB(ctypes.c_int, ctypes.c_char_p, _p(Stat))
readlink_t = _CB(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
                 ctypes.c_size_t)
mknod_t = _CB(ctypes.c_int, ctypes.c_char_p, c_mode_t, c_dev_t)
mkdir_t = _CB(ctypes.c_int, ctypes.c_char_p, c_mode_t)
path_t = _CB(ctypes.c_int, ctypes.c_char_p)
path2_t = _CB(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p)
chmod_t = _CB(ctypes.c_int, ctypes.c_char_p, c_mode_t)
chown_t = _CB(ctypes.c_int, ctypes.c_char_p, c_uid_t, c_gid_t)
truncate_t = _CB(ctypes.c_int, ctypes.c_char_p, c_off_t)
open_t = _CB(ctypes.c_int, ctypes.c_char_p, _p(FuseFileInfo))
read_t = _CB(ctypes.c_int, ctypes.c_char_p, _p(ctypes.c_char),
             ctypes.c_size_t, c_off_t, _p(FuseFileInfo))
write_t = _CB(ctypes.c_int, ctypes.c_char_p, _p(ctypes.c_char),
              ctypes.c_size_t, c_off_t, _p(FuseFileInfo))
readdir_t = _CB(ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
                fill_dir_t, c_off_t, _p(FuseFileInfo))
create_t = _CB(ctypes.c_int, ctypes.c_char_p, c_mode_t,
               _p(FuseFileInfo))
utimens_t = _CB(ctypes.c_int, ctypes.c_char_p, _p(Timespec))
access_t = _CB(ctypes.c_int, ctypes.c_char_p, ctypes.c_int)
fsync_t = _CB(ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
              _p(FuseFileInfo))


class FuseOperations(ctypes.Structure):
    """``struct fuse_operations`` field order for FUSE_USE_VERSION 26
    (libfuse 2.9 ``fuse.h``). Unimplemented slots stay NULL."""

    _fields_ = [
        ("getattr", getattr_t),
        ("readlink", readlink_t),
        ("getdir", ctypes.c_void_p),  # deprecated
        ("mknod", mknod_t),
        ("mkdir", mkdir_t),
        ("unlink", path_t),
        ("rmdir", path_t),
        ("symlink", path2_t),
        ("rename", path2_t),
        ("link", path2_t),
        ("chmod", chmod_t),
        ("chown", chown_t),
        ("truncate", truncate_t),
        ("utime", ctypes.c_void_p),  # superseded by utimens
        ("open", open_t),
        ("read", read_t),
        ("write", write_t),
        ("statfs", ctypes.c_void_p),
        ("flush", open_t),
        ("release", open_t),
        ("fsync", fsync_t),
        ("setxattr", ctypes.c_void_p),
        ("getxattr", ctypes.c_void_p),
        ("listxattr", ctypes.c_void_p),
        ("removexattr", ctypes.c_void_p),
        ("opendir", open_t),
        ("readdir", readdir_t),
        ("releasedir", open_t),
        ("fsyncdir", ctypes.c_void_p),
        ("init", ctypes.c_void_p),
        ("destroy", ctypes.c_void_p),
        ("access", access_t),
        ("create", create_t),
        ("ftruncate", ctypes.c_void_p),
        ("fgetattr", ctypes.c_void_p),
        ("lock", ctypes.c_void_p),
        ("utimens", utimens_t),
        ("bmap", ctypes.c_void_p),
        ("flags_", ctypes.c_uint),  # nullpath_ok/nopath/... bitfield
        ("ioctl", ctypes.c_void_p),
        ("poll", ctypes.c_void_p),
        ("write_buf", ctypes.c_void_p),
        ("read_buf", ctypes.c_void_p),
        ("flock", ctypes.c_void_p),
        ("fallocate", ctypes.c_void_p),
    ]


_lib: Optional[ctypes.CDLL] = None


def load() -> ctypes.CDLL:
    """Load and prototype libfuse.so.2; raises OSError when absent."""
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("fuse") or "libfuse.so.2"
    lib = ctypes.CDLL(name, use_errno=True)
    lib.fuse_mount.restype = ctypes.c_void_p  # struct fuse_chan *
    lib.fuse_mount.argtypes = [ctypes.c_char_p, _p(FuseArgs)]
    # fuse_new MUST be the versioned FUSE_2.6 symbol: the library also
    # exports an UNVERSIONED compat shim (first arg ``int fd``) that
    # plain dlsym prefers — it truncates the chan pointer to an fd and
    # every later channel read fails with EBADF
    libc = ctypes.CDLL(None, use_errno=True)
    libc.dlvsym.restype = ctypes.c_void_p
    libc.dlvsym.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_char_p]
    addr = libc.dlvsym(lib._handle, b"fuse_new", b"FUSE_2.6")
    if not addr:  # pragma: no cover - other libfuse2 builds
        addr = ctypes.cast(lib.fuse_new, ctypes.c_void_p).value
    lib.fuse_new_versioned = ctypes.CFUNCTYPE(
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        _p(FuseOperations), ctypes.c_size_t, ctypes.c_void_p)(addr)
    lib.fuse_loop.restype = ctypes.c_int
    lib.fuse_loop.argtypes = [ctypes.c_void_p]
    lib.fuse_exit.restype = None
    lib.fuse_exit.argtypes = [ctypes.c_void_p]
    lib.fuse_unmount.restype = None
    lib.fuse_unmount.argtypes = [ctypes.c_char_p, ctypes.c_void_p]
    lib.fuse_destroy.restype = None
    lib.fuse_destroy.argtypes = [ctypes.c_void_p]
    lib.fuse_get_context.restype = _p(FuseContext)
    lib.fuse_get_context.argtypes = []
    _lib = lib
    return lib


def make_args(options: str) -> FuseArgs:
    """Build ``struct fuse_args`` for ``-o <options>`` (keep a reference
    to the returned object alive for the duration of the mount)."""
    argv_list = [b"alluxio-tpu-fuse"]
    if options:
        argv_list += [b"-o", options.encode()]
    argv = (ctypes.c_char_p * (len(argv_list) + 1))(*argv_list, None)
    args = FuseArgs(len(argv_list), argv, 0)
    args._argv_keepalive = argv  # noqa: SLF001 - GC anchor
    return args
