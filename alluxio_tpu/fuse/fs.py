"""FUSE operation handlers over the native client.

Re-design of ``integration/fuse/src/main/java/alluxio/fuse/
AlluxioFuseFileSystem.java:52-55`` (jnr-fuse callbacks -> the master/
worker clients): the same operation semantics — sequential-only writes,
whole-file truncate, POSIX errno mapping — expressed as plain Python
methods so they are unit-testable without a kernel mount, then bridged
into ``fuse_operations`` by ``process.py``.

Returns follow the FUSE convention: >= 0 success (read/write return
byte counts), negative errno on failure.
"""

from __future__ import annotations

import errno
import logging
import stat as stat_mod
import threading
from typing import Dict, Optional, Tuple

from alluxio_tpu.utils.exceptions import (
    AlluxioTpuError, DirectoryNotEmptyError, FileAlreadyExistsError,
    FileDoesNotExistError, InvalidPathError, PermissionDeniedError,
)

LOG = logging.getLogger(__name__)

_ERRNO = (
    (FileDoesNotExistError, errno.ENOENT),
    (FileAlreadyExistsError, errno.EEXIST),
    (DirectoryNotEmptyError, errno.ENOTEMPTY),
    (PermissionDeniedError, errno.EACCES),
    (InvalidPathError, errno.EINVAL),
)


def _neg_errno(e: Exception) -> int:
    for exc_type, code in _ERRNO:
        if isinstance(e, exc_type):
            return -code
    if isinstance(e, AlluxioTpuError):
        return -errno.EIO
    return -errno.EIO


class _OpenFile:
    """One open handle: a read stream, a sequential write stream, or a
    deferred write (``lazy_path``: an existing file opened writable
    without O_TRUNC — content is preserved unless a write arrives)."""

    def __init__(self, reader=None, writer=None,
                 lazy_path: Optional[str] = None) -> None:
        self.reader = reader
        self.writer = writer
        self.lazy_path = lazy_path
        self.write_pos = 0
        self.lock = threading.Lock()


class FuseFs:
    """Callback logic (kernel-independent)."""

    def __init__(self, fs, root: str = "/") -> None:
        self._fs = fs
        self._root = root.rstrip("/")
        self._handles: Dict[int, _OpenFile] = {}
        self._next_fh = 1
        self._lock = threading.Lock()

    def _path(self, fuse_path: str) -> str:
        return (self._root + fuse_path).rstrip("/") or "/"

    # -- handle table --------------------------------------------------------
    def _add(self, of: _OpenFile) -> int:
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = of
            return fh

    def _get(self, fh: int) -> Optional[_OpenFile]:
        with self._lock:
            return self._handles.get(fh)

    # -- metadata ------------------------------------------------------------
    def getattr(self, path: str) -> "int | Tuple[int, int, int, int]":
        """(mode, size, mtime_ms, nlink) or -errno."""
        try:
            st = self._fs.get_status(self._path(path))
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)
        if st.folder:
            return (stat_mod.S_IFDIR | 0o755, 0,
                    st.last_modification_time_ms, 2)
        return (stat_mod.S_IFREG | 0o644, st.length,
                st.last_modification_time_ms, 1)

    def readdir(self, path: str):
        """List of names or -errno."""
        try:
            infos = self._fs.list_status(self._path(path))
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)
        return [i.name for i in infos]

    def mkdir(self, path: str) -> int:
        try:
            self._fs.create_directory(self._path(path))
            return 0
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)

    def unlink(self, path: str) -> int:
        try:
            self._fs.delete(self._path(path))
            return 0
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)

    def rmdir(self, path: str) -> int:
        try:
            self._fs.delete(self._path(path))
            return 0
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)

    def rename(self, src: str, dst: str) -> int:
        try:
            self._fs.rename(self._path(src), self._path(dst))
            return 0
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)

    def truncate(self, path: str, length: int) -> int:
        """Like the reference: truncate-to-0 = delete+recreate (the
        common ``open(O_TRUNC)`` path); anything else is unsupported
        (blocks are immutable once committed)."""
        full = self._path(path)
        try:
            st = self._fs.get_status(full)
        except FileDoesNotExistError:
            return -errno.ENOENT
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)
        if length == st.length:
            return 0
        if length == 0:
            try:
                self._fs.delete(full)
                self._fs.create_file(full).close()
                return 0
            except Exception as e:  # noqa: BLE001
                return _neg_errno(e)
        return -errno.EOPNOTSUPP

    # -- data ----------------------------------------------------------------
    def open(self, path: str, write: bool) -> int:
        """fh (>0) or -errno."""
        full = self._path(path)
        try:
            if write:
                try:
                    st = self._fs.get_status(full)
                except FileDoesNotExistError:
                    # O_CREAT on a fresh path (kernels with a create
                    # callback normally route here only for existing
                    # files, but be safe)
                    return self._add(_OpenFile(
                        writer=self._fs.create_file(full)))
                if st.folder:
                    return -errno.EISDIR
                # EXISTING file, no O_TRUNC (the kernel truncates via a
                # separate truncate() call): POSIX demands the content
                # survive until something actually writes — `touch` and
                # read-only r+ opens must not wipe the file
                return self._add(_OpenFile(
                    reader=self._fs.open_file(full, info=st),
                    lazy_path=full))
            st = self._fs.get_status(full)
            if st.folder:
                return -errno.EISDIR
            return self._add(_OpenFile(
                reader=self._fs.open_file(full, info=st)))
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)

    def create(self, path: str) -> int:
        try:
            return self._add(_OpenFile(
                writer=self._fs.create_file(self._path(path))))
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)

    def read(self, fh: int, size: int, offset: int) -> "int | bytes":
        of = self._get(fh)
        if of is None or of.reader is None:
            return -errno.EBADF
        try:
            with of.lock:
                return of.reader.pread(offset, size)
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)

    def write(self, fh: int, data: bytes, offset: int) -> int:
        """Sequential-only, like the reference FUSE adapter."""
        of = self._get(fh)
        if of is None:
            return -errno.EBADF
        if of.writer is None and of.lazy_path is not None:
            # first write through a deferred handle: a full rewrite
            # from offset 0 is the one pattern blocks support
            with of.lock:
                if of.writer is None:
                    if offset != 0:
                        return -errno.EOPNOTSUPP
                    try:
                        if of.reader is not None:
                            of.reader.close()
                            of.reader = None
                        of.writer = self._fs.create_file(
                            of.lazy_path, overwrite=True)
                    except Exception as e:  # noqa: BLE001
                        return _neg_errno(e)
        if of.writer is None:
            return -errno.EBADF
        with of.lock:
            if offset != of.write_pos:
                LOG.warning("non-sequential FUSE write at %d (expected "
                            "%d)", offset, of.write_pos)
                return -errno.EOPNOTSUPP
            try:
                of.writer.write(data)
            except Exception as e:  # noqa: BLE001
                return _neg_errno(e)
            of.write_pos += len(data)
            return len(data)

    def flush(self, fh: int) -> int:
        """Called at every fd close: COMMIT a write stream here so the
        application's ``close()`` returns with the file durably visible
        (FUSE ``release`` is async — committing there races readers;
        same choice as the reference's AlluxioFuseFileSystem)."""
        of = self._get(fh)
        if of is None:
            return 0
        with of.lock:
            if of.writer is not None:
                try:
                    of.writer.close()
                except Exception as e:  # noqa: BLE001
                    return _neg_errno(e)
                of.writer = None
        return 0

    def release(self, fh: int) -> int:
        with self._lock:
            of = self._handles.pop(fh, None)
        if of is None:
            return 0
        try:
            if of.writer is not None:
                of.writer.close()
            if of.reader is not None:
                of.reader.close()
            return 0
        except Exception as e:  # noqa: BLE001
            return _neg_errno(e)

    def close_all(self) -> None:
        with self._lock:
            handles, self._handles = dict(self._handles), {}
        for of in handles.values():
            try:
                if of.writer is not None:
                    of.writer.cancel()
                if of.reader is not None:
                    of.reader.close()
            except Exception:  # noqa: BLE001
                pass
