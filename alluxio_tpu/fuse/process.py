"""FUSE mount lifecycle: bridge ``FuseFs`` into ``fuse_operations`` and
drive the kernel loop.

Re-design of ``integration/fuse/src/main/java/alluxio/fuse/
{AlluxioFuse.java,AlluxioFuseFileSystem.java:52}``: ``AlluxioFuseMount``
mounts the namespace at a local path so ANY process (shell tools, numpy
``mmap``, torch ``DataLoader``) reads cached data through the kernel.

The loop runs on a daemon thread (libfuse single-threaded mode: every
callback re-enters Python under the GIL anyway, so ``fuse_loop_mt``
would only add contention); ``unmount()`` wakes it via
``fuse_unmount``.
"""

from __future__ import annotations

import ctypes
import errno
import logging
import os
import threading
import time
from typing import Optional

from alluxio_tpu.fuse import libfuse as lf
from alluxio_tpu.fuse.fs import FuseFs

LOG = logging.getLogger(__name__)


def fuse_available() -> bool:
    """True when the host can serve a mount (lib + device present)."""
    try:
        lf.load()
    except OSError:
        return False
    return os.path.exists("/dev/fuse")


class AlluxioFuseMount:
    """One kernel mount of the namespace."""

    def __init__(self, fs, mountpoint: str, *, root: str = "/",
                 options: str = "") -> None:
        self._ops_impl = FuseFs(fs, root)
        self.mountpoint = os.path.abspath(mountpoint)
        base = "fsname=alluxio-tpu,subtype=atpu,default_permissions"
        self._options = f"{base},{options}" if options else base
        self._chan: Optional[int] = None
        self._fuse: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._ops = self._build_ops()  # keepalive: kernel holds pointers

    # -- callback bridge -----------------------------------------------------
    def _build_ops(self) -> lf.FuseOperations:
        impl = self._ops_impl

        def _dec(p: bytes) -> str:
            return p.decode("utf-8", "surrogateescape")

        def c_getattr(path, stbuf):
            r = impl.getattr(_dec(path))
            if isinstance(r, int):
                return r
            mode, size, mtime_ms, nlink = r
            st = stbuf.contents
            ctypes.memset(ctypes.byref(st), 0, ctypes.sizeof(st))
            st.st_mode = mode
            st.st_nlink = nlink
            st.st_size = size
            st.st_uid = os.getuid()
            st.st_gid = os.getgid()
            st.st_blksize = 4096
            st.st_blocks = (size + 511) // 512
            sec, ms = divmod(mtime_ms, 1000)
            for pfx in ("st_atime", "st_mtime", "st_ctime"):
                setattr(st, pfx + "_sec", sec)
                setattr(st, pfx + "_nsec", ms * 1_000_000)
            return 0

        def c_readdir(path, buf, filler, _offset, _fi):
            r = impl.readdir(_dec(path))
            if isinstance(r, int):
                return r
            for name in [".", ".."] + r:
                # surrogateescape round-trips non-UTF-8 names that
                # _dec() admitted; strict encode would EIO the whole
                # directory listing over one bad name
                if filler(buf, name.encode("utf-8", "surrogateescape"),
                          None, 0):
                    break
            return 0

        def c_open(path, fi):
            flags = fi.contents.flags
            write = flags & (os.O_WRONLY | os.O_RDWR | os.O_APPEND)
            fh = impl.open(_dec(path), bool(write))
            if fh < 0:
                return fh
            fi.contents.fh = fh
            return 0

        def c_create(path, _mode, fi):
            fh = impl.create(_dec(path))
            if fh < 0:
                return fh
            fi.contents.fh = fh
            return 0

        def c_read(path, buf, size, offset, fi):
            data = impl.read(fi.contents.fh, size, offset)
            if isinstance(data, int):
                return data
            n = min(len(data), size)
            ctypes.memmove(buf, data, n)
            return n

        def c_write(path, buf, size, offset, fi):
            data = ctypes.string_at(buf, size)
            return impl.write(fi.contents.fh, data, offset)

        def c_release(path, fi):
            return impl.release(fi.contents.fh)

        def c_flush(path, fi):
            return impl.flush(fi.contents.fh)

        def c_truncate(path, length):
            return impl.truncate(_dec(path), length)

        def c_mkdir(path, _mode):
            return impl.mkdir(_dec(path))

        def c_unlink(path):
            return impl.unlink(_dec(path))

        def c_rmdir(path):
            return impl.rmdir(_dec(path))

        def c_rename(src, dst):
            return impl.rename(_dec(src), _dec(dst))

        def c_chmod(_path, _mode):
            return 0  # accepted, not persisted (matches reference default)

        def c_chown(_path, _uid, _gid):
            return 0

        def c_utimens(_path, _times):
            return 0

        def c_access(_path, _mask):
            return 0

        def c_fsync(_path, _datasync, _fi):
            return 0

        def guard(fn, name):
            def wrapped(*a):
                try:
                    return fn(*a)
                except Exception:  # noqa: BLE001 - never unwind into C
                    LOG.exception("fuse %s failed", name)
                    return -errno.EIO
            return wrapped

        ops = lf.FuseOperations()
        ops.getattr = lf.getattr_t(guard(c_getattr, "getattr"))
        ops.readdir = lf.readdir_t(guard(c_readdir, "readdir"))
        ops.open = lf.open_t(guard(c_open, "open"))
        ops.create = lf.create_t(guard(c_create, "create"))
        ops.read = lf.read_t(guard(c_read, "read"))
        ops.write = lf.write_t(guard(c_write, "write"))
        ops.release = lf.open_t(guard(c_release, "release"))
        ops.flush = lf.open_t(guard(c_flush, "flush"))
        ops.truncate = lf.truncate_t(guard(c_truncate, "truncate"))
        ops.mkdir = lf.mkdir_t(guard(c_mkdir, "mkdir"))
        ops.unlink = lf.path_t(guard(c_unlink, "unlink"))
        ops.rmdir = lf.path_t(guard(c_rmdir, "rmdir"))
        ops.rename = lf.path2_t(guard(c_rename, "rename"))
        ops.chmod = lf.chmod_t(guard(c_chmod, "chmod"))
        ops.chown = lf.chown_t(guard(c_chown, "chown"))
        ops.utimens = lf.utimens_t(guard(c_utimens, "utimens"))
        ops.access = lf.access_t(guard(c_access, "access"))
        ops.fsync = lf.fsync_t(guard(c_fsync, "fsync"))
        return ops

    # -- lifecycle -----------------------------------------------------------
    def mount(self, *, timeout_s: float = 10.0) -> None:
        lib = lf.load()
        os.makedirs(self.mountpoint, exist_ok=True)
        # mount options go to fuse_mount only; fuse_new takes NULL args
        # (it rejects fuse_mount's chewed remainder otherwise)
        mount_args = lf.make_args(self._options)
        self._args = mount_args  # keepalive
        mp = self.mountpoint.encode()
        chan = lib.fuse_mount(mp, ctypes.byref(mount_args))
        if not chan:
            raise OSError("fuse_mount failed (no permission for /dev/fuse"
                          " in this environment?)")
        fuse = lib.fuse_new_versioned(chan, None, ctypes.byref(self._ops),
                                      ctypes.sizeof(self._ops), None)
        if not fuse:
            lib.fuse_unmount(mp, chan)
            raise OSError("fuse_new failed")
        self._chan, self._fuse = chan, fuse
        self._thread = threading.Thread(
            target=lib.fuse_loop, args=(fuse,), name="fuse-loop",
            daemon=True)
        self._thread.start()
        # the mount is live once the kernel answers a stat of the root
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                st = os.stat(self.mountpoint)
                if os.path.ismount(self.mountpoint):
                    self._conn_dev = st.st_dev
                    LOG.info("fuse: %s mounted", self.mountpoint)
                    return
            except OSError:
                pass
            time.sleep(0.05)
        self.unmount()
        raise TimeoutError(f"mount of {self.mountpoint} did not come up")

    def _abort_connection(self) -> None:
        """Force-abort the kernel connection (sysfs knob) so in-flight
        and straggler requests — e.g. a FLUSH from an fd the caller
        leaked past unmount — fail with ENOTCONN instead of racing
        libfuse2's teardown (intermittent SIGSEGV otherwise)."""
        dev = getattr(self, "_conn_dev", None)
        if dev is None:
            return
        path = f"/sys/fs/fuse/connections/{dev}/abort"
        try:
            with open(path, "w") as f:
                f.write("1")
        except OSError:  # pragma: no cover - sysfs unavailable
            LOG.debug("fuse abort knob unavailable: %s", path)

    def unmount(self) -> None:
        lib = lf.load()
        if self._fuse is not None:
            lib.fuse_exit(self._fuse)
        if self._thread is not None:
            # the loop thread is blocked in fuse_chan_receive; freeing
            # the channel under it (fuse_unmount) is a use-after-free
            # (GPF in libfuse observed). Wake the read so the loop
            # observes the exit flag and returns FIRST. The poke must
            # be a LOOKUP of a name the kernel has never seen — a plain
            # stat of the root is served from the attribute cache and
            # wakes nothing.
            # poke from side threads: if the loop exited between pokes,
            # a stat against the reader-less connection blocks in
            # uninterruptible sleep — the later fuse_unmount aborts the
            # connection and frees any stuck poke thread.
            def _poke(n: int) -> None:
                try:
                    os.stat(os.path.join(
                        self.mountpoint, f".__wake_{n}__"))
                except OSError:
                    pass

            for attempt in range(100):
                threading.Thread(target=_poke, args=(attempt,),
                                 daemon=True).start()
                self._thread.join(timeout=0.1)
                if not self._thread.is_alive():
                    break
            else:  # pragma: no cover - wedged callback
                LOG.warning("fuse loop did not exit; forcing unmount")
            self._thread = None
        self._abort_connection()
        if self._chan is not None:
            lib.fuse_unmount(self.mountpoint.encode(), self._chan)
            self._chan = None
        if self._fuse is not None:
            lib.fuse_destroy(self._fuse)
            self._fuse = None
        self._ops_impl.close_all()

    def __enter__(self) -> "AlluxioFuseMount":
        self.mount()
        return self

    def __exit__(self, *exc) -> bool:
        self.unmount()
        return False
