"""YARN deploy integration: run an alluxio-tpu cluster as a YARN app.

Env-adapted analogue of the reference's ``integration/yarn`` module
(``Client.java:96``, ``ApplicationMaster.java``,
``ContainerAllocator.java:39``, ``CommandBuilder.java``): a submission
client speaking the ResourceManager REST API (stdlib-only, like every
other connector in this repo), a deterministic round-based container
allocator, and an application-master loop that launches this repo's
own master/worker processes inside the granted containers.

Departure from the reference, written down: the reference negotiates
containers through the asynchronous ``AMRMClientAsync`` protobuf
protocol; here allocation runs as synchronous request/offer rounds
against an injectable RM interface (`` RmProtocol``). The rounds are
semantically the same negotiation (per-host caps, release of excess
offers, bounded attempts) but deterministic — testable without a YARN
cluster, and driven over REST where a real one exists.
"""

from alluxio_tpu.yarn.allocator import (  # noqa: F401
    Container, ContainerAllocator, NotEnoughHostsError,
)
from alluxio_tpu.yarn.client import YarnRestClient  # noqa: F401
from alluxio_tpu.yarn.am import ApplicationMaster, ClusterSpec  # noqa: F401
