"""Round-based YARN container allocator with per-host caps.

Mirrors the negotiation contract of the reference's
``ContainerAllocator.java:39``: request the shortfall each round,
accept offers only while the per-host cap and the global target hold,
release every excess offer back to the RM, fail fast when the cluster
cannot possibly satisfy the request, and give up after a bounded
number of rounds (``MAX_WORKER_CONTAINER_REQUEST_ATTEMPTS = 20`` in
the reference, same default here).
"""

from __future__ import annotations

import logging
from collections import Counter
from dataclasses import dataclass
from typing import List, Optional, Protocol, Sequence

logger = logging.getLogger(__name__)

MAX_REQUEST_ATTEMPTS = 20

#: the reference's magic preferred-host value that relaxes locality
#: (``ContainerAllocator.java`` requestContainers: ``"any"``)
ANY_HOST = "any"


class NotEnoughHostsError(RuntimeError):
    """The cluster cannot satisfy the request even in principle
    (hosts x max_per_host < needed) — reference
    ``ExceptionMessage.YARN_NOT_ENOUGH_HOSTS``."""


class AllocationFailedError(RuntimeError):
    """Attempts exhausted before reaching the target count."""


@dataclass(frozen=True)
class Container:
    """A granted container (reference ``Container``: id + node host)."""

    container_id: str
    host: str


class RmProtocol(Protocol):
    """What the allocator needs from a ResourceManager.

    ``request_containers`` is one negotiation round: ask for ``count``
    containers constrained to ``hosts`` (empty = anywhere) and return
    the offers the RM made this round — possibly fewer, more, or on
    capped hosts; the allocator filters. ``release`` hands an excess
    offer back.
    """

    def node_hosts(self) -> Sequence[str]: ...

    def request_containers(self, count: int, hosts: Sequence[str],
                           relax_locality: bool, *,
                           memory_mb: int = 1024,
                           vcores: int = 1) -> Sequence[Container]: ...

    def release(self, container_id: str) -> None: ...


class ContainerAllocator:
    """Negotiate ``target`` containers, at most ``max_per_host`` on any
    single host; ``preferred_host`` pins every request to one host
    (``ANY_HOST`` keeps the pin but relaxes locality, as the reference
    does for masters that may float)."""

    def __init__(self, name: str, target: int, max_per_host: int,
                 rm: RmProtocol, preferred_host: Optional[str] = None,
                 max_attempts: int = MAX_REQUEST_ATTEMPTS,
                 memory_mb: int = 1024, vcores: int = 1) -> None:
        self._name = name
        self._target = target
        self._max_per_host = max_per_host
        self._rm = rm
        self._preferred_host = preferred_host
        self._max_attempts = max_attempts
        self._memory_mb = memory_mb
        self._vcores = vcores
        self._per_host: Counter = Counter()
        self._allocated: List[Container] = []

    # -- offer filtering (reference allocateContainer) ----------------
    def offer(self, container: Container) -> bool:
        """Accept or release one RM offer; returns True if kept."""
        if (self._per_host[container.host] < self._max_per_host
                and len(self._allocated) < self._target):
            self._per_host[container.host] += 1
            self._allocated.append(container)
            return True
        logger.info("releasing excess %s container on host %s",
                    self._name, container.host)
        self._rm.release(container.container_id)
        return False

    def _request_hosts(self) -> tuple:
        if self._preferred_host is not None:
            return ([self._preferred_host],
                    self._preferred_host == ANY_HOST)
        # hosts that still have per-host headroom
        hosts = [h for h in self._rm.node_hosts()
                 if self._per_host[h] < self._max_per_host]
        return hosts, True

    def allocate(self) -> List[Container]:
        for attempt in range(self._max_attempts):
            needed = self._target - len(self._allocated)
            if needed == 0:
                break
            hosts, relax = self._request_hosts()
            if self._preferred_host is None and \
                    len(hosts) * self._max_per_host < needed:
                raise NotEnoughHostsError(
                    f"need {needed} more {self._name} containers but "
                    f"only {len(hosts)} hosts have headroom at "
                    f"{self._max_per_host}/host")
            logger.debug("attempt %d: requesting %d %s containers on "
                         "%d hosts", attempt, needed, self._name,
                         len(hosts))
            for c in self._rm.request_containers(
                    needed, hosts, relax, memory_mb=self._memory_mb,
                    vcores=self._vcores):
                self.offer(c)
        if len(self._allocated) != self._target:
            raise AllocationFailedError(
                f"failed to allocate {self._target} {self._name} "
                f"containers after {self._max_attempts} attempts "
                f"(got {len(self._allocated)})")
        return list(self._allocated)

    @property
    def allocated(self) -> List[Container]:
        return list(self._allocated)
