"""YARN ResourceManager REST client (stdlib-only).

Env-adapted analogue of the reference's submission client
(``integration/yarn/.../Client.java:96``): where the reference drives
the protobuf ``YarnClient``, this speaks the RM's public REST API
(``/ws/v1/cluster``) — the same dialect discipline as the repo's other
hand-rolled connectors (WebHDFS, Swift, Glue). Covers the submission
lifecycle: new-application, submit with an AM launch command, state
polling, and kill.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

from alluxio_tpu.utils.httperr import error_body
from alluxio_tpu.yarn.allocator import Container

logger = logging.getLogger(__name__)

_TERMINAL = {"FINISHED", "FAILED", "KILLED"}


class YarnRestError(RuntimeError):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"RM REST error {status}: {body[:300]}")
        self.status = status


class YarnRestClient:
    """Talk to a ResourceManager at ``http://host:8088`` (default RM
    webapp port). Also exposes ``node_hosts``/``request_containers``/
    ``release`` so it can serve as the allocator's ``RmProtocol`` where
    the RM (or a gateway) offers container grants over REST."""

    def __init__(self, endpoint: str, timeout: float = 30.0) -> None:
        self._base = endpoint.rstrip("/")
        self._timeout = timeout

    # -- plumbing -----------------------------------------------------
    def _call(self, method: str, path: str,
              body: Optional[dict] = None) -> dict:
        url = f"{self._base}/ws/v1/cluster{path}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"}
            if data else {})
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                raw = r.read()
        except urllib.error.HTTPError as e:
            # RM errors carry full Java stack traces operators need —
            # keep parity with the pre-helper unlimited read
            raise YarnRestError(e.code,
                                error_body(e, limit=1 << 20)) from e
        return json.loads(raw) if raw.strip() else {}

    # -- submission lifecycle (Client.java run()) ---------------------
    def new_application(self) -> str:
        out = self._call("POST", "/apps/new-application")
        return out["application-id"]

    def submit(self, app_id: str, name: str, am_command: str, *,
               memory_mb: int = 1024, vcores: int = 1,
               queue: str = "default",
               env: Optional[Dict[str, str]] = None) -> None:
        ctx = {
            "application-id": app_id,
            "application-name": name,
            "application-type": "ALLUXIO-TPU",
            "queue": queue,
            "am-container-spec": {
                "commands": {"command": am_command},
                "environment": {
                    "entry": [{"key": k, "value": v}
                              for k, v in (env or {}).items()],
                },
            },
            "resource": {"memory": memory_mb, "vCores": vcores},
        }
        self._call("POST", "/apps", ctx)

    def state(self, app_id: str) -> str:
        return self._call("GET", f"/apps/{app_id}/state")["state"]

    def kill(self, app_id: str) -> None:
        self._call("PUT", f"/apps/{app_id}/state", {"state": "KILLED"})

    def wait_for_state(self, app_id: str, wanted: Sequence[str],
                       timeout: float = 300.0,
                       poll_s: float = 1.0) -> str:
        deadline = time.monotonic() + timeout
        state = self.state(app_id)
        while time.monotonic() < deadline:
            if state in wanted or state in _TERMINAL:
                return state
            time.sleep(poll_s)
            state = self.state(app_id)
        raise TimeoutError(
            f"app {app_id} still {state} after {timeout}s")

    # -- RmProtocol (allocation over REST) ----------------------------
    def node_hosts(self) -> List[str]:
        out = self._call("GET", "/nodes")
        nodes = (out.get("nodes") or {}).get("node") or []
        return [n["nodeHostName"] for n in nodes
                if n.get("state", "RUNNING") == "RUNNING"]

    def request_containers(self, count: int, hosts: Sequence[str],
                           relax_locality: bool, *,
                           memory_mb: int = 1024,
                           vcores: int = 1) -> List[Container]:
        out = self._call("POST", "/containers/request", {
            "count": count, "hosts": list(hosts),
            "relax-locality": relax_locality,
            "resource": {"memory": memory_mb, "vCores": vcores},
        })
        return [Container(c["container-id"], c["host"])
                for c in out.get("containers", [])]

    def release(self, container_id: str) -> None:
        self._call("POST", f"/containers/{container_id}/release")
