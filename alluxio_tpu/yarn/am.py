"""Application-master loop: turn granted containers into a cluster.

Env-adapted analogue of the reference's ``ApplicationMaster.java`` +
``CommandBuilder.java``: allocate one master container (optionally
pinned to a host), then the worker fleet with a per-host cap, build
each container's launch command around this repo's own process
entrypoints (``python -m alluxio_tpu.master.process`` etc. — the
reference launches ``alluxio-start.sh`` inside its containers), and
hand the commands to a ``ContainerLauncher``. The launcher seam is
injectable because real container launch goes through the
NodeManager; tests record commands instead.
"""

from __future__ import annotations

import logging
import shlex
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol

from alluxio_tpu.yarn.allocator import (
    ANY_HOST, Container, ContainerAllocator, RmProtocol,
)

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class ClusterSpec:
    """What to stand up (reference ``Client.java`` CLI options)."""

    num_workers: int
    master_host: Optional[str] = None     # None -> ANY_HOST semantics
    max_workers_per_host: int = 1
    master_mem_mb: int = 2048
    worker_mem_mb: int = 4096
    worker_ramdisk_mb: int = 2048
    conf: Dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class LaunchPlan:
    container: Container
    command: str
    env: Dict[str, str]


class ContainerLauncher(Protocol):
    """NodeManager seam: start ``plan.command`` inside the granted
    container. Real deployments shell out through the NM; tests inject
    a recorder."""

    def launch(self, plan: LaunchPlan) -> None: ...


def build_command(module: str, conf: Dict[str, str]) -> str:
    """CommandBuilder analogue: one shell-safe command line, config
    passed as ``ATPU_*`` env assignments so the container needs no
    config file (``conf/configuration.py`` env-var surface)."""
    pairs = [f"{_env_key(k)}={shlex.quote(v)}"
             for k, v in sorted(conf.items())]
    return " ".join(["env", *pairs, "python", "-m", module])


def _env_key(prop: str) -> str:
    # atpu.master.rpc.port -> ATPU_MASTER_RPC_PORT
    return prop.upper().replace(".", "_")


class SubprocessLauncher:
    """Launch plans as local child processes. This is the AM-side
    fallback when no NodeManager launch gateway is configured: every
    granted container resolves to this host (single-node YARN, or a
    gateway-less smoke deployment). Real multi-host launch goes
    through an NM gateway implementing ``ContainerLauncher``."""

    def __init__(self) -> None:
        import subprocess

        self._subprocess = subprocess
        self.procs: List = []

    def launch(self, plan: LaunchPlan) -> None:
        import os

        self.procs.append(self._subprocess.Popen(
            shlex.split(plan.command),
            env={**os.environ, **plan.env}))

    def wait(self) -> None:
        for p in self.procs:
            p.wait()


class ApplicationMaster:
    """Allocate master + workers, then emit launch plans."""

    def __init__(self, spec: ClusterSpec, rm: RmProtocol,
                 launcher: ContainerLauncher) -> None:
        self._spec = spec
        self._rm = rm
        self._launcher = launcher
        self.master_container: Optional[Container] = None
        self.worker_containers: List[Container] = []

    def run(self) -> List[LaunchPlan]:
        spec = self._spec
        master_alloc = ContainerAllocator(
            "master", 1, 1, self._rm,
            preferred_host=spec.master_host or ANY_HOST,
            memory_mb=spec.master_mem_mb)
        self.master_container = master_alloc.allocate()[0]
        worker_alloc = ContainerAllocator(
            "worker", spec.num_workers, spec.max_workers_per_host,
            self._rm, memory_mb=spec.worker_mem_mb)
        self.worker_containers = worker_alloc.allocate()

        master_host = self.master_container.host
        base_conf = dict(spec.conf)
        base_conf.setdefault("atpu.master.hostname", master_host)

        plans = [LaunchPlan(
            container=self.master_container,
            command=build_command("alluxio_tpu.master.process",
                                  base_conf),
            env={"ATPU_ROLE": "master"})]
        for c in self.worker_containers:
            wconf = dict(base_conf)
            # the worker's real ramdisk key takes a BYTES-typed value
            # (worker/process.py reads atpu.worker.ramdisk.size)
            wconf.setdefault("atpu.worker.ramdisk.size",
                             f"{spec.worker_ramdisk_mb}MB")
            plans.append(LaunchPlan(
                container=c,
                command=build_command("alluxio_tpu.worker.process",
                                      wconf),
                env={"ATPU_ROLE": "worker"}))
        for plan in plans:
            logger.info("launching %s on %s", plan.env["ATPU_ROLE"],
                        plan.container.host)
            self._launcher.launch(plan)
        return plans


def _main(argv=None) -> int:
    """``python -m alluxio_tpu.yarn.am`` — the in-container AM
    entrypoint the submission client's command line points at."""
    import argparse

    from alluxio_tpu.yarn.client import YarnRestClient

    ap = argparse.ArgumentParser(prog="alluxio-tpu-yarn-am")
    ap.add_argument("--rm", required=True)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--master-host", default=None)
    ap.add_argument("--max-workers-per-host", type=int, default=1)
    ap.add_argument("-C", "--conf", action="append", default=[],
                    metavar="key=value")
    args = ap.parse_args(argv)
    conf = dict(kv.split("=", 1) for kv in args.conf)
    spec = ClusterSpec(num_workers=args.workers,
                       master_host=args.master_host,
                       max_workers_per_host=args.max_workers_per_host,
                       conf=conf)
    launcher = SubprocessLauncher()
    ApplicationMaster(spec, YarnRestClient(args.rm), launcher).run()
    launcher.wait()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())

