"""``python -m alluxio_tpu.yarn`` — submit/status/kill an alluxio-tpu
cluster on YARN (reference ``integration/yarn/bin`` +
``Client.java:173`` main)."""

from __future__ import annotations

import argparse
import shlex
import sys

from alluxio_tpu.yarn.client import YarnRestClient


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="alluxio-tpu-yarn")
    ap.add_argument("--rm", required=True,
                    help="ResourceManager endpoint, e.g. "
                         "http://rm-host:8088")
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("submit", help="submit a cluster application")
    s.add_argument("--name", default="alluxio-tpu")
    s.add_argument("--workers", type=int, default=1)
    s.add_argument("--master-host", default=None)
    s.add_argument("--max-workers-per-host", type=int, default=1)
    s.add_argument("--am-memory-mb", type=int, default=1024)
    s.add_argument("--queue", default="default")
    s.add_argument("-C", "--conf", action="append", default=[],
                   metavar="key=value")
    for name in ("status", "kill"):
        p = sub.add_parser(name)
        p.add_argument("app_id")
    args = ap.parse_args(argv)

    cli = YarnRestClient(args.rm)
    if args.cmd == "status":
        print(cli.state(args.app_id))
        return 0
    if args.cmd == "kill":
        cli.kill(args.app_id)
        print(f"{args.app_id} kill requested")
        return 0

    am_cmd = ["python", "-m", "alluxio_tpu.yarn.am",
              "--rm", args.rm, "--workers", str(args.workers),
              "--max-workers-per-host",
              str(args.max_workers_per_host)]
    if args.master_host:
        am_cmd += ["--master-host", args.master_host]
    for kv in args.conf:
        am_cmd += ["-C", kv]
    app_id = cli.new_application()
    cli.submit(app_id, args.name,
               " ".join(shlex.quote(a) for a in am_cmd),
               memory_mb=args.am_memory_mb, queue=args.queue)
    print(app_id)
    return 0


if __name__ == "__main__":
    sys.exit(main())
