"""Job master: accepts jobs, plans them, tracks lifecycles, commands
job workers.

Re-design of ``job/server/src/main/java/alluxio/master/job/
{JobMaster.java:81,222,plan/PlanCoordinator.java:49,plan/PlanTracker.java,
workflow/WorkflowTracker.java}``: a capacity-bounded tracker holds plan
coordinators; job workers pull ``RunTask`` commands on heartbeat and push
task status updates back; workflows run children sequentially. Lost job
workers are detected by heartbeat silence and their tasks failed over
(reference: JobMaster's LostWorkerDetectionHeartbeatExecutor analogue).
"""

from __future__ import annotations

import collections
import itertools
import threading
from typing import Any, Deque, Dict, List, Optional

from alluxio_tpu.job.plan import (
    PlanRegistry, RegisteredJobWorker, SelectContext, default_registry,
)
from alluxio_tpu.job.wire import (
    JobCommand, JobInfo, JobWorkerHealth, Status, TaskInfo,
)
from alluxio_tpu.utils.clock import Clock, SystemClock
from alluxio_tpu.utils.exceptions import (
    JobDoesNotExistError, ResourceExhaustedError,
)


class _PlanCoordinator:
    """Drives one plan job: select executors -> dispatch tasks -> aggregate
    (reference: ``PlanCoordinator.java:49``)."""

    def __init__(self, job_id: int, config: Dict[str, Any], plan,
                 clock: Clock) -> None:
        self.job_id = job_id
        self.config = config
        self.plan = plan
        self._clock = clock
        self.info = JobInfo(job_id=job_id, name=plan.name,
                            status=Status.CREATED,
                            last_updated_ms=clock.millis())
        self.tasks: Dict[int, TaskInfo] = {}
        self._task_ids = itertools.count()
        #: task_id -> times re-dispatched after a worker loss
        self._retries: Dict[int, int] = {}
        #: parent workflow, notified on completion
        self.parent: Optional["_WorkflowCoordinator"] = None

    def start(self, workers: List[RegisteredJobWorker], ctx: SelectContext,
              dispatch) -> None:
        try:
            executors = self.plan.select_executors(self.config, workers, ctx)
        except Exception as e:  # noqa: BLE001 - planning error fails the job
            self._finish(Status.FAILED, error=f"{type(e).__name__}: {e}")
            return
        if not executors:
            # nothing to do (e.g. already loaded everywhere)
            self.info.result = self.plan.join(self.config, [])
            self._finish(Status.COMPLETED)
            return
        self.info.status = Status.RUNNING
        for worker_id, task_args in executors:
            tid = next(self._task_ids)
            task = TaskInfo(job_id=self.job_id, task_id=tid,
                            worker_id=worker_id, status=Status.CREATED,
                            args=task_args)
            self.tasks[tid] = task
            dispatch(worker_id, JobCommand(
                kind="run", job_id=self.job_id, task_id=tid,
                job_config=self.config, task_args=task_args))

    def on_task_update(self, task_id: int, status: str, result: Any,
                       error_message: str) -> None:
        task = self.tasks.get(task_id)
        if task is None or Status.is_finished(task.status):
            return
        task.status = status
        task.result = result
        task.error_message = error_message
        self.info.last_updated_ms = self._clock.millis()
        self._maybe_finish()

    def fail_tasks_of_worker(self, worker_id: int, reason: str) -> None:
        for task in self.tasks.values():
            if task.worker_id == worker_id and \
                    not Status.is_finished(task.status):
                task.status = Status.FAILED
                task.error_message = reason
        self._maybe_finish()

    MAX_TASK_RETRIES = 2

    def reassign_tasks_of_worker(self, worker_id: int,
                                 live_workers: List["RegisteredJobWorker"],
                                 dispatch) -> None:
        """Failover: a lost worker's unfinished tasks are re-dispatched
        round-robin onto live workers instead of failing the job.
        Departure from the reference (its ``PlanCoordinator`` fails the
        job and leaves retry to the DistributedLoad CLI's outer loop,
        ``LoadDefinition.java:65`` callers): a mid-load worker loss on a
        training pod must not restart the whole prefetch — the retry
        loop belongs in the framework. Per-task retries are capped; when
        no live worker remains the tasks fail as before.

        Targets are live workers with the FEWEST unfinished tasks of
        this job — a reassigned load task landing on a worker that
        already caches its blocks is a no-op, so spreading to
        uninvolved workers first preserves the most replication. When
        every live worker is involved (e.g. replication == cluster
        size) some copies are simply gone with the dead worker; the
        durable guarantee is ``replication_min`` + ReplicationChecker,
        not the one-shot job."""
        victims = [t for t in self.tasks.values()
                   if t.worker_id == worker_id
                   and not Status.is_finished(t.status)]
        if not victims:
            return
        if not live_workers:
            self.fail_tasks_of_worker(worker_id, "no live job workers "
                                      "left to fail over to")
            return
        if not getattr(self.plan, "relocatable", False):
            # host-affine tasks (evict and friends) must not run on a
            # different worker — they'd act on the wrong replica
            self.fail_tasks_of_worker(
                worker_id, f"job worker {worker_id} lost "
                f"({self.plan.name} tasks are host-affine)")
            return
        load = collections.Counter(
            t.worker_id for t in self.tasks.values()
            if not Status.is_finished(t.status))
        targets = sorted(live_workers,
                         key=lambda w: (load.get(w.worker_id, 0),
                                        w.worker_id))
        for i, task in enumerate(victims):
            retries = self._retries.get(task.task_id, 0)
            if retries >= self.MAX_TASK_RETRIES:
                task.status = Status.FAILED
                task.error_message = (
                    f"task retried {retries}x after worker losses")
                continue
            self._retries[task.task_id] = retries + 1
            new_wid = targets[i % len(targets)].worker_id
            task.worker_id = new_wid
            task.status = Status.CREATED
            dispatch(new_wid, JobCommand(
                kind="run", job_id=self.job_id, task_id=task.task_id,
                job_config=self.config, task_args=task.args))
        self.info.last_updated_ms = self._clock.millis()
        self._maybe_finish()

    def cancel(self) -> List[JobCommand]:
        cmds = []
        for task in self.tasks.values():
            if not Status.is_finished(task.status):
                task.status = Status.CANCELED
                cmds.append(JobCommand(kind="cancel", job_id=self.job_id,
                                       task_id=task.task_id))
        if not Status.is_finished(self.info.status):
            self._finish(Status.CANCELED)
        return cmds

    def _maybe_finish(self) -> None:
        statuses = [t.status for t in self.tasks.values()]
        if not all(Status.is_finished(s) for s in statuses):
            return
        if any(s == Status.FAILED for s in statuses):
            errs = "; ".join(t.error_message for t in self.tasks.values()
                             if t.status == Status.FAILED)
            self._finish(Status.FAILED, error=errs)
        elif any(s == Status.CANCELED for s in statuses):
            self._finish(Status.CANCELED)
        else:
            try:
                self.info.result = self.plan.join(
                    self.config,
                    [t.result for t in sorted(self.tasks.values(),
                                              key=lambda t: t.task_id)])
                self._finish(Status.COMPLETED)
            except Exception as e:  # noqa: BLE001
                self._finish(Status.FAILED,
                             error=f"join failed: {type(e).__name__}: {e}")

    def _finish(self, status: str, error: str = "") -> None:
        self.info.status = status
        self.info.error_message = error
        self.info.last_updated_ms = self._clock.millis()
        self.info.tasks = list(self.tasks.values())
        if self.parent is not None:
            self.parent.on_child_finished(self.job_id, status)


class _WorkflowCoordinator:
    """Sequential composite of child jobs (reference:
    ``job/workflow/composite/CompositeExecution.java`` +
    ``WorkflowTracker.java``)."""

    def __init__(self, job_id: int, config: Dict[str, Any], master,
                 clock: Clock) -> None:
        self.job_id = job_id
        self.config = config
        self._master = master
        self._clock = clock
        self._pending: Deque[Dict[str, Any]] = collections.deque(
            config.get("jobs", []))
        self.info = JobInfo(job_id=job_id, name="workflow",
                            status=Status.RUNNING,
                            last_updated_ms=clock.millis())

    def start(self) -> None:
        if not self._pending:
            self.info.status = Status.COMPLETED
            return
        self._launch_next()

    def _launch_next(self) -> None:
        child_cfg = self._pending.popleft()
        child_id = self._master._run_locked(child_cfg, parent=self)
        self.info.children.append(child_id)

    def on_child_finished(self, child_id: int, status: str) -> None:
        if status != Status.COMPLETED:
            self.info.status = status
            child = self._master._coordinators.get(child_id)
            self.info.error_message = (
                child.info.error_message if child is not None else
                f"child job {child_id} {status}")
            return
        if self._pending:
            self._launch_next()
        else:
            self.info.status = Status.COMPLETED
            self.info.last_updated_ms = self._clock.millis()

    def cancel(self) -> List[JobCommand]:
        cmds = []
        for cid in self.info.children:
            child = self._master._coordinators.get(cid)
            if child is not None and \
                    not Status.is_finished(child.info.status):
                cmds.extend(child.cancel())
        self._pending.clear()
        if not Status.is_finished(self.info.status):
            self.info.status = Status.CANCELED
        return cmds


class JobMaster:
    """The job-service control plane (reference: ``JobMaster.java:81``)."""

    def __init__(self, fs_master, block_master, *,
                 registry: Optional[PlanRegistry] = None,
                 capacity: int = 1024,
                 clock: Optional[Clock] = None,
                 worker_timeout_ms: int = 60_000) -> None:
        self._fs_master = fs_master
        self._block_master = block_master
        self._registry = registry or default_registry()
        self._capacity = capacity
        self._clock = clock or SystemClock()
        self._worker_timeout_ms = worker_timeout_ms
        self._lock = threading.RLock()
        self._job_ids = itertools.count(1)
        self._worker_ids = itertools.count(1)
        self._coordinators: Dict[int, Any] = {}  # job_id -> coordinator
        self._finished_fifo: Deque[int] = collections.deque()
        self._workers: Dict[int, RegisteredJobWorker] = {}
        self._last_contact_ms: Dict[int, int] = {}
        self._command_queues: Dict[int, Deque[JobCommand]] = {}

    # -- client API ---------------------------------------------------------
    def run(self, config: Dict[str, Any]) -> int:
        with self._lock:
            return self._run_locked(config)

    def _run_locked(self, config: Dict[str, Any],
                    parent=None) -> int:
        self._evict_finished()
        active = sum(1 for c in self._coordinators.values()
                     if not Status.is_finished(c.info.status))
        if active >= self._capacity:
            raise ResourceExhaustedError(
                f"job master at capacity ({self._capacity} active jobs)")
        job_id = next(self._job_ids)
        if config.get("type") == "workflow":
            wf = _WorkflowCoordinator(job_id, config, self, self._clock)
            self._coordinators[job_id] = wf
            wf.start()
            return job_id
        plan = self._registry.get(config.get("type", ""))
        coord = _PlanCoordinator(job_id, config, plan, self._clock)
        coord.parent = parent
        self._coordinators[job_id] = coord
        ctx = SelectContext(self._fs_master, self._block_master)
        coord.start(list(self._workers.values()), ctx, self._dispatch)
        return job_id

    def cancel(self, job_id: int) -> None:
        with self._lock:
            coord = self._require(job_id)
            for cmd in coord.cancel():
                q = self._command_queues.get(
                    self._task_worker(cmd.job_id, cmd.task_id))
                if q is not None:
                    q.append(cmd)

    def get_status(self, job_id: int) -> JobInfo:
        with self._lock:
            coord = self._require(job_id)
            info = coord.info
            if hasattr(coord, "tasks"):
                info.tasks = list(coord.tasks.values())
            return info

    def list_jobs(self) -> List[JobInfo]:
        with self._lock:
            return [c.info for c in self._coordinators.values()]

    def list_plan_types(self) -> List[str]:
        return self._registry.names()

    # -- worker protocol ----------------------------------------------------
    def register_worker(self, hostname: str) -> int:
        with self._lock:
            worker_id = next(self._worker_ids)
            self._workers[worker_id] = RegisteredJobWorker(
                worker_id=worker_id, hostname=hostname,
                health=JobWorkerHealth(worker_id=worker_id,
                                       hostname=hostname))
            self._command_queues[worker_id] = collections.deque()
            self._last_contact_ms[worker_id] = self._clock.millis()
            return worker_id

    def heartbeat(self, worker_id: int, health: Dict[str, Any],
                  task_updates: List[Dict[str, Any]]) -> List[dict]:
        with self._lock:
            if worker_id not in self._workers:
                # master lost this worker: tell it to re-register
                return [JobCommand(kind="register").to_wire()]
            self._last_contact_ms[worker_id] = self._clock.millis()
            if health:
                self._workers[worker_id].health = JobWorkerHealth.from_wire(
                    health)
            for upd in task_updates:
                coord = self._coordinators.get(upd["job_id"])
                if coord is not None and hasattr(coord, "on_task_update"):
                    coord.on_task_update(
                        upd["task_id"], upd["status"], upd.get("result"),
                        upd.get("error_message", ""))
            q = self._command_queues[worker_id]
            cmds = []
            while q:
                cmds.append(q.popleft().to_wire())
            return cmds

    def detect_lost_workers(self) -> None:
        """Expire silent job workers and fail over their running tasks
        (reference: job-worker liveness in ``JobMaster``)."""
        with self._lock:
            now = self._clock.millis()
            dead = [wid for wid, t in self._last_contact_ms.items()
                    if now - t > self._worker_timeout_ms]
            # drop EVERY dead worker first: a mass loss (rack partition)
            # must not reassign one dead worker's tasks onto the next
            # dead worker in the same pass, burning the retry cap
            for wid in dead:
                self._workers.pop(wid, None)
                self._last_contact_ms.pop(wid, None)
                self._command_queues.pop(wid, None)
            live = list(self._workers.values())
            for wid in dead:
                for coord in self._coordinators.values():
                    if hasattr(coord, "reassign_tasks_of_worker"):
                        coord.reassign_tasks_of_worker(
                            wid, live, self._dispatch)
                    elif hasattr(coord, "fail_tasks_of_worker"):
                        coord.fail_tasks_of_worker(
                            wid, f"job worker {wid} lost")

    def workers(self) -> List[RegisteredJobWorker]:
        with self._lock:
            return list(self._workers.values())

    # -- internals ----------------------------------------------------------
    def _dispatch(self, worker_id: int, cmd: JobCommand) -> None:
        q = self._command_queues.get(worker_id)
        if q is None:
            coord = self._coordinators.get(cmd.job_id)
            if coord is not None and hasattr(coord, "fail_tasks_of_worker"):
                coord.fail_tasks_of_worker(
                    worker_id, f"job worker {worker_id} not registered")
            return
        q.append(cmd)

    def _task_worker(self, job_id: int, task_id: int) -> int:
        coord = self._coordinators.get(job_id)
        if coord is None or not hasattr(coord, "tasks"):
            return -1
        task = coord.tasks.get(task_id)
        return task.worker_id if task is not None else -1

    def _require(self, job_id: int):
        coord = self._coordinators.get(job_id)
        if coord is None:
            raise JobDoesNotExistError(f"job {job_id} does not exist")
        return coord

    def _evict_finished(self) -> None:
        """FIFO-evict finished jobs beyond capacity (reference:
        ``PlanTracker``'s finished-job eviction)."""
        for jid, coord in self._coordinators.items():
            if Status.is_finished(coord.info.status) and \
                    jid not in self._finished_fifo:
                self._finished_fifo.append(jid)
        while len(self._finished_fifo) > self._capacity:
            jid = self._finished_fifo.popleft()
            self._coordinators.pop(jid, None)
