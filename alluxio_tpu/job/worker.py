"""Job worker: pulls task commands on heartbeat, runs them in a bounded
executor pool.

Re-design of ``job/server/src/main/java/alluxio/worker/{job/command/
CommandHandlingExecutor.java,job/task/{TaskExecutor.java:35,88,
TaskExecutorManager,PausableThreadPoolExecutor}.java,JobWorker.java}``:
register -> heartbeat (ship health + task updates, receive commands) ->
execute ``PlanDefinition.run_task`` with a locality-pinned FS client;
the pool supports pause/resume and a throttleable width.
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent import futures
from typing import Any, Dict, List, Optional

from alluxio_tpu.heartbeat import (
    HeartbeatContext, HeartbeatExecutor, HeartbeatThread,
)
from alluxio_tpu.job.plan import (
    PlanRegistry, RunTaskContext, default_registry,
)
from alluxio_tpu.job.wire import JobCommand, JobWorkerHealth, Status

LOG = logging.getLogger(__name__)


class TaskExecutorManager:
    """Bounded, pausable task pool (reference: ``TaskExecutorManager`` +
    ``PausableThreadPoolExecutor``)."""

    def __init__(self, width: int = 4) -> None:
        self.width = width
        self._pool = futures.ThreadPoolExecutor(
            max_workers=width, thread_name_prefix="job-task")
        self._unpaused = threading.Event()
        self._unpaused.set()
        self._active = 0
        self._lock = threading.Lock()

    def submit(self, fn, *args) -> "futures.Future":
        def gated():
            self._unpaused.wait()
            with self._lock:
                self._active += 1
            try:
                return fn(*args)
            finally:
                with self._lock:
                    self._active -= 1

        return self._pool.submit(gated)

    def pause(self) -> None:
        self._unpaused.clear()

    def resume(self) -> None:
        self._unpaused.set()

    @property
    def num_active(self) -> int:
        with self._lock:
            return self._active

    def shutdown(self) -> None:
        self._unpaused.set()
        self._pool.shutdown(wait=False, cancel_futures=True)


class JobWorker:
    """One job worker bound to a co-located block worker's locality host."""

    def __init__(self, job_master_client, file_system, hostname: str, *,
                 registry: Optional[PlanRegistry] = None,
                 task_pool_width: int = 4,
                 heartbeat_interval_s: float = 1.0) -> None:
        self._jm = job_master_client
        self._fs = file_system
        self.hostname = hostname
        self._registry = registry or default_registry()
        self._executor = TaskExecutorManager(task_pool_width)
        self._hb_interval = heartbeat_interval_s
        self.worker_id: Optional[int] = None
        self._lock = threading.Lock()
        self._pending_updates: List[Dict[str, Any]] = []
        self._running: Dict[tuple, futures.Future] = {}
        self._hb_thread: Optional[HeartbeatThread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.register()
        self._hb_thread = HeartbeatThread(
            HeartbeatContext.JOB_WORKER_COMMAND_HANDLING,
            _HbExec(self.heartbeat), self._hb_interval)
        self._hb_thread.start()

    def stop(self) -> None:
        if self._hb_thread is not None:
            self._hb_thread.stop()
        self._executor.shutdown()

    def register(self) -> None:
        self.worker_id = self._jm.register_worker(self.hostname)

    # -- heartbeat ----------------------------------------------------------
    def heartbeat(self) -> None:
        with self._lock:
            updates, self._pending_updates = self._pending_updates, []
        health = JobWorkerHealth(
            worker_id=self.worker_id or 0, hostname=self.hostname,
            load_avg=_load_avg(), task_pool_size=self._executor.width,
            num_active_tasks=self._executor.num_active,
            unfinished_tasks=len(self._running))
        try:
            commands = self._jm.heartbeat(self.worker_id, health.to_wire(),
                                          updates)
        except Exception:  # noqa: BLE001 - master may be failing over
            with self._lock:  # retry updates next tick
                self._pending_updates = updates + self._pending_updates
            LOG.debug("job heartbeat failed", exc_info=True)
            return
        for raw in commands:
            self._handle(JobCommand.from_wire(raw))

    def _handle(self, cmd: JobCommand) -> None:
        if cmd.kind == "run":
            self._run_task(cmd)
        elif cmd.kind == "cancel":
            fut = self._running.get((cmd.job_id, cmd.task_id))
            if fut is not None:
                fut.cancel()
        elif cmd.kind == "register":
            self.register()
        elif cmd.kind == "set_throttle":
            if cmd.task_args == "pause":
                self._executor.pause()
            else:
                self._executor.resume()

    # -- task execution -----------------------------------------------------
    def _run_task(self, cmd: JobCommand) -> None:
        key = (cmd.job_id, cmd.task_id)
        self._push_update(cmd.job_id, cmd.task_id, Status.RUNNING)

        def run():
            plan = self._registry.get(cmd.job_config.get("type", ""))
            ctx = RunTaskContext(self._fs, self.hostname)
            return plan.run_task(cmd.job_config, cmd.task_args, ctx)

        fut = self._executor.submit(run)
        self._running[key] = fut
        fut.add_done_callback(
            lambda f, jid=cmd.job_id, tid=cmd.task_id:
            self._on_task_done(jid, tid, f))

    def _on_task_done(self, job_id: int, task_id: int,
                      fut: "futures.Future") -> None:
        self._running.pop((job_id, task_id), None)
        if fut.cancelled():
            self._push_update(job_id, task_id, Status.CANCELED)
            return
        err = fut.exception()
        if err is not None:
            LOG.warning("task %s/%s failed: %s", job_id, task_id, err)
            self._push_update(job_id, task_id, Status.FAILED,
                              error=f"{type(err).__name__}: {err}")
        else:
            self._push_update(job_id, task_id, Status.COMPLETED,
                              result=fut.result())

    def _push_update(self, job_id: int, task_id: int, status: str, *,
                     result: Any = None, error: str = "") -> None:
        with self._lock:
            self._pending_updates.append({
                "job_id": job_id, "task_id": task_id, "status": status,
                "result": result, "error_message": error})


class _HbExec(HeartbeatExecutor):
    def __init__(self, fn) -> None:
        self._fn = fn

    def heartbeat(self) -> None:
        self._fn()


def _load_avg() -> float:
    try:
        return os.getloadavg()[0]
    except OSError:
        return 0.0
