"""Plan SPI: two-phase jobs (reference: ``job/server/src/main/java/alluxio/
job/plan/PlanDefinition.java`` + ``PlanDefinitionRegistry.java``).

``select_executors`` runs on the job master and partitions work over the
registered job workers; ``run_task`` runs on the chosen workers with an FS
client bound to the worker's locality (so reads cache into the co-located
block worker — the TPU-host-local tier).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from alluxio_tpu.job.wire import JobWorkerHealth
from alluxio_tpu.utils.exceptions import InvalidArgumentError
from alluxio_tpu.utils.wire import WorkerInfo


@dataclass
class RegisteredJobWorker:
    """Job-master view of one job worker."""

    worker_id: int
    hostname: str
    health: JobWorkerHealth


class SelectContext:
    """Master-side planning context: read-only cluster views."""

    def __init__(self, fs_master, block_master) -> None:
        self.fs_master = fs_master
        self.block_master = block_master

    def block_workers(self) -> List[WorkerInfo]:
        return self.block_master.get_worker_infos()

    def live_hosts(self) -> set:
        """Locality hosts that have a live block worker — load/replicate
        targets must be co-located with one."""
        return {w.address.tiered_identity.value("host")
                for w in self.block_workers()}


class RunTaskContext:
    """Worker-side execution context: a FileSystem client whose locality
    identity matches the co-located block worker, so LOCAL_FIRST policies
    target this host's tier."""

    def __init__(self, file_system, worker_hostname: str) -> None:
        self.fs = file_system
        self.hostname = worker_hostname


class PlanDefinition:
    #: registry key; job configs carry {"type": name, ...}
    name = ""
    #: True when a task's effect is the same on ANY worker (cache/copy/
    #: persist work), letting the coordinator re-dispatch a lost
    #: worker's tasks. Host-AFFINE tasks (evict: "remove MY copy") must
    #: stay False — run elsewhere they'd destroy a healthy replica.
    relocatable = False

    def select_executors(self, config: Dict[str, Any],
                         workers: List[RegisteredJobWorker],
                         ctx: SelectContext
                         ) -> List[Tuple[int, Any]]:
        """Return [(job_worker_id, task_args), ...]."""
        raise NotImplementedError

    def run_task(self, config: Dict[str, Any], task_args: Any,
                 ctx: RunTaskContext) -> Any:
        raise NotImplementedError

    def join(self, config: Dict[str, Any],
             task_results: List[Any]) -> Any:
        """Aggregate task results into the job result (reference:
        ``PlanDefinition.join``)."""
        return task_results


class PlanRegistry:
    """Name -> PlanDefinition (reference: ``PlanDefinitionRegistry`` uses
    ServiceLoader discovery; here plans self-register on import)."""

    def __init__(self) -> None:
        self._plans: Dict[str, PlanDefinition] = {}

    def register(self, plan: PlanDefinition) -> None:
        self._plans[plan.name] = plan

    def get(self, name: str) -> PlanDefinition:
        plan = self._plans.get(name)
        if plan is None:
            raise InvalidArgumentError(f"unknown job type: {name!r}; "
                                       f"known: {sorted(self._plans)}")
        return plan

    def names(self) -> List[str]:
        return sorted(self._plans)


_DEFAULT: Optional[PlanRegistry] = None


def default_registry() -> PlanRegistry:
    """The shared registry with all built-in plans loaded."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = PlanRegistry()
        from alluxio_tpu.job.plans import register_builtin_plans

        register_builtin_plans(_DEFAULT)
    return _DEFAULT
