"""Job master / job worker process assembly.

Re-design of ``job/server/src/main/java/alluxio/master/
AlluxioJobMasterProcess.java:58`` and ``worker/JobWorker.java``: the job
master is its own RPC endpoint (co-deployable with the metadata master),
job workers ride alongside block workers on each TPU host.
"""

from __future__ import annotations

from typing import Optional

from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.heartbeat import (
    HeartbeatContext, HeartbeatExecutor, HeartbeatThread,
)
from alluxio_tpu.job.master import JobMaster
from alluxio_tpu.job.worker import JobWorker
from alluxio_tpu.rpc.clients import BlockMasterClient, FsMasterClient
from alluxio_tpu.rpc.core import RpcServer
from alluxio_tpu.rpc.job_service import JobMasterClient, job_master_service


class _Exec(HeartbeatExecutor):
    def __init__(self, fn) -> None:
        self._fn = fn

    def heartbeat(self) -> None:
        self._fn()


class JobMasterProcess:
    def __init__(self, conf: Configuration, master_address: str, *,
                 clock=None) -> None:
        self._conf = conf
        self.job_master = JobMaster(
            FsMasterClient(master_address, conf=conf),
            BlockMasterClient(master_address, conf=conf),
            capacity=conf.get_int(Keys.JOB_MASTER_JOB_CAPACITY),
            clock=clock,
            worker_timeout_ms=conf.get_ms(Keys.JOB_MASTER_WORKER_TIMEOUT))
        self.rpc_server: Optional[RpcServer] = None
        self.rpc_port: Optional[int] = None
        self._threads = []

    def start(self) -> int:
        from alluxio_tpu.utils.tracing import set_tracing_enabled

        set_tracing_enabled(self._conf.get_bool(Keys.TRACE_ENABLED))
        self.rpc_server = RpcServer(
            bind_host="0.0.0.0",
            port=self._conf.get_int(Keys.JOB_MASTER_RPC_PORT))
        self.rpc_server.add_service(job_master_service(self.job_master))
        self.rpc_port = self.rpc_server.start()
        self._threads = [HeartbeatThread(
            HeartbeatContext.JOB_MASTER_LOST_WORKER_DETECTION,
            _Exec(self.job_master.detect_lost_workers),
            self._conf.get_duration_s(
                Keys.JOB_MASTER_LOST_WORKER_INTERVAL))]
        for t in self._threads:
            t.start()
        return self.rpc_port

    def stop(self) -> None:
        for t in self._threads:
            t.stop()
        if self.rpc_server is not None:
            self.rpc_server.stop()

    @property
    def address(self) -> str:
        return f"localhost:{self.rpc_port}"


def make_job_worker(conf: Configuration, job_master_address: str,
                    master_address: str, hostname: str) -> JobWorker:
    """Build a job worker whose FS client is locality-pinned to the
    co-located block worker's host."""
    from alluxio_tpu.client.file_system import FileSystem

    wconf = conf.copy()
    wconf.set(Keys.TIERED_IDENTITY, f"host={hostname}")
    fs = FileSystem(master_address, conf=wconf)
    return JobWorker(
        JobMasterClient(job_master_address), fs, hostname,
        task_pool_width=conf.get_int(Keys.JOB_WORKER_THREADPOOL_SIZE),
        heartbeat_interval_s=conf.get_duration_s(
            Keys.JOB_WORKER_HEARTBEAT_INTERVAL))
