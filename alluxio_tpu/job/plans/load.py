"""DistributedLoad: replicated cache prefetch — the north-star workload.

Re-design of ``job/server/src/main/java/alluxio/job/plan/load/
LoadDefinition.java:52,65,138``: ``select_executors`` picks, per block, up
to ``replication`` job workers whose co-located block worker does NOT hold
the block; ``run_task`` pulls each assigned block into the co-located
worker's tier via the worker's async-cache path and waits for the commit
to land in the block master (read-through caching, §3.5 of SURVEY.md).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Tuple

from alluxio_tpu.job.plan import (
    PlanDefinition, RegisteredJobWorker, RunTaskContext, SelectContext,
)
from alluxio_tpu.utils.exceptions import (
    InvalidArgumentError, UnavailableError,
)


def _expand_files(ctx: SelectContext, path: str, recursive: bool) -> List:
    info = ctx.fs_master.get_status(path)
    if not info.folder:
        return [info]
    return [i for i in ctx.fs_master.list_status(path, recursive=recursive)
            if not i.folder]


class LoadDefinition(PlanDefinition):
    name = "load"
    relocatable = True  # caching a block is valid on any worker

    def select_executors(self, config: Dict[str, Any],
                         workers: List[RegisteredJobWorker],
                         ctx: SelectContext) -> List[Tuple[int, Any]]:
        path = config.get("path")
        if not path:
            raise InvalidArgumentError("load job requires 'path'")
        replication = int(config.get("replication", 1))
        recursive = bool(config.get("recursive", True))
        if not workers:
            raise UnavailableError("no job workers registered")
        # job workers keyed by the co-located block worker's locality host;
        # a job worker whose block worker is dead cannot cache anything
        live = ctx.live_hosts()
        by_host: Dict[str, RegisteredJobWorker] = {
            w.hostname: w for w in workers if w.hostname in live}
        if not by_host:
            raise UnavailableError(
                "no job worker is co-located with a live block worker")
        assignments: Dict[int, List[dict]] = collections.defaultdict(list)
        # round-robin cursor so load spreads evenly when many hosts qualify
        cursor = 0
        for finfo in _expand_files(ctx, path, recursive):
            fbis = ctx.fs_master.get_file_block_info_list(finfo.path)
            for fbi in fbis:
                blk = fbi.block_info
                have = {loc.address.tiered_identity.value("host")
                        for loc in blk.locations}
                missing = [w for h, w in sorted(by_host.items())
                           if h not in have]
                if not missing:
                    continue
                need = max(0, replication - len(blk.locations))
                chosen = [missing[(cursor + i) % len(missing)]
                          for i in range(min(need, len(missing)))]
                cursor += 1
                for w in chosen:
                    assignments[w.worker_id].append({
                        "path": finfo.path,
                        "block_id": blk.block_id,
                        "offset": fbi.offset,
                        "length": blk.length,
                        "ufs_path": finfo.ufs_path,
                        "mount_id": finfo.mount_id,
                        "persisted": finfo.persisted,
                    })
        return [(wid, blocks) for wid, blocks in assignments.items()]

    def run_task(self, config: Dict[str, Any], task_args: Any,
                 ctx: RunTaskContext) -> Any:
        """Cache every assigned block into the co-located block worker."""
        store = ctx.fs.store
        local = None
        # include_quarantined: co-location lookup wants the LIVE set,
        # not the placement view — a quarantined local worker is still
        # alive and must still be findable (e.g. to evict from it)
        for w in ctx.fs.block_master.get_worker_infos(
                include_quarantined=True):
            if w.address.tiered_identity.value("host") == ctx.hostname:
                local = w
                break
        if local is None:
            raise UnavailableError(
                f"no block worker co-located with job worker {ctx.hostname}")
        client = store.worker_client(local.address)
        loaded = []
        for blk in task_args:
            if blk.get("persisted") and blk.get("ufs_path"):
                client.async_cache(blk["block_id"], blk["ufs_path"],
                                   blk["offset"], blk["length"],
                                   blk.get("mount_id", 0))
                self._await_commit(ctx.fs.block_master, blk["block_id"],
                                   ctx.hostname)
            else:
                # block only exists in other workers' cache: remote-read it
                # through the local worker (worker-to-worker replication)
                self._replicate_from_peer(ctx, client, blk)
            loaded.append(blk["block_id"])
        return {"loaded_blocks": loaded}

    @staticmethod
    def _await_commit(block_master, block_id: int, hostname: str,
                      timeout_s: float = 60.0) -> None:
        deadline = time.monotonic() + timeout_s
        sleep_s = 0.02
        next_live_check = time.monotonic() + 1.0
        absent_checks = 0
        while time.monotonic() < deadline:
            info = block_master.get_block_info(block_id)
            if any(loc.address.tiered_identity.value("host") == hostname
                   for loc in info.locations):
                return
            if time.monotonic() >= next_live_check:
                # fail FAST when the target worker has left the live
                # set (killed mid-task): burning the full timeout in a
                # 20ms poll loop clogs the executor pool and starves
                # the re-replication the failure is supposed to
                # trigger. HYSTERESIS (3 consecutive absent checks,
                # ~3s): a task-raised error fails the whole plan, so a
                # transient lost-marking (GC pause under a short
                # worker timeout) must get the chance to re-register —
                # only a persistently-absent worker aborts the wait.
                next_live_check = time.monotonic() + 1.0
                # LIVE set incl. quarantined: a worker quarantined
                # mid-load is still registered and still committing —
                # it must not read as "left the cluster"
                live = {w.address.tiered_identity.value("host")
                        for w in block_master.get_worker_infos(
                            include_quarantined=True)}
                absent_checks = 0 if hostname in live \
                    else absent_checks + 1
                if absent_checks >= 3:
                    raise UnavailableError(
                        f"target worker {hostname} left the live set "
                        f"while waiting for block {block_id}")
            time.sleep(sleep_s)
            sleep_s = min(sleep_s * 1.5, 0.25)  # adaptive backoff
        raise UnavailableError(
            f"block {block_id} did not land on {hostname} "
            f"within {timeout_s}s")

    @staticmethod
    def _replicate_from_peer(ctx: RunTaskContext, local_client,
                             blk: dict) -> None:
        info = ctx.fs.block_master.get_block_info(blk["block_id"])
        if not info.locations:
            raise UnavailableError(
                f"block {blk['block_id']} has no cached copy and no "
                "persisted UFS source")
        src = info.locations[0].address
        data = ctx.fs.store.worker_client(src).read_block_bytes(
            blk["block_id"])
        session_id = ctx.fs.store.session_id
        local_client.write_block(blk["block_id"], session_id, data)

    def join(self, config: Dict[str, Any],
             task_results: List[Any]) -> Any:
        blocks = sorted({b for r in task_results
                         for b in (r or {}).get("loaded_blocks", [])})
        return {"loaded_blocks": blocks, "num_blocks": len(blocks)}
