"""Async persist: write a cached file back to its UFS.

Re-design of ``job/server/src/main/java/alluxio/job/plan/persist/
PersistDefinition.java``: one task on a worker holding (most of) the file's
blocks; the task drives the worker-side ``persist_file`` (worker streams
blocks to the UFS and returns the fingerprint), then marks the inode
persisted on the master.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Tuple

from alluxio_tpu.job.plan import (
    PlanDefinition, RegisteredJobWorker, RunTaskContext, SelectContext,
)
from alluxio_tpu.utils.exceptions import (
    InvalidArgumentError, UnavailableError,
)


class PersistDefinition(PlanDefinition):
    name = "persist"
    relocatable = True  # any worker can write the UFS copy

    def select_executors(self, config: Dict[str, Any],
                         workers: List[RegisteredJobWorker],
                         ctx: SelectContext) -> List[Tuple[int, Any]]:
        path = config.get("path")
        if not path:
            raise InvalidArgumentError("persist job requires 'path'")
        if not workers:
            raise UnavailableError("no job workers registered")
        info = ctx.fs_master.get_status(path)
        # prefer the job worker co-located with the most cached blocks
        votes: Dict[str, int] = collections.Counter()
        for fbi in ctx.fs_master.get_file_block_info_list(path):
            for loc in fbi.block_info.locations:
                votes[loc.address.tiered_identity.value("host")] += 1
        by_host = {w.hostname: w for w in workers}
        best = None
        for host, _ in votes.most_common():
            if host in by_host:
                best = by_host[host]
                break
        if best is None:
            best = sorted(workers, key=lambda w: w.worker_id)[0]
        return [(best.worker_id, {"path": info.path,
                                  "inode_id": config.get("inode_id",
                                                         0)})]

    def run_task(self, config: Dict[str, Any], task_args: Any,
                 ctx: RunTaskContext) -> Any:
        path = task_args["path"]
        # id-pinned: a rename racing the job must FAIL it (the
        # scheduler re-resolves and retries at the new path), never
        # succeed against whatever file now sits at the old path
        ctx.fs.persist_now(path,
                           expected_id=task_args.get("inode_id", 0))
        return {"persisted": path}

    def join(self, config: Dict[str, Any],
             task_results: List[Any]) -> Any:
        return task_results[0] if task_results else {}
