"""Table transform (compact) job plan.

Re-design of ``job/server/src/main/java/alluxio/job/plan/transform/
{CompactDefinition,CompactTask}.java`` + ``format/parquet``: coalesce a
partition's many small Parquet files into ``num_files`` outputs so scan
jobs open fewer objects. One task per partition, assigned round-robin
over job workers; each task reads through the caching FS client (cold
data caches into the co-located worker) and writes the compacted files
back through the namespace.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from alluxio_tpu.job.plan import (
    PlanDefinition, RegisteredJobWorker, RunTaskContext, SelectContext,
)
from alluxio_tpu.utils.exceptions import (
    InvalidArgumentError, UnavailableError,
)


class TransformDefinition(PlanDefinition):
    name = "transform"

    def select_executors(self, config: Dict[str, Any],
                         workers: List[RegisteredJobWorker],
                         ctx: SelectContext) -> List[Tuple[int, Any]]:
        table = config.get("table_wire")
        if not table:
            raise InvalidArgumentError(
                "transform job requires 'table_wire'")
        if not workers:
            raise UnavailableError("no job workers registered")
        out_root = config["output_root"]
        assignments: List[Tuple[int, Any]] = []
        for i, part in enumerate(table["partitions"]):
            w = workers[i % len(workers)]
            out_dir = f"{out_root}/{part['spec']}" if part["spec"] \
                else out_root
            assignments.append((w.worker_id, [{
                "location": part["location"], "output_dir": out_dir}]))
        return assignments

    def run_task(self, config: Dict[str, Any], task_args: Any,
                 ctx: RunTaskContext) -> Any:
        import pyarrow as pa
        import pyarrow.parquet as pq

        from alluxio_tpu.table.reader import read_columns

        num_files = int(config.get("num_files", 1))
        write_type = config.get("write_type", "CACHE_THROUGH")
        compacted = []
        for item in task_args:
            loc, out_dir = item["location"], item["output_dir"]
            paths = [f"{loc}/{info.name}"
                     for info in ctx.fs.list_status(loc)
                     if not info.folder and info.name.endswith(".parquet")]
            if not paths:
                continue
            table = read_columns(ctx.fs, paths)
            if not ctx.fs.exists(out_dir):
                ctx.fs.create_directory(out_dir, recursive=True,
                                        allow_exists=True)
            rows_per = -(-table.num_rows // num_files)
            for i in range(num_files):
                chunk = table.slice(i * rows_per, rows_per)
                if chunk.num_rows == 0:
                    break
                sink = pa.BufferOutputStream()
                pq.write_table(chunk, sink)
                out_path = f"{out_dir}/part-{i:05d}.parquet"
                ctx.fs.write_all(out_path,
                                 sink.getvalue().to_pybytes(),
                                 write_type=write_type)
                compacted.append(out_path)
        return {"outputs": compacted}
