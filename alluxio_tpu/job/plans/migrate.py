"""DistributedCp/Mv: copy or move files across mounts/UFSes.

Re-design of ``job/server/src/main/java/alluxio/job/plan/migrate/
MigrateDefinition.java``: executors are picked per source file (hashed over
job workers); each task streams one file source -> destination through the
FS client, honoring ``overwrite`` and the write type; ``delete_source``
turns copy into move.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Tuple

from alluxio_tpu.job.plan import (
    PlanDefinition, RegisteredJobWorker, RunTaskContext, SelectContext,
)
from alluxio_tpu.utils.exceptions import (
    AlreadyExistsError, InvalidArgumentError, UnavailableError,
)
from alluxio_tpu.utils.uri import AlluxioURI


class MigrateDefinition(PlanDefinition):
    name = "migrate"
    relocatable = True  # copy/move work is worker-agnostic

    def select_executors(self, config: Dict[str, Any],
                         workers: List[RegisteredJobWorker],
                         ctx: SelectContext) -> List[Tuple[int, Any]]:
        src = config.get("source")
        dst = config.get("destination")
        if not src or not dst:
            raise InvalidArgumentError(
                "migrate job requires 'source' and 'destination'")
        if not workers:
            raise UnavailableError("no job workers registered")
        src_info = ctx.fs_master.get_status(src)
        pairs: List[Tuple[str, str]] = []
        if src_info.folder:
            base = AlluxioURI(src).path
            for info in ctx.fs_master.list_status(src, recursive=True):
                if info.folder:
                    continue
                rel = info.path[len(base):].lstrip("/")
                pairs.append((info.path, AlluxioURI(dst).join(rel).path))
        else:
            dst_path = dst
            try:
                dst_info = ctx.fs_master.get_status(dst)
                if dst_info.folder:
                    dst_path = AlluxioURI(dst).join(
                        AlluxioURI(src).name).path
            except Exception:  # noqa: BLE001 - dst may not exist yet
                pass
            pairs.append((src_info.path, dst_path))
        ordered = sorted(workers, key=lambda w: w.worker_id)
        assignments: Dict[int, List[dict]] = collections.defaultdict(list)
        for i, (s, d) in enumerate(pairs):
            w = ordered[i % len(ordered)]
            assignments[w.worker_id].append({"source": s, "destination": d})
        return [(wid, files) for wid, files in assignments.items()]

    def run_task(self, config: Dict[str, Any], task_args: Any,
                 ctx: RunTaskContext) -> Any:
        overwrite = bool(config.get("overwrite", False))
        write_type = config.get("write_type")
        delete_source = bool(config.get("delete_source", False))
        migrated = []
        for item in task_args:
            src, dst = item["source"], item["destination"]
            if ctx.fs.exists(dst):
                if not overwrite:
                    raise AlreadyExistsError(
                        f"{dst} exists and overwrite=False")
                ctx.fs.delete(dst)
            parent = AlluxioURI(dst).parent()
            if parent is not None and not ctx.fs.exists(parent.path):
                ctx.fs.create_directory(parent.path, recursive=True,
                                        allow_exists=True)
            with ctx.fs.open_file(src) as fin, \
                    ctx.fs.create_file(dst, write_type=write_type) as fout:
                while True:
                    chunk = fin.read(4 << 20)
                    if not chunk:
                        break
                    fout.write(chunk)
            if delete_source:
                ctx.fs.delete(src)
            migrated.append(dst)
        return {"migrated": migrated}

    def join(self, config: Dict[str, Any],
             task_results: List[Any]) -> Any:
        files = sorted({f for r in task_results
                        for f in (r or {}).get("migrated", [])})
        return {"migrated": files, "num_files": len(files)}
