"""Built-in plan definitions (reference: ``job/server/.../job/plan/{load,
migrate,persist,replicate}``)."""

from __future__ import annotations


def register_builtin_plans(registry) -> None:
    from alluxio_tpu.job.plans.load import LoadDefinition
    from alluxio_tpu.job.plans.migrate import MigrateDefinition
    from alluxio_tpu.job.plans.persist import PersistDefinition
    from alluxio_tpu.job.plans.replicate import (
        EvictDefinition, MoveDefinition, ReplicateDefinition,
    )
    from alluxio_tpu.job.plans.stressbench import StressBenchDefinition
    from alluxio_tpu.job.plans.transform import TransformDefinition

    for plan in (LoadDefinition(), MigrateDefinition(), PersistDefinition(),
                 ReplicateDefinition(), EvictDefinition(), MoveDefinition(),
                 TransformDefinition(), StressBenchDefinition()):
        registry.register(plan)
